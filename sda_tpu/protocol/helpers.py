"""Serde helpers: ids, binary blobs, canonical JSON, Signed/Labelled wrappers.

Wire compatibility targets the reference's serde conventions
(reference: protocol/src/helpers.rs, protocol/src/byte_arrays.rs):

- ids are hyphenated-UUID strings (helpers.rs:46-60);
- binary blobs are standard base64 with padding (helpers.rs:178-186);
- fixed-size byte arrays (B8/B32/B64) are base64 too (byte_arrays.rs:3-99);
- enums are externally tagged: unit variant -> ``"None"``, newtype variant ->
  ``{"Sodium": <value>}``, struct variant -> ``{"Full": {"modulus": 433}}``;
- signing operates over *canonical JSON* — compact separators, declared field
  order (helpers.rs:129-142: ``Sign::canonical`` is ``serde_json::to_vec``).
"""

from __future__ import annotations

import base64
import json
import uuid as _uuid
from typing import Any, Callable, Generic, Optional, Type, TypeVar


# ---------------------------------------------------------------------------
# Canonical JSON

def canonical_json(obj: Any) -> bytes:
    """Compact, declaration-ordered JSON bytes — the signing payload.

    Matches serde_json's default output (no whitespace, struct-field order,
    raw UTF-8), reference: protocol/src/helpers.rs:138-142.
    """
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False).encode("utf-8")


# ---------------------------------------------------------------------------
# Identifiers

class ResourceId:
    """UUID-valued unique identifier, serialized as a hyphenated string.

    Subclasses (AgentId, AggregationId, ...) exist purely for type clarity,
    mirroring the reference's ``uuid_id!`` macro (protocol/src/helpers.rs:19-86).
    """

    __slots__ = ("uuid",)

    def __init__(self, value: "str | _uuid.UUID | ResourceId | None" = None):
        if value is None:
            self.uuid = _uuid.uuid4()
        elif isinstance(value, _uuid.UUID):
            self.uuid = value
        elif isinstance(value, ResourceId):
            self.uuid = value.uuid
        else:
            try:
                self.uuid = _uuid.UUID(str(value))
            except ValueError:
                raise ValueError(f"unparseable uuid {value!r}")

    @classmethod
    def random(cls):
        return cls(_uuid.uuid4())

    def __str__(self) -> str:
        return str(self.uuid)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.uuid})"

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self.uuid == other.uuid

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.uuid))

    def __lt__(self, other: "ResourceId") -> bool:
        # UUID ordering = byte order, matching Rust's Uuid Ord (used by
        # suggest_committee sorting, reference: server/src/jfs_stores/agents.rs:66-72).
        return self.uuid.bytes < other.uuid.bytes

    def to_obj(self) -> str:
        return str(self.uuid)

    @classmethod
    def from_obj(cls, obj: str):
        return cls(obj)


# ---------------------------------------------------------------------------
# Binary blobs and fixed-size byte arrays

class Binary:
    """Arbitrary byte blob, base64 on the wire (helpers.rs:175-216)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        if not isinstance(data, (bytes, bytearray)):
            raise TypeError("Binary wraps bytes")
        self.data = bytes(data)

    def __eq__(self, other) -> bool:
        return isinstance(other, Binary) and self.data == other.data

    def __hash__(self) -> int:
        return hash(self.data)

    def __len__(self) -> int:
        return len(self.data)

    def __repr__(self) -> str:
        return f"Binary({len(self.data)} bytes)"

    def to_obj(self) -> str:
        return base64.b64encode(self.data).decode("ascii")

    @classmethod
    def from_obj(cls, obj: str) -> "Binary":
        try:
            return cls(base64.b64decode(obj, validate=True))
        except Exception as e:
            raise ValueError(f"Base64 decoding error: {e}")


class ByteArray:
    """Fixed-size byte array with base64 serde (byte_arrays.rs:3-99)."""

    SIZE = 0
    __slots__ = ("data",)

    def __init__(self, data: Optional[bytes] = None):
        if data is None:
            data = bytes(self.SIZE)
        data = bytes(data)
        if len(data) != self.SIZE:
            raise ValueError(f"{type(self).__name__} requires {self.SIZE} bytes, got {len(data)}")
        self.data = data

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self.data == other.data

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.data))

    def __repr__(self) -> str:
        return f"{type(self).__name__}(<{self.SIZE} bytes>)"

    def to_obj(self) -> str:
        return base64.b64encode(self.data).decode("ascii")

    @classmethod
    def from_obj(cls, obj: str):
        return cls(base64.b64decode(obj, validate=True))


class B8(ByteArray):
    SIZE = 8


class B32(ByteArray):
    SIZE = 32


class B64(ByteArray):
    SIZE = 64


# ---------------------------------------------------------------------------
# Externally-tagged enum helper

class TaggedEnum:
    """Base for serde externally-tagged enums with a single payload.

    Each subclass declares ``VARIANTS: {variant_name: payload_codec | None}``
    where ``payload_codec`` is a class with to_obj/from_obj, or ``None`` for a
    unit variant. An instance is (variant, value).
    """

    VARIANTS: dict = {}
    __slots__ = ("variant", "value")

    def __init__(self, variant: str, value: Any = None):
        if variant not in self.VARIANTS:
            raise ValueError(f"unknown variant {variant!r} for {type(self).__name__}")
        codec = self.VARIANTS[variant]
        if codec is None:
            if value is not None:
                raise ValueError(f"unit variant {variant} takes no value")
        elif not isinstance(value, codec):
            value = codec(value)
        self.variant = variant
        self.value = value

    def __eq__(self, other) -> bool:
        return (
            type(self) is type(other)
            and self.variant == other.variant
            and self.value == other.value
        )

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.variant, self.value))

    def __repr__(self) -> str:
        if self.value is None:
            return f"{type(self).__name__}.{self.variant}"
        return f"{type(self).__name__}.{self.variant}({self.value!r})"

    def to_obj(self):
        if self.VARIANTS[self.variant] is None:
            return self.variant
        return {self.variant: self.value.to_obj()}

    @classmethod
    def from_obj(cls, obj):
        if isinstance(obj, str):
            return cls(obj)
        if isinstance(obj, dict) and len(obj) == 1:
            [(variant, payload)] = obj.items()
            codec = cls.VARIANTS.get(variant)
            if codec is None:
                raise ValueError(f"variant {variant!r} of {cls.__name__} is not a newtype")
            return cls(variant, codec.from_obj(payload))
        raise ValueError(f"cannot decode {cls.__name__} from {obj!r}")


# ---------------------------------------------------------------------------
# Labelled and Signed wrappers

M = TypeVar("M")
ID = TypeVar("ID", bound=ResourceId)


class Labelled(Generic[ID, M]):
    """A message labelled by an identifier (helpers.rs:144-162)."""

    __slots__ = ("id", "body")

    def __init__(self, id: ID, body: M):
        self.id = id
        self.body = body

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Labelled)
            and self.id == other.id
            and self.body == other.body
        )

    def __repr__(self) -> str:
        return f"Labelled(id={self.id!r}, body={self.body!r})"

    def to_obj(self):
        return {"id": self.id.to_obj(), "body": self.body.to_obj()}

    @classmethod
    def from_obj(cls, obj, id_type: Type[ResourceId], body_type):
        return cls(id_type.from_obj(obj["id"]), body_type.from_obj(obj["body"]))

    def canonical(self) -> bytes:
        """Bytes that get signed (helpers.rs:129-142)."""
        return canonical_json(self.to_obj())


class Signed(Generic[M]):
    """A message with a detached signature and claimed signer (helpers.rs:99-127)."""

    __slots__ = ("signature", "signer", "body")

    def __init__(self, signature, signer, body):
        self.signature = signature
        self.signer = signer
        self.body = body

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Signed)
            and self.signature == other.signature
            and self.signer == other.signer
            and self.body == other.body
        )

    def __repr__(self) -> str:
        return f"Signed(signer={self.signer!r}, body={self.body!r})"

    @property
    def id(self):
        return self.body.id

    def to_obj(self):
        # Field order matters for canonical bytes: signature, signer, body
        # (declaration order in helpers.rs:101-107).
        return {
            "signature": self.signature.to_obj(),
            "signer": self.signer.to_obj(),
            "body": self.body.to_obj(),
        }

    @classmethod
    def from_obj(cls, obj, signature_type, signer_type, body_from_obj: Callable):
        return cls(
            signature_type.from_obj(obj["signature"]),
            signer_type.from_obj(obj["signer"]),
            body_from_obj(obj["body"]),
        )
