"""The SDA service seam — one interface, many transports.

Mirrors reference: protocol/src/methods.rs. The same interface is implemented
by the real server (``sda_tpu.server.SdaServerService``), by the HTTP proxy
(``sda_tpu.http.SdaHttpClient``), and consumed identically by the client —
so the whole distributed system can run in one process for tests, over REST
in production, or on a device mesh in simulated-pod mode (the key seam noted
in SURVEY.md §1).

Python note: the reference splits this across six Rust traits
(SdaBaseService/Agent/Aggregation/Participation/Clerking/Recipient,
methods.rs:13-112); here they are ABC mixins combined into ``SdaService``.
Absence of a resource is signalled by ``None`` returns; errors raise
``sda_tpu.protocol.errors.SdaError`` subclasses.
"""

from __future__ import annotations

import abc
from typing import List, Optional

from .resources import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    Participation,
    Profile,
    Snapshot,
    SnapshotId,
    SnapshotResult,
)
from .helpers import Signed


class Pong:
    """Return message of ``ping`` (methods.rs:6-10)."""

    __slots__ = ("running",)

    def __init__(self, running: bool):
        self.running = bool(running)

    def __eq__(self, other):
        return isinstance(other, Pong) and self.running == other.running

    def to_obj(self):
        return {"running": self.running}

    @classmethod
    def from_obj(cls, obj):
        return cls(obj["running"])


class SdaBaseService(abc.ABC):
    @abc.abstractmethod
    def ping(self) -> Pong:
        """Health check; raises if the service is not running correctly."""


class SdaAgentService(SdaBaseService):
    """Discovery and maintenance of agents and their identities (methods.rs:31-50)."""

    @abc.abstractmethod
    def create_agent(self, caller: Agent, agent: Agent) -> None: ...

    @abc.abstractmethod
    def get_agent(self, caller: Agent, agent: AgentId) -> Optional[Agent]: ...

    @abc.abstractmethod
    def upsert_profile(self, caller: Agent, profile: Profile) -> None: ...

    @abc.abstractmethod
    def get_profile(self, caller: Agent, owner: AgentId) -> Optional[Profile]: ...

    @abc.abstractmethod
    def create_encryption_key(self, caller: Agent, key: Signed) -> None: ...

    @abc.abstractmethod
    def get_encryption_key(self, caller: Agent, key: EncryptionKeyId) -> Optional[Signed]: ...


class SdaAggregationService(SdaBaseService):
    """Discovery of aggregation objects (methods.rs:53-64)."""

    @abc.abstractmethod
    def list_aggregations(
        self,
        caller: Agent,
        filter: Optional[str] = None,
        recipient: Optional[AgentId] = None,
    ) -> List[AggregationId]: ...

    @abc.abstractmethod
    def get_aggregation(self, caller: Agent, aggregation: AggregationId) -> Optional[Aggregation]: ...

    @abc.abstractmethod
    def get_committee(self, caller: Agent, aggregation: AggregationId) -> Optional[Committee]: ...


class SdaParticipationService(SdaBaseService):
    """Participation upload (methods.rs:68-73)."""

    @abc.abstractmethod
    def create_participation(self, caller: Agent, participation: Participation) -> None: ...


class SdaClerkingService(SdaBaseService):
    """Clerk job polling and result upload (methods.rs:76-84)."""

    @abc.abstractmethod
    def get_clerking_job(self, caller: Agent, clerk: AgentId) -> Optional[ClerkingJob]: ...

    @abc.abstractmethod
    def create_clerking_result(self, caller: Agent, result: ClerkingResult) -> None: ...


class SdaRecipientService(SdaBaseService):
    """Aggregation lifecycle operations reserved to the recipient (methods.rs:87-112)."""

    @abc.abstractmethod
    def create_aggregation(self, caller: Agent, aggregation: Aggregation) -> None: ...

    @abc.abstractmethod
    def delete_aggregation(self, caller: Agent, aggregation: AggregationId) -> None: ...

    @abc.abstractmethod
    def suggest_committee(self, caller: Agent, aggregation: AggregationId) -> List[ClerkCandidate]: ...

    @abc.abstractmethod
    def create_committee(self, caller: Agent, committee: Committee) -> None: ...

    @abc.abstractmethod
    def get_aggregation_status(
        self, caller: Agent, aggregation: AggregationId
    ) -> Optional[AggregationStatus]: ...

    @abc.abstractmethod
    def create_snapshot(self, caller: Agent, snapshot: Snapshot) -> None: ...

    @abc.abstractmethod
    def get_snapshot_result(
        self, caller: Agent, aggregation: AggregationId, snapshot: SnapshotId
    ) -> Optional[SnapshotResult]: ...

    def get_round_status(
        self, caller: Agent, aggregation: AggregationId
    ) -> Optional["RoundStatus"]:
        """Lifecycle state of the aggregation's current round
        (``server/lifecycle.py`` state machine), or ``None`` when this
        service does not track round lifecycle — deliberately concrete
        (not abstract) so pre-supervisor service implementations keep
        working unchanged."""
        return None


class SdaService(
    SdaAgentService,
    SdaAggregationService,
    SdaParticipationService,
    SdaClerkingService,
    SdaRecipientService,
):
    """The combined SDA service (methods.rs:13-22)."""
