"""Scheme parameters for the cryptographic primitives — schemes are *data*.

Mirrors the reference's scheme algebra (reference: protocol/src/crypto.rs):
ciphertext/key wrappers (:8-39), masking schemes (:43-75), secret-sharing
schemes with derived properties (:79-155), and additive encryption schemes
(:159-188). All scheme configuration travels in-band inside the Aggregation
resource, so adding a scheme never changes the wire protocol shape.
"""

from __future__ import annotations

from .helpers import B32, B64, Binary, TaggedEnum


# ---------------------------------------------------------------------------
# Ciphertexts, keys, signatures (crypto.rs:8-39)

class Encryption(TaggedEnum):
    """A ciphertext. ``Sodium`` = Curve25519+XSalsa20+Poly1305 sealed box;
    ``PackedPaillier`` = length-framed homomorphic ciphertext batch (the
    reference declares this variant but ships it disabled,
    crypto.rs:164-174)."""
    VARIANTS = {"Sodium": Binary, "PackedPaillier": Binary}

    @classmethod
    def sodium(cls, data: bytes) -> "Encryption":
        return cls("Sodium", Binary(data))


class EncryptionKey(TaggedEnum):
    """A public encryption key: 32-byte Curve25519, or a big-endian
    Paillier modulus n."""
    VARIANTS = {"Sodium": B32, "PackedPaillier": Binary}


class Signature(TaggedEnum):
    """A detached signature (64-byte Ed25519)."""
    VARIANTS = {"Sodium": B64}


class SigningKey(TaggedEnum):
    """A secret signing key (64-byte Ed25519 expanded key)."""
    VARIANTS = {"Sodium": B64}


class VerificationKey(TaggedEnum):
    """A public signature-verification key (32-byte Ed25519)."""
    VARIANTS = {"Sodium": B32}


# ---------------------------------------------------------------------------
# Masking schemes (crypto.rs:43-75)

#: ChaCha mask-PRG identifiers. The bare Rust wire shape (no "prg" key)
#: means the stream the reference actually draws — rand 0.3's ChaChaRng
#: (crypto.rs:53 documents the scheme as `rand::chacha::ChaChaRng`) — so a
#: scheme parsed from a Rust peer expands masks identically here and a
#: mixed round reveals the CORRECT aggregate. The TPU-native CHACHA_PRG_V1
#: spec is an explicit opt-in extension serialized as an extra "prg" key.
#: Unknown tags are rejected at parse time: an unrecognized stream must
#: fail loudly, never silently alias another one (that is the
#: wrong-aggregate hazard the tag exists to prevent). Literals duplicated
#: in fields.chacha (the spec home) to keep this wire layer import-light;
#: tests pin the two sets equal.
CHACHA_PRG_RAND03 = "rand-0.3/chacharng"
CHACHA_PRG_V1 = "sda-tpu/chacha20-prg/v1"
_CHACHA_PRGS = (CHACHA_PRG_RAND03, CHACHA_PRG_V1)


class LinearMaskingScheme:
    """Masking between recipient and committee; subclasses are the variants."""

    #: whether masks are produced at all (crypto.rs:66-75)
    has_mask: bool = True

    def to_obj(self):
        raise NotImplementedError

    @staticmethod
    def from_obj(obj) -> "LinearMaskingScheme":
        if obj == "None":
            return NoMasking()
        if isinstance(obj, dict) and len(obj) == 1:
            [(variant, p)] = obj.items()
            if variant == "Full":
                return FullMasking(modulus=p["modulus"])
            if variant == "ChaCha":
                return ChaChaMasking(
                    modulus=p["modulus"],
                    dimension=p["dimension"],
                    seed_bitsize=p["seed_bitsize"],
                    prg=p.get("prg", CHACHA_PRG_RAND03),
                )
        raise ValueError(f"unknown masking scheme {obj!r}")

    def __eq__(self, other):
        return type(self) is type(other) and self.to_obj() == other.to_obj()

    def __hash__(self):
        return hash(repr(self.to_obj()))

    def __repr__(self):
        return f"{type(self).__name__}({self.to_obj()!r})"


class NoMasking(LinearMaskingScheme):
    """No masking: secrets are shared directly to the clerks."""
    has_mask = False

    def to_obj(self):
        return "None"


class FullMasking(LinearMaskingScheme):
    """Per-element fresh-random mask; mask uploaded in full (O(d))."""

    def __init__(self, modulus: int):
        self.modulus = int(modulus)

    def to_obj(self):
        return {"Full": {"modulus": self.modulus}}


class ChaChaMasking(LinearMaskingScheme):
    """Seed-compressed masking: upload a <=256-bit seed, not an O(d) mask.

    Trades upload/download bandwidth for seed-expansion compute on both
    participant and recipient sides (crypto.rs:53-62). ``prg`` names the
    expansion stream; the default (CHACHA_PRG_RAND03) serializes to the
    exact Rust wire shape and draws the exact rand-0.3 ChaChaRng stream,
    so rounds mixed with a Rust peer stay correct.
    """

    def __init__(self, modulus: int, dimension: int, seed_bitsize: int,
                 prg: str = CHACHA_PRG_RAND03):
        self.modulus = int(modulus)
        self.dimension = int(dimension)
        self.seed_bitsize = int(seed_bitsize)
        if prg not in _CHACHA_PRGS:
            raise ValueError(
                f"unknown ChaCha PRG {prg!r}; known: {list(_CHACHA_PRGS)}"
            )
        self.prg = str(prg)

    def to_obj(self):
        obj = {
            "modulus": self.modulus,
            "dimension": self.dimension,
            "seed_bitsize": self.seed_bitsize,
        }
        if self.prg != CHACHA_PRG_RAND03:
            obj["prg"] = self.prg
        return {"ChaCha": obj}


# ---------------------------------------------------------------------------
# Secret-sharing schemes (crypto.rs:79-155)

class LinearSecretSharingScheme:
    """Sharing of masked secrets across the committee, with derived properties."""

    #: number of secrets shared together (crypto.rs:120-126)
    input_size: int
    #: number of shares produced == committee size (crypto.rs:129-135)
    output_size: int
    #: max colluding clerks before privacy is lost (crypto.rs:138-144)
    privacy_threshold: int
    #: min clerk results needed to reconstruct (crypto.rs:147-153)
    reconstruction_threshold: int

    def to_obj(self):
        raise NotImplementedError

    @staticmethod
    def from_obj(obj) -> "LinearSecretSharingScheme":
        if isinstance(obj, dict) and len(obj) == 1:
            [(variant, p)] = obj.items()
            if variant == "Additive":
                return AdditiveSharing(share_count=p["share_count"], modulus=p["modulus"])
            if variant == "BasicShamir":
                return BasicShamirSharing(
                    share_count=p["share_count"],
                    privacy_threshold=p["privacy_threshold"],
                    prime_modulus=p["prime_modulus"],
                )
            if variant == "PackedShamir":
                return PackedShamirSharing(
                    secret_count=p["secret_count"],
                    share_count=p["share_count"],
                    privacy_threshold=p["privacy_threshold"],
                    prime_modulus=p["prime_modulus"],
                    omega_secrets=p["omega_secrets"],
                    omega_shares=p["omega_shares"],
                )
        raise ValueError(f"unknown sharing scheme {obj!r}")

    def __eq__(self, other):
        return type(self) is type(other) and self.to_obj() == other.to_obj()

    def __hash__(self):
        return hash(repr(self.to_obj()))

    def __repr__(self):
        return f"{type(self).__name__}({self.to_obj()!r})"


class AdditiveSharing(LinearSecretSharingScheme):
    """n-of-n additive sharing over Z_modulus (computationally cheap)."""

    def __init__(self, share_count: int, modulus: int):
        self.share_count = int(share_count)
        self.modulus = int(modulus)

    input_size = 1

    @property
    def output_size(self) -> int:
        return self.share_count

    @property
    def privacy_threshold(self) -> int:
        return self.share_count - 1

    @property
    def reconstruction_threshold(self) -> int:
        return self.share_count

    def to_obj(self):
        return {"Additive": {"share_count": self.share_count, "modulus": self.modulus}}


class BasicShamirSharing(LinearSecretSharingScheme):
    """Classic (non-packed) Shamir over Z_p: one secret per polynomial,
    any ``privacy_threshold + 1`` of ``share_count`` shares reconstruct.

    The reference DECLARES this variant but ships it commented out
    (protocol/src/crypto.rs:89-95: share_count, privacy_threshold,
    prime_modulus), with its derived properties spelled out in the
    commented match arms of crypto.rs:117-155 (input_size 1,
    output_size share_count, reconstruction_threshold t + 1). Implemented
    for real here: shares are Vandermonde evaluations at points 1..n and
    reconstruction is Lagrange interpolation at zero — host-built
    matrices applied with the same device matmuls as the packed scheme,
    so every execution mode (federated, pod, streamed, Pallas, dropout
    quorums) works unchanged. Unlike PackedShamir the prime needs no
    root-of-unity structure: ANY prime > share_count qualifies.
    """

    def __init__(self, share_count: int, privacy_threshold: int,
                 prime_modulus: int):
        self.share_count = int(share_count)
        self._privacy_threshold = int(privacy_threshold)
        self.prime_modulus = int(prime_modulus)
        if not 1 <= self._privacy_threshold < self.share_count:
            raise ValueError(
                f"privacy threshold {privacy_threshold} must be in "
                f"[1, share_count {share_count})"
            )
        if self.prime_modulus <= self.share_count:
            raise ValueError(
                f"prime modulus {prime_modulus} must exceed share_count "
                f"{share_count} (evaluation points 1..n must be distinct "
                f"and nonzero mod p)"
            )

    #: one secret per polynomial — the k=1 degenerate of the packed layout,
    #: so downstream batching/matrix code is shared
    secret_count = 1
    input_size = 1

    @property
    def output_size(self) -> int:
        return self.share_count

    @property
    def privacy_threshold(self) -> int:
        return self._privacy_threshold

    @property
    def reconstruction_threshold(self) -> int:
        return self._privacy_threshold + 1

    def to_obj(self):
        return {
            "BasicShamir": {
                "share_count": self.share_count,
                "privacy_threshold": self._privacy_threshold,
                "prime_modulus": self.prime_modulus,
            }
        }


class PackedShamirSharing(LinearSecretSharingScheme):
    """Packed Shamir over Z_p: k secrets per polynomial, fault-tolerant.

    ``omega_secrets`` is a root of unity of power-of-2 order
    ``secret_count + privacy_threshold + 1``; ``omega_shares`` of power-of-3
    order ``share_count + 1`` — enabling NTT-based polynomial evaluation
    (reference scheme parameters: protocol/src/crypto.rs:98-113; working
    vector p=433, omega=354/150: integration-tests/tests/full_loop.rs:55-67).
    """

    def __init__(
        self,
        secret_count: int,
        share_count: int,
        privacy_threshold: int,
        prime_modulus: int,
        omega_secrets: int,
        omega_shares: int,
    ):
        self.secret_count = int(secret_count)
        self.share_count = int(share_count)
        self._privacy_threshold = int(privacy_threshold)
        self.prime_modulus = int(prime_modulus)
        self.omega_secrets = int(omega_secrets)
        self.omega_shares = int(omega_shares)

    @property
    def input_size(self) -> int:
        return self.secret_count

    @property
    def output_size(self) -> int:
        return self.share_count

    @property
    def privacy_threshold(self) -> int:
        return self._privacy_threshold

    @property
    def reconstruction_threshold(self) -> int:
        return self._privacy_threshold + self.secret_count

    def to_obj(self):
        return {
            "PackedShamir": {
                "secret_count": self.secret_count,
                "share_count": self.share_count,
                "privacy_threshold": self._privacy_threshold,
                "prime_modulus": self.prime_modulus,
                "omega_secrets": self.omega_secrets,
                "omega_shares": self.omega_shares,
            }
        }


# ---------------------------------------------------------------------------
# Additive encryption schemes (crypto.rs:159-188)

class AdditiveEncryptionScheme:
    """Share-transport encryption scheme."""

    batch_size: int = 1

    def to_obj(self):
        raise NotImplementedError

    @staticmethod
    def from_obj(obj) -> "AdditiveEncryptionScheme":
        if obj == "Sodium":
            return SodiumEncryption()
        if isinstance(obj, dict) and set(obj) == {"PackedPaillier"}:
            p = obj["PackedPaillier"]
            return PackedPaillierEncryption(
                component_count=p["component_count"],
                component_bitsize=p["component_bitsize"],
                max_value_bitsize=p["max_value_bitsize"],
                min_modulus_bitsize=p["min_modulus_bitsize"],
            )
        raise ValueError(f"unknown encryption scheme {obj!r}")

    def __eq__(self, other):
        return type(self) is type(other) and self.to_obj() == other.to_obj()

    def __hash__(self):
        return hash(repr(self.to_obj()))

    def __repr__(self):
        return f"{type(self).__name__}()"


class SodiumEncryption(AdditiveEncryptionScheme):
    """libsodium sealed box (Curve25519+XSalsa20+Poly1305), anonymous sender."""

    batch_size = 1

    def to_obj(self):
        return "Sodium"


class PackedPaillierEncryption(AdditiveEncryptionScheme):
    """Packed Paillier: additively homomorphic ciphertexts.

    Parameter semantics follow the reference's (disabled) declaration,
    crypto.rs:164-174: ``component_count`` values are packed per plaintext in
    ``component_bitsize``-bit windows; fresh values must fit
    ``max_value_bitsize`` bits, so up to ``2^(component_bitsize -
    max_value_bitsize)`` ciphertexts can be summed homomorphically before a
    component overflows its window; ``min_modulus_bitsize`` floors the key
    size n (and component_count * component_bitsize must fit under it).
    ``batch_size()`` is ``component_count``, matching crypto.rs:181-186.

    Sizing note: in the *recipient* slot under ChaCha masking the encrypted
    "mask" vector carries 32-bit seed words (chacha.rs:49-53 convention), so
    that slot needs ``max_value_bitsize >= 32``; the committee slot only
    carries field elements ``< modulus``.
    """

    def __init__(self, component_count: int, component_bitsize: int,
                 max_value_bitsize: int, min_modulus_bitsize: int):
        if max_value_bitsize > component_bitsize:
            raise ValueError("max_value_bitsize exceeds the component window")
        if component_bitsize > 63:
            raise ValueError("component window exceeds the int64 share range")
        if component_count * component_bitsize >= min_modulus_bitsize:
            raise ValueError("packed plaintext does not fit under the modulus floor")
        self.component_count = component_count
        self.component_bitsize = component_bitsize
        self.max_value_bitsize = max_value_bitsize
        self.min_modulus_bitsize = min_modulus_bitsize

    @property
    def batch_size(self) -> int:  # type: ignore[override]
        return self.component_count

    @property
    def additive_capacity(self) -> int:
        """How many fresh ciphertexts may be summed without window overflow."""
        return 1 << (self.component_bitsize - self.max_value_bitsize)

    def to_obj(self):
        return {
            "PackedPaillier": {
                "component_count": self.component_count,
                "component_bitsize": self.component_bitsize,
                "max_value_bitsize": self.max_value_bitsize,
                "min_modulus_bitsize": self.min_modulus_bitsize,
            }
        }

    def __repr__(self):
        return (
            f"PackedPaillierEncryption({self.component_count}, "
            f"{self.component_bitsize}, {self.max_value_bitsize}, "
            f"{self.min_modulus_bitsize})"
        )
