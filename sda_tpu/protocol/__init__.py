"""L0: protocol data model, scheme parameters, and the service seam."""

from .errors import (
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    PermissionDenied,
    SdaError,
    ServerError,
)
from .helpers import (
    B8,
    B32,
    B64,
    Binary,
    Labelled,
    ResourceId,
    Signed,
    canonical_json,
)
from .crypto import (
    CHACHA_PRG_RAND03,
    CHACHA_PRG_V1,
    AdditiveEncryptionScheme,
    AdditiveSharing,
    BasicShamirSharing,
    ChaChaMasking,
    Encryption,
    EncryptionKey,
    FullMasking,
    LinearMaskingScheme,
    LinearSecretSharingScheme,
    NoMasking,
    PackedPaillierEncryption,
    PackedShamirSharing,
    Signature,
    SigningKey,
    SodiumEncryption,
    VerificationKey,
)
from .resources import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    Participation,
    ParticipationId,
    Profile,
    Snapshot,
    SnapshotId,
    SnapshotResult,
    SnapshotStatus,
    VerificationKeyId,
    signed_encryption_key_from_obj,
)
from .methods import (
    Pong,
    SdaAgentService,
    SdaAggregationService,
    SdaBaseService,
    SdaClerkingService,
    SdaParticipationService,
    SdaRecipientService,
    SdaService,
)

__all__ = [name for name in dir() if not name.startswith("_")]
