"""Core protocol resources — the nouns of the SDA system.

Mirrors reference: protocol/src/resources.rs (Agent :12-17, Profile :23-35,
Aggregation :44-67, ClerkCandidate :73-79, Committee :83-88, Participation
:92-108, Snapshot :116-121, ClerkingJob :128-139, ClerkingResult :146-153,
AggregationStatus :157-164, SnapshotStatus :167-175, SnapshotResult :179-188).

Serde: `to_obj`/`from_obj` produce the same JSON shapes as the reference's
serde derive — struct fields in declaration order (canonical-JSON signing
depends on it), ids as uuid strings, Option as null, Vec<(A,B)> as nested
arrays.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .crypto import (
    AdditiveEncryptionScheme,
    Encryption,
    EncryptionKey,
    LinearMaskingScheme,
    LinearSecretSharingScheme,
    Signature,
    VerificationKey,
)
from .helpers import Labelled, ResourceId, Signed, canonical_json


class AgentId(ResourceId):
    pass


class VerificationKeyId(ResourceId):
    pass


class EncryptionKeyId(ResourceId):
    pass


class AggregationId(ResourceId):
    pass


class ParticipationId(ResourceId):
    pass


class SnapshotId(ResourceId):
    pass


class ClerkingJobId(ResourceId):
    pass


def labelled_verification_key(id: VerificationKeyId, key: VerificationKey):
    return Labelled(id, key)


class Agent:
    """Fundamental description of an agent (participant/clerk/recipient/admin)."""

    __slots__ = ("id", "verification_key")

    def __init__(self, id: AgentId, verification_key: Labelled):
        self.id = id
        self.verification_key = verification_key

    def __eq__(self, other):
        return (
            isinstance(other, Agent)
            and self.id == other.id
            and self.verification_key == other.verification_key
        )

    def __repr__(self):
        return f"Agent(id={self.id!r})"

    def to_obj(self):
        return {"id": self.id.to_obj(), "verification_key": self.verification_key.to_obj()}

    @classmethod
    def from_obj(cls, obj):
        return cls(
            id=AgentId.from_obj(obj["id"]),
            verification_key=Labelled.from_obj(
                obj["verification_key"], VerificationKeyId, VerificationKey
            ),
        )


class Profile:
    """Extended, trust-building profile of an agent."""

    __slots__ = ("owner", "name", "twitter_id", "keybase_id", "website")

    def __init__(
        self,
        owner: AgentId,
        name: Optional[str] = None,
        twitter_id: Optional[str] = None,
        keybase_id: Optional[str] = None,
        website: Optional[str] = None,
    ):
        self.owner = owner
        self.name = name
        self.twitter_id = twitter_id
        self.keybase_id = keybase_id
        self.website = website

    def __eq__(self, other):
        return isinstance(other, Profile) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {
            "owner": self.owner.to_obj(),
            "name": self.name,
            "twitter_id": self.twitter_id,
            "keybase_id": self.keybase_id,
            "website": self.website,
        }

    @classmethod
    def from_obj(cls, obj):
        return cls(
            owner=AgentId.from_obj(obj["owner"]),
            name=obj.get("name"),
            twitter_id=obj.get("twitter_id"),
            keybase_id=obj.get("keybase_id"),
            website=obj.get("website"),
        )


#: Encryption key labelled by its id and signed by the owning agent.
#: SignedEncryptionKey = Signed<Labelled<EncryptionKeyId, EncryptionKey>>
def signed_encryption_key_from_obj(obj) -> Signed:
    return Signed.from_obj(
        obj,
        signature_type=Signature,
        signer_type=AgentId,
        body_from_obj=lambda b: Labelled.from_obj(b, EncryptionKeyId, EncryptionKey),
    )


class TreeLink:
    """Position of an aggregation inside a hierarchical (tree) round.

    Flat committees cap out structurally — every clerk touches every
    participation — so population-scale rounds shard the population into
    leaf groups whose committees feed a parent round (``sda_tpu/tree``;
    Bonawitz et al., MLSys 2019). This resource is the linkage that makes
    the topology first-class on the wire:

    - ``root``: the aggregation at the top of the tree (the one whose
      recipient learns the final aggregate);
    - ``parent``: the immediate parent aggregation this node's *relay*
      re-shares its masked total into (``None`` on the root itself);
    - ``children``: the child aggregations feeding this node (empty on
      leaves) — recorded at plan time so any worker can walk the tree
      from the round documents alone;
    - ``level``: 0 at the root, increasing towards the leaves;
    - ``group``: the leaf-group index assigned by the routing ring
      (``server/routing.py``), ``None`` for internal nodes;
    - ``mask_recipient`` / ``mask_recipient_key``: where participants
      seal their recipient-mask ciphertexts. In a tree these name the
      ROOT recipient, not the node's own recipient (the relay): the
      relay quorum-reconstructs only the *masked* leaf total and
      forwards the mask ciphertexts upward unopened, so privacy composes
      per level — no relay ever sees an unmasked value.
    """

    __slots__ = ("root", "parent", "children", "level", "group",
                 "mask_recipient", "mask_recipient_key")

    def __init__(
        self,
        root: AggregationId,
        parent: Optional[AggregationId] = None,
        children: Optional[List[AggregationId]] = None,
        level: int = 0,
        group: Optional[int] = None,
        mask_recipient: Optional[AgentId] = None,
        mask_recipient_key: Optional[EncryptionKeyId] = None,
    ):
        self.root = root
        self.parent = parent
        self.children = list(children or [])
        self.level = int(level)
        self.group = None if group is None else int(group)
        self.mask_recipient = mask_recipient
        self.mask_recipient_key = mask_recipient_key

    def __eq__(self, other):
        return isinstance(other, TreeLink) and self.to_obj() == other.to_obj()

    def __repr__(self):
        return (f"TreeLink(root={self.root!r}, parent={self.parent!r}, "
                f"level={self.level}, group={self.group})")

    def to_obj(self):
        return {
            "root": self.root.to_obj(),
            "parent": None if self.parent is None else self.parent.to_obj(),
            "children": [c.to_obj() for c in self.children],
            "level": self.level,
            "group": self.group,
            "mask_recipient": (
                None if self.mask_recipient is None
                else self.mask_recipient.to_obj()),
            "mask_recipient_key": (
                None if self.mask_recipient_key is None
                else self.mask_recipient_key.to_obj()),
        }

    @classmethod
    def from_obj(cls, obj):
        parent = obj.get("parent")
        mask_recipient = obj.get("mask_recipient")
        mask_key = obj.get("mask_recipient_key")
        return cls(
            root=AggregationId.from_obj(obj["root"]),
            parent=None if parent is None else AggregationId.from_obj(parent),
            children=[AggregationId.from_obj(c)
                      for c in (obj.get("children") or [])],
            level=obj.get("level") or 0,
            group=obj.get("group"),
            mask_recipient=(None if mask_recipient is None
                            else AgentId.from_obj(mask_recipient)),
            mask_recipient_key=(None if mask_key is None
                                else EncryptionKeyId.from_obj(mask_key)),
        )


class Aggregation:
    """Description of an aggregation: dimensions, modulus, schemes, recipient.

    ``tree`` places the aggregation inside a hierarchical round
    (:class:`TreeLink`); ``None`` — the default, and the only shape the
    reference knows — means an ordinary flat round. The field is omitted
    from the serialized object when absent, so flat aggregations keep the
    exact reference wire shape."""

    __slots__ = (
        "id",
        "title",
        "vector_dimension",
        "modulus",
        "recipient",
        "recipient_key",
        "masking_scheme",
        "committee_sharing_scheme",
        "recipient_encryption_scheme",
        "committee_encryption_scheme",
        "tree",
    )

    def __init__(
        self,
        id: AggregationId,
        title: str,
        vector_dimension: int,
        modulus: int,
        recipient: AgentId,
        recipient_key: EncryptionKeyId,
        masking_scheme: LinearMaskingScheme,
        committee_sharing_scheme: LinearSecretSharingScheme,
        recipient_encryption_scheme: AdditiveEncryptionScheme,
        committee_encryption_scheme: AdditiveEncryptionScheme,
        tree: Optional[TreeLink] = None,
    ):
        self.id = id
        self.title = title
        self.vector_dimension = int(vector_dimension)
        self.modulus = int(modulus)
        self.recipient = recipient
        self.recipient_key = recipient_key
        self.masking_scheme = masking_scheme
        self.committee_sharing_scheme = committee_sharing_scheme
        self.recipient_encryption_scheme = recipient_encryption_scheme
        self.committee_encryption_scheme = committee_encryption_scheme
        self.tree = tree

    def __eq__(self, other):
        return isinstance(other, Aggregation) and self.to_obj() == other.to_obj()

    def __repr__(self):
        return f"Aggregation(id={self.id!r}, title={self.title!r})"

    def replace(self, **kwargs) -> "Aggregation":
        """Functional update, mirroring Rust struct-update syntax in tests."""
        fields = {name: getattr(self, name) for name in self.__slots__}
        fields.update(kwargs)
        return Aggregation(**fields)

    def mask_seal_target(self):
        """``(owner AgentId, EncryptionKeyId)`` the recipient-MASK
        ciphertext must seal to. Flat rounds: the aggregation's own
        recipient. Tree rounds redirect to the ROOT recipient
        (``TreeLink.mask_recipient_key``) — the node's own recipient is
        a relay that must reconstruct only the masked total, and sealing
        the mask past it is what makes privacy compose per level. THE
        single rule for every participant implementation (Python client
        and embedded client both call this)."""
        if self.tree is not None and self.tree.mask_recipient_key is not None:
            return self.tree.mask_recipient, self.tree.mask_recipient_key
        return self.recipient, self.recipient_key

    def to_obj(self):
        obj = {
            "id": self.id.to_obj(),
            "title": self.title,
            "vector_dimension": self.vector_dimension,
            "modulus": self.modulus,
            "recipient": self.recipient.to_obj(),
            "recipient_key": self.recipient_key.to_obj(),
            "masking_scheme": self.masking_scheme.to_obj(),
            "committee_sharing_scheme": self.committee_sharing_scheme.to_obj(),
            "recipient_encryption_scheme": self.recipient_encryption_scheme.to_obj(),
            "committee_encryption_scheme": self.committee_encryption_scheme.to_obj(),
        }
        if self.tree is not None:
            obj["tree"] = self.tree.to_obj()
        return obj

    @classmethod
    def from_obj(cls, obj):
        tree = obj.get("tree")
        return cls(
            id=AggregationId.from_obj(obj["id"]),
            title=obj["title"],
            vector_dimension=obj["vector_dimension"],
            modulus=obj["modulus"],
            recipient=AgentId.from_obj(obj["recipient"]),
            recipient_key=EncryptionKeyId.from_obj(obj["recipient_key"]),
            masking_scheme=LinearMaskingScheme.from_obj(obj["masking_scheme"]),
            committee_sharing_scheme=LinearSecretSharingScheme.from_obj(
                obj["committee_sharing_scheme"]
            ),
            recipient_encryption_scheme=AdditiveEncryptionScheme.from_obj(
                obj["recipient_encryption_scheme"]
            ),
            committee_encryption_scheme=AdditiveEncryptionScheme.from_obj(
                obj["committee_encryption_scheme"]
            ),
            tree=None if tree is None else TreeLink.from_obj(tree),
        )


class ClerkCandidate:
    """Suggested clerk for an aggregation, with matching encryption keys."""

    __slots__ = ("id", "keys")

    def __init__(self, id: AgentId, keys: List[EncryptionKeyId]):
        self.id = id
        self.keys = list(keys)

    def __eq__(self, other):
        return isinstance(other, ClerkCandidate) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {"id": self.id.to_obj(), "keys": [k.to_obj() for k in self.keys]}

    @classmethod
    def from_obj(cls, obj):
        return cls(
            id=AgentId.from_obj(obj["id"]),
            keys=[EncryptionKeyId.from_obj(k) for k in obj["keys"]],
        )


class Committee:
    """Committee elected for an aggregation: clerks with their chosen keys."""

    __slots__ = ("aggregation", "clerks_and_keys")

    def __init__(
        self, aggregation: AggregationId, clerks_and_keys: List[Tuple[AgentId, EncryptionKeyId]]
    ):
        self.aggregation = aggregation
        self.clerks_and_keys = [(a, k) for (a, k) in clerks_and_keys]

    def __eq__(self, other):
        return isinstance(other, Committee) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {
            "aggregation": self.aggregation.to_obj(),
            "clerks_and_keys": [[a.to_obj(), k.to_obj()] for (a, k) in self.clerks_and_keys],
        }

    @classmethod
    def from_obj(cls, obj):
        return cls(
            aggregation=AggregationId.from_obj(obj["aggregation"]),
            clerks_and_keys=[
                (AgentId.from_obj(a), EncryptionKeyId.from_obj(k))
                for (a, k) in obj["clerks_and_keys"]
            ],
        )


class Participation:
    """A participant's encrypted input to an aggregation.

    The fresh ``id`` lets the server dedupe retried uploads
    (resources.rs:93-101).

    ``forwarded_masks`` is the tree-aggregation extension: a *relay*
    re-sharing its leaf's masked total into a parent round attaches the
    leaf's recipient-mask ciphertexts (sealed to the ROOT recipient,
    which the relay cannot open) so they travel upward IN-BAND with the
    re-share — one exactly-once ingest covers both, and the parent's
    snapshot mask collection picks them up alongside the relay's own
    mask. ``None`` (the default) keeps the exact reference wire shape
    and canonical digest for ordinary participations.
    """

    __slots__ = ("id", "participant", "aggregation", "recipient_encryption",
                 "clerk_encryptions", "forwarded_masks")

    def __init__(
        self,
        id: ParticipationId,
        participant: AgentId,
        aggregation: AggregationId,
        recipient_encryption: Optional[Encryption],
        clerk_encryptions: List[Tuple[AgentId, Encryption]],
        forwarded_masks: Optional[List[Encryption]] = None,
    ):
        self.id = id
        self.participant = participant
        self.aggregation = aggregation
        self.recipient_encryption = recipient_encryption
        self.clerk_encryptions = [(a, e) for (a, e) in clerk_encryptions]
        self.forwarded_masks = (
            None if forwarded_masks is None else list(forwarded_masks))

    def __eq__(self, other):
        return isinstance(other, Participation) and self.to_obj() == other.to_obj()

    def canonical_digest(self) -> str:
        """SHA-256 over the canonical JSON bytes of this participation —
        the content half of the exactly-once ingestion key. Two uploads
        with equal digests are byte-identical replays of one sealed
        bundle (safe to dedupe); unequal digests under one
        ``(aggregation, participant)`` key are an equivocation
        (``ParticipationConflict``). Uses the same ``canonical_json``
        serialization the signature layer trusts, so the digest is
        stable across store round trips."""
        import hashlib

        return hashlib.sha256(canonical_json(self.to_obj())).hexdigest()

    def to_obj(self):
        obj = {
            "id": self.id.to_obj(),
            "participant": self.participant.to_obj(),
            "aggregation": self.aggregation.to_obj(),
            "recipient_encryption": (
                None if self.recipient_encryption is None else self.recipient_encryption.to_obj()
            ),
            "clerk_encryptions": [
                [a.to_obj(), e.to_obj()] for (a, e) in self.clerk_encryptions
            ],
        }
        if self.forwarded_masks is not None:
            obj["forwarded_masks"] = [e.to_obj() for e in self.forwarded_masks]
        return obj

    @classmethod
    def from_obj(cls, obj):
        rec = obj.get("recipient_encryption")
        forwarded = obj.get("forwarded_masks")
        return cls(
            id=ParticipationId.from_obj(obj["id"]),
            participant=AgentId.from_obj(obj["participant"]),
            aggregation=AggregationId.from_obj(obj["aggregation"]),
            recipient_encryption=None if rec is None else Encryption.from_obj(rec),
            clerk_encryptions=[
                (AgentId.from_obj(a), Encryption.from_obj(e))
                for (a, e) in obj["clerk_encryptions"]
            ],
            forwarded_masks=(
                None if forwarded is None
                else [Encryption.from_obj(e) for e in forwarded]),
        )


class Snapshot:
    """Freezes a consistent subset of participations for clerking."""

    __slots__ = ("id", "aggregation")

    def __init__(self, id: SnapshotId, aggregation: AggregationId):
        self.id = id
        self.aggregation = aggregation

    def __eq__(self, other):
        return isinstance(other, Snapshot) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {"id": self.id.to_obj(), "aggregation": self.aggregation.to_obj()}

    @classmethod
    def from_obj(cls, obj):
        return cls(
            id=SnapshotId.from_obj(obj["id"]),
            aggregation=AggregationId.from_obj(obj["aggregation"]),
        )


class ClerkingJob:
    """Partial-aggregation job for one clerk: its column of encryptions."""

    __slots__ = ("id", "clerk", "aggregation", "snapshot", "encryptions")

    def __init__(
        self,
        id: ClerkingJobId,
        clerk: AgentId,
        aggregation: AggregationId,
        snapshot: SnapshotId,
        encryptions: List[Encryption],
    ):
        self.id = id
        self.clerk = clerk
        self.aggregation = aggregation
        self.snapshot = snapshot
        self.encryptions = list(encryptions)

    def __eq__(self, other):
        return isinstance(other, ClerkingJob) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {
            "id": self.id.to_obj(),
            "clerk": self.clerk.to_obj(),
            "aggregation": self.aggregation.to_obj(),
            "snapshot": self.snapshot.to_obj(),
            "encryptions": [e.to_obj() for e in self.encryptions],
        }

    @classmethod
    def from_obj(cls, obj):
        return cls(
            id=ClerkingJobId.from_obj(obj["id"]),
            clerk=AgentId.from_obj(obj["clerk"]),
            aggregation=AggregationId.from_obj(obj["aggregation"]),
            snapshot=SnapshotId.from_obj(obj["snapshot"]),
            encryptions=[Encryption.from_obj(e) for e in obj["encryptions"]],
        )


class ClerkingResult:
    """Result of a clerking job: encryption of the combined shares."""

    __slots__ = ("job", "clerk", "encryption")

    def __init__(self, job: ClerkingJobId, clerk: AgentId, encryption: Encryption):
        self.job = job
        self.clerk = clerk
        self.encryption = encryption

    def __eq__(self, other):
        return isinstance(other, ClerkingResult) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {
            "job": self.job.to_obj(),
            "clerk": self.clerk.to_obj(),
            "encryption": self.encryption.to_obj(),
        }

    @classmethod
    def from_obj(cls, obj):
        return cls(
            job=ClerkingJobId.from_obj(obj["job"]),
            clerk=AgentId.from_obj(obj["clerk"]),
            encryption=Encryption.from_obj(obj["encryption"]),
        )


class SnapshotStatus:
    """Progress of one snapshot: result count and readiness."""

    __slots__ = ("id", "number_of_clerking_results", "result_ready")

    def __init__(self, id: SnapshotId, number_of_clerking_results: int, result_ready: bool):
        self.id = id
        self.number_of_clerking_results = int(number_of_clerking_results)
        self.result_ready = bool(result_ready)

    def __eq__(self, other):
        return isinstance(other, SnapshotStatus) and self.to_obj() == other.to_obj()

    def to_obj(self):
        return {
            "id": self.id.to_obj(),
            "number_of_clerking_results": self.number_of_clerking_results,
            "result_ready": self.result_ready,
        }

    @classmethod
    def from_obj(cls, obj):
        return cls(
            id=SnapshotId.from_obj(obj["id"]),
            number_of_clerking_results=obj["number_of_clerking_results"],
            result_ready=obj["result_ready"],
        )


class AggregationStatus:
    """Participation count plus per-snapshot statuses."""

    __slots__ = ("aggregation", "number_of_participations", "snapshots")

    def __init__(
        self,
        aggregation: AggregationId,
        number_of_participations: int,
        snapshots: List[SnapshotStatus],
    ):
        self.aggregation = aggregation
        self.number_of_participations = int(number_of_participations)
        self.snapshots = list(snapshots)

    def to_obj(self):
        return {
            "aggregation": self.aggregation.to_obj(),
            "number_of_participations": self.number_of_participations,
            "snapshots": [s.to_obj() for s in self.snapshots],
        }

    @classmethod
    def from_obj(cls, obj):
        return cls(
            aggregation=AggregationId.from_obj(obj["aggregation"]),
            number_of_participations=obj["number_of_participations"],
            snapshots=[SnapshotStatus.from_obj(s) for s in obj["snapshots"]],
        )


class RoundStatus:
    """Lifecycle state of an aggregation's current round — the explicit
    state machine the round supervisor persists (``server/lifecycle.py``:
    ``collecting → frozen → clerking → ready → revealed`` plus terminal
    ``degraded``/``failed``/``expired``). ``results`` is the LIVE
    clerking-result count; ``history`` is the bounded list of
    ``[state, unix_ts]`` transition stamps.

    ``parent``/``children`` expose the hierarchical-round linkage
    (:class:`TreeLink`): a stuck tree is diagnosable from any worker by
    walking round documents — ``GET /v1/aggregations/{id}/round`` on the
    root names its children, each child names its parent."""

    __slots__ = ("aggregation", "state", "snapshot", "scheme",
                 "committee_size", "reconstruction_threshold", "results",
                 "dead_clerks", "reason", "deadline_at", "updated_at",
                 "history", "parent", "children")

    def __init__(
        self,
        aggregation: AggregationId,
        state: str,
        snapshot: Optional[SnapshotId] = None,
        scheme: Optional[str] = None,
        committee_size: int = 0,
        reconstruction_threshold: int = 0,
        results: int = 0,
        dead_clerks=None,
        reason: Optional[str] = None,
        deadline_at: Optional[float] = None,
        updated_at: Optional[float] = None,
        history=None,
        parent: Optional[AggregationId] = None,
        children=None,
    ):
        self.aggregation = aggregation
        self.state = str(state)
        self.snapshot = snapshot
        self.scheme = scheme
        self.committee_size = int(committee_size)
        self.reconstruction_threshold = int(reconstruction_threshold)
        self.results = int(results)
        self.dead_clerks = [AgentId(c) for c in (dead_clerks or [])]
        self.reason = reason
        self.deadline_at = None if deadline_at is None else float(deadline_at)
        self.updated_at = None if updated_at is None else float(updated_at)
        self.history = [[str(s), float(ts)] for (s, ts) in (history or [])]
        self.parent = None if parent is None else AggregationId(parent)
        self.children = [AggregationId(c) for c in (children or [])]

    def __eq__(self, other):
        return isinstance(other, RoundStatus) and self.to_obj() == other.to_obj()

    def __repr__(self):
        return (f"RoundStatus(aggregation={self.aggregation!r}, "
                f"state={self.state!r}, results={self.results})")

    def to_obj(self):
        return {
            "aggregation": self.aggregation.to_obj(),
            "state": self.state,
            "snapshot": None if self.snapshot is None else self.snapshot.to_obj(),
            "scheme": self.scheme,
            "committee_size": self.committee_size,
            "reconstruction_threshold": self.reconstruction_threshold,
            "results": self.results,
            "dead_clerks": [c.to_obj() for c in self.dead_clerks],
            "reason": self.reason,
            "deadline_at": self.deadline_at,
            "updated_at": self.updated_at,
            "history": [[s, ts] for (s, ts) in self.history],
            "parent": None if self.parent is None else self.parent.to_obj(),
            "children": [c.to_obj() for c in self.children],
        }

    @classmethod
    def from_obj(cls, obj):
        snap = obj.get("snapshot")
        return cls(
            aggregation=AggregationId.from_obj(obj["aggregation"]),
            state=obj["state"],
            snapshot=None if snap is None else SnapshotId.from_obj(snap),
            scheme=obj.get("scheme"),
            committee_size=obj.get("committee_size") or 0,
            reconstruction_threshold=obj.get("reconstruction_threshold") or 0,
            results=obj.get("results") or 0,
            dead_clerks=obj.get("dead_clerks") or [],
            reason=obj.get("reason"),
            deadline_at=obj.get("deadline_at"),
            updated_at=obj.get("updated_at"),
            history=obj.get("history") or [],
            parent=obj.get("parent"),
            children=obj.get("children") or [],
        )


class SnapshotResult:
    """Everything the recipient needs to reconstruct: clerk results + masks."""

    __slots__ = ("snapshot", "number_of_participations", "clerk_encryptions", "recipient_encryptions")

    def __init__(
        self,
        snapshot: SnapshotId,
        number_of_participations: int,
        clerk_encryptions: List[ClerkingResult],
        recipient_encryptions: Optional[List[Encryption]],
    ):
        self.snapshot = snapshot
        self.number_of_participations = int(number_of_participations)
        self.clerk_encryptions = list(clerk_encryptions)
        self.recipient_encryptions = (
            None if recipient_encryptions is None else list(recipient_encryptions)
        )

    def to_obj(self):
        return {
            "snapshot": self.snapshot.to_obj(),
            "number_of_participations": self.number_of_participations,
            "clerk_encryptions": [c.to_obj() for c in self.clerk_encryptions],
            "recipient_encryptions": (
                None
                if self.recipient_encryptions is None
                else [e.to_obj() for e in self.recipient_encryptions]
            ),
        }

    @classmethod
    def from_obj(cls, obj):
        rec = obj.get("recipient_encryptions")
        return cls(
            snapshot=SnapshotId.from_obj(obj["snapshot"]),
            number_of_participations=obj["number_of_participations"],
            clerk_encryptions=[ClerkingResult.from_obj(c) for c in obj["clerk_encryptions"]],
            recipient_encryptions=None if rec is None else [Encryption.from_obj(e) for e in rec],
        )
