"""Error model for the SDA protocol and services.

Mirrors the error kinds the reference distinguishes (reference:
protocol/src/errors.rs and server/src/errors.rs): permission denied,
invalid credentials, invalid request, and generic failures — these drive
both the server-side ACL wrapper and the HTTP status mapping
(reference: server-http/src/lib.rs:105-122).
"""

from __future__ import annotations


class SdaError(Exception):
    """Base class for all protocol-level errors."""


class PermissionDenied(SdaError):
    """Caller is not allowed to perform the operation (ACL failure)."""

    def __init__(self, message: str = "permission denied"):
        super().__init__(message)


class InvalidCredentials(SdaError):
    """Authentication failed (bad or missing auth token)."""

    def __init__(self, message: str = "invalid credentials"):
        super().__init__(message)


class InvalidRequest(SdaError):
    """Request is malformed or violates an invariant (HTTP 400)."""


class NotFound(SdaError):
    """Referenced resource does not exist.

    Services normally signal missing resources by returning ``None``; this
    error is for flows where absence is fatal (e.g. "aggregation not found"
    while creating a committee, reference: server/src/server.rs:86-99).
    """


class ServerError(SdaError):
    """Internal server failure (HTTP 500).

    ``retry_after`` (seconds, optional) is stamped on instances that know
    when the condition clears — the HTTP client copies the server's
    ``Retry-After`` hint here on terminal 5xx responses, and pollers
    (``SdaClient.await_result``) honor it instead of their fixed cadence.
    """

    retry_after = None


class StoreUnavailable(ServerError):
    """The storage backend is browning out and the circuit breaker is
    OPEN (``server/breaker.py``): the operation was shed WITHOUT touching
    the store. Maps to HTTP 503 + ``Retry-After`` — the client-side
    immutable-document cache keeps reads flowing and the retrying
    transport resubmits writes once the breaker half-opens."""

    def __init__(self, message: str = "store unavailable",
                 retry_after: float = None):
        super().__init__(message)
        self.retry_after = retry_after


class ParticipationConflict(SdaError):
    """Exactly-once ingestion rejected a participation upload.

    The store already holds a DIFFERENT share bundle under the same key —
    either the same ``(aggregation, participant)`` pair with other content
    (a device that recomputed with fresh randomness instead of resuming
    its journal, or an equivocating device submitting two inputs) or the
    same participation id with other bytes (a buggy peer trying to
    replace an earlier upload in place). Byte-identical replays are NOT
    conflicts: they return success idempotently, which is what makes
    crash/retry loops safe. Maps to HTTP 409, which the retrying
    transport classifies terminal — retrying an equivocation cannot ever
    succeed (docs/robustness.md)."""

    def __init__(self, message: str = "participation conflict", *,
                 participant=None, aggregation=None):
        super().__init__(message)
        self.participant = participant
        self.aggregation = aggregation


class RoundFailed(SdaError):
    """The round lifecycle supervisor declared the round terminally
    ``failed`` — e.g. a dead clerk under additive sharing (every share is
    required) or dead clerks leaving a Shamir committee below its
    reconstruction threshold. Carries the server's diagnosis so callers
    can act on it programmatically (``server/lifecycle.py``)."""

    def __init__(self, message: str = "round failed", *, state=None,
                 reason=None, dead_clerks=None):
        super().__init__(message)
        self.state = state
        self.reason = reason
        self.dead_clerks = list(dead_clerks or [])


class RoundExpired(RoundFailed):
    """The round ran out of time — a phase deadline lapsed server-side
    (terminal ``expired`` state) or a client-side ``await_result``
    deadline was exceeded before the round completed."""

    def __init__(self, message: str = "round expired", *, state=None,
                 reason=None, dead_clerks=None):
        super().__init__(message, state=state, reason=reason,
                         dead_clerks=dead_clerks)
