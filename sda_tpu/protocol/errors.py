"""Error model for the SDA protocol and services.

Mirrors the error kinds the reference distinguishes (reference:
protocol/src/errors.rs and server/src/errors.rs): permission denied,
invalid credentials, invalid request, and generic failures — these drive
both the server-side ACL wrapper and the HTTP status mapping
(reference: server-http/src/lib.rs:105-122).
"""

from __future__ import annotations


class SdaError(Exception):
    """Base class for all protocol-level errors."""


class PermissionDenied(SdaError):
    """Caller is not allowed to perform the operation (ACL failure)."""

    def __init__(self, message: str = "permission denied"):
        super().__init__(message)


class InvalidCredentials(SdaError):
    """Authentication failed (bad or missing auth token)."""

    def __init__(self, message: str = "invalid credentials"):
        super().__init__(message)


class InvalidRequest(SdaError):
    """Request is malformed or violates an invariant (HTTP 400)."""


class NotFound(SdaError):
    """Referenced resource does not exist.

    Services normally signal missing resources by returning ``None``; this
    error is for flows where absence is fatal (e.g. "aggregation not found"
    while creating a committee, reference: server/src/server.rs:86-99).
    """


class ServerError(SdaError):
    """Internal server failure (HTTP 500)."""
