"""Dim-tiled round schedule: lax.scan over fixed-width dimension tiles.

The round-3 hardware window measured the full-width single-chip round
SUPERLINEAR in d (marginal 25.8ms at d~1M vs 7.7ms at d/2 — per-element
cost 1.7x worse at full width; benchmarks/ROOFLINE.md 'Superlinearity').
Scanning fixed-width tiles keeps every tile on the fast side of that
cliff and makes round cost affine in d by construction. Shared by the
XLA (mesh.single_chip_round) and Pallas (fields.pallas_round) drivers,
and — via :func:`tile_plan` — by the model-scale sharded driver
(mesh/devscale.py), so every tiled lane slices the dimension with ONE
arithmetic.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class TilePlan(NamedTuple):
    """The fixed-width tiling of a dimension: THE schedule arithmetic,
    shared by the in-program scan below and the host-driven model-scale
    loop (mesh/devscale.py) so the two lanes cannot drift.

    ``width``   — the grain-rounded tile width actually used;
    ``n_tiles`` — number of tiles covering the (padded) dimension;
    ``pad``     — zero columns appended so ``n_tiles * width`` covers
                  ``dim`` (zero columns aggregate as zero and are
                  sliced off the output).
    """

    width: int
    n_tiles: int
    pad: int

    @property
    def padded_dim(self) -> int:
        return self.n_tiles * self.width


def tile_plan(dim: int, grain: int, dim_tile: int) -> TilePlan:
    """Fixed-width tiling of ``dim`` at the requested ``dim_tile`` width.

    The width is rounded UP to a whole multiple of ``grain`` (whole
    packing columns x whole ChaCha blocks — a tile must be a complete
    round over its own columns). A dimension narrower than one tile is
    a single tile of its own grain-rounded width: a wide tile knob must
    not inflate small shapes.
    """
    if dim_tile <= 0:
        raise ValueError(f"dim_tile must be positive, got {dim_tile}")
    if grain <= 0:
        raise ValueError(f"grain must be positive, got {grain}")
    T = -(-int(dim_tile) // grain) * grain
    if dim < T:
        width = -(-int(dim) // grain) * grain
        return TilePlan(width, 1, width - dim)
    n_tiles = -(-dim // T)
    return TilePlan(T, n_tiles, n_tiles * T - dim)


def scan_dim_tiles(one_tile, grain: int, dim_tile: int):
    """Wrap a per-tile round into a full-round function.

    ``one_tile(blk, round_key, tile_key, tile_idx, width)`` computes a
    complete round over ``blk`` ([P, width] raw inputs) and returns the
    [width] int64 aggregate; ``tile_idx`` may be traced. ``grain`` is the
    tile-width quantum (whole packing columns x whole ChaCha blocks).

    Returns ``round_fn(inputs, key)``. Inputs narrower than one tile run
    ``one_tile`` directly (no pad/scan machinery — a wide tile knob must
    not inflate small shapes); everything else runs the scan, INCLUDING
    the exactly-one-tile case, so timing points at 1, 2, ... tiles all
    measure the same schedule (a fit mixing the untiled program into its
    first point would misclassify the tiled schedule).
    """
    if dim_tile <= 0:
        raise ValueError(f"dim_tile must be positive, got {dim_tile}")
    T = -(-int(dim_tile) // grain) * grain

    def round_fn(inputs, key):
        P, d = inputs.shape
        if d < T:
            return one_tile(inputs, key, key, jnp.int32(0), d)
        plan = tile_plan(d, grain, T)
        if plan.pad:  # zero columns aggregate as zero; sliced off below
            inputs = jnp.pad(inputs, ((0, 0), (0, plan.pad)))
        xt = jnp.moveaxis(
            inputs.reshape(P, plan.n_tiles, plan.width), 1, 0)
        # [n_tiles, P, T]

        def body(_, blk_i):
            blk, i = blk_i
            # fold_in keeps tile randomness streams distinct (exactness
            # never depends on it — masks cancel and random polynomial
            # rows are annihilated by reconstruction)
            return None, one_tile(
                blk, key, jax.random.fold_in(key, i), i, plan.width)

        _, tiles = jax.lax.scan(
            body, None, (xt, jnp.arange(plan.n_tiles, dtype=jnp.int32)))
        return tiles.reshape(-1)[:d]

    return round_fn
