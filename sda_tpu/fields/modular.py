"""Device-side modular arithmetic kernels (jnp, jit-friendly).

All arrays carry int64 values in canonical form [0, m). On TPU int64 is
emulated in int32 pairs, so kernels are written to (a) keep intermediates
small enough for exactness, and (b) expose an int8-limb MXU path for the
hot matmul (``modmatmul``), which lowers to native int8 systolic-array
matmuls with int32 accumulation.

Overflow discipline (p < 2^31 enforced by schemes):
- direct einsum path: products < p^2 < 2^62, safe only when k*p^2 < 2^63;
- limb path: b split as b_hi*2^16 + b_lo, products < p*2^16 < 2^47, safe
  for contraction sizes k < 2^15.

The reference computes the same algebra as scalar Rust loops over Vec<i64>
(client/src/crypto/sharing/*.rs); the canonical-form convention here differs
only by a final `positive()` lift (receive.rs:14-21) — values are congruent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def canon(x, m):
    """Canonical representative in [0, m) of any int64 residues."""
    return jnp.mod(x, m)


def modadd(a, b, m):
    return jnp.mod(a + b, m)


def modsub(a, b, m):
    return jnp.mod(a - b, m)


def modsum(x, m, axis=0):
    """Sum of canonical residues along ``axis`` mod m.

    Safe while n_terms * m < 2^63 (n < 2^32 for the largest 31-bit moduli) —
    this is THE clerk kernel (reference hot loop: sharing/combiner.rs:15-30).
    """
    return jnp.mod(jnp.sum(x, axis=axis, dtype=jnp.int64), m)


def _modmatmul_direct(a, b, p):
    return jnp.mod(jnp.matmul(a, b, preferred_element_type=jnp.int64), p)


def _modmatmul_limb(a, b, p):
    b_hi = b >> 16
    b_lo = b & 0xFFFF
    hi = jnp.matmul(a, b_hi, preferred_element_type=jnp.int64)
    lo = jnp.matmul(a, b_lo, preferred_element_type=jnp.int64)
    return jnp.mod(jnp.mod(hi, p) * ((1 << 16) % p) + jnp.mod(lo, p), p)


#: Largest supported modulus (exclusive): residues must fit 31 bits so the
#: 16-bit limb split keeps every int64 intermediate exact.
MAX_MODULUS = 1 << 31


def modmatmul(a, b, p: int):
    """(a @ b) mod p for canonical int64 operands; p < 2^31.

    ``a`` is typically a small host-built scheme matrix ([n, m2] share or
    [k, r] reconstruct matrix), ``b`` the batch-column data [m2, B] with B
    huge — the MXU-shaped formulation of packed-Shamir share/reconstruct.
    """
    if p >= MAX_MODULUS:
        raise ValueError(f"modulus {p} >= 2^31 unsupported by limb modmatmul")
    k = b.shape[-2] if b.ndim >= 2 else b.shape[0]  # contraction axis
    if k * p * p < (1 << 62):
        return _modmatmul_direct(a, b, p)
    if k >= (1 << 15):
        raise ValueError(f"contraction size {k} too large for limb modmatmul")
    return _modmatmul_limb(a, b, p)


def uniform_mod(key, shape, m: int):
    """Uniform draws in [0, m) from threefry bits; m < 2^62.

    64 random bits reduced mod m: statistical distance from uniform is
    <= m / 2^64 (< 2^-33 for 31-bit moduli) — the TPU-native replacement for
    the reference's OsRng.gen_range (additive.rs:42-44, full.rs:25-27).
    """
    if not 0 < m < (1 << 62):
        raise ValueError(f"modulus {m} out of range for uniform_mod")
    bits = jax.random.bits(key, shape=shape + (2,), dtype=jnp.uint32)
    v = (bits[..., 0].astype(jnp.uint64) << jnp.uint64(32)) | bits[..., 1].astype(jnp.uint64)
    return jnp.mod(v, jnp.uint64(m)).astype(jnp.int64)


# ---------------------------------------------------------------------------
# NumPy mirrors (host oracle building blocks — bit-exact same algorithms)

def np_modmatmul(a: np.ndarray, b: np.ndarray, p: int) -> np.ndarray:
    if p >= MAX_MODULUS:
        raise ValueError(f"modulus {p} >= 2^31 unsupported by limb modmatmul")
    a = np.asarray(a, dtype=np.int64)
    b = np.asarray(b, dtype=np.int64)
    k = b.shape[-2] if b.ndim >= 2 else b.shape[0]  # contraction axis
    if k * p * p < (1 << 62):
        return np.matmul(a, b) % p
    if k >= (1 << 15):
        raise ValueError(f"contraction size {k} too large for limb modmatmul")
    hi = np.matmul(a, b >> 16)
    lo = np.matmul(a, b & 0xFFFF)
    return ((hi % p) * ((1 << 16) % p) + (lo % p)) % p


def np_modsum(x: np.ndarray, m: int, axis=0) -> np.ndarray:
    return np.sum(np.asarray(x, dtype=np.int64), axis=axis) % m
