"""Device-side modular arithmetic kernels (jnp, jit-friendly).

All arrays carry int64 values in canonical form [0, m). On TPU int64 is
emulated in int32 pairs and — crucially — XLA cannot lower an s64
``dot_general`` at all (the X64 rewrite is unimplemented for dot), so the
hot matmul (``modmatmul``) is formulated dot-free: a broadcast multiply +
reduction over the (always tiny: committee-sized) contraction axis, with
the modular reduction applied every ``group`` terms so emulated-s64
intermediates never overflow. XLA fuses the broadcast product into the
reduction, so the big operand streams from HBM once.

Overflow discipline (p < 2^31 enforced by schemes): products < p^2 < 2^62;
``group = (2^63 - 1) // p^2 >= 2`` terms are accumulated between
reductions, so partial sums stay < 2^63.

The reference computes the same algebra as scalar Rust loops over Vec<i64>
(client/src/crypto/sharing/*.rs); the canonical-form convention here differs
only by a final `positive()` lift (receive.rs:14-21) — values are congruent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def canon(x, m):
    """Canonical representative in [0, m) of any int64 residues."""
    return jnp.mod(x, m)


def modadd(a, b, m):
    return jnp.mod(a + b, m)


def modsub(a, b, m):
    return jnp.mod(a - b, m)


def modsum(x, m, axis=0):
    """Sum of canonical residues along ``axis`` mod m — THE clerk kernel
    (reference hot loop: sharing/combiner.rs:15-30).

    Exact for any m < 2^62 and any term count: when a flat int64 sum could
    wrap (n_terms * (m-1) >= 2^63, e.g. 8 shares of a 2^61 modulus), the
    reduction folds in chunks small enough that every partial sum provably
    fits, canonicalizing between levels. For m < 2^31 the fan exceeds any
    realistic axis and this is a single plain sum.
    """
    x = jnp.asarray(x, jnp.int64)
    n = x.shape[axis]
    fan = max(2, ((1 << 63) - 1) // max(1, int(m) - 1))
    if n <= fan:
        return jnp.mod(jnp.sum(x, axis=axis, dtype=jnp.int64), m)
    x = jnp.moveaxis(x, axis, 0)
    while x.shape[0] > 1:
        k = x.shape[0]
        chunk = min(fan, k)
        pad = (-k) % chunk
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], jnp.int64)], axis=0
            )
        x = x.reshape((x.shape[0] // chunk, chunk) + x.shape[1:])
        x = jnp.mod(jnp.sum(x, axis=1, dtype=jnp.int64), m)
    return x[0]


#: Largest supported modulus (exclusive): residues must fit 31 bits so
#: products fit s64 and at least two terms accumulate between reductions.
MAX_MODULUS = 1 << 31


def modmatmul(a, b, p: int):
    """(a @ b) mod p for canonical int64 operands; p < 2^31.

    ``a`` is typically a small host-built scheme matrix ([n, m2] share or
    [k, r] reconstruct matrix), ``b`` the batch-column data [..., m2, B]
    with B huge — the batched formulation of packed-Shamir
    share/reconstruct. Contraction runs as broadcast multiply + chunked
    modular sum (no dot: TPU cannot lower s64 dot_general); exact for any
    contraction size since partial sums are reduced every ``group`` terms.
    """
    if p >= MAX_MODULUS:
        raise ValueError(f"modulus {p} >= 2^31 unsupported by modmatmul")
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    a_vec, b_vec = a.ndim == 1, b.ndim == 1  # matmul vector promotion rules
    if a_vec:
        a = a[None, :]
    if b_vec:
        b = b[:, None]
    k = b.shape[-2]  # contraction axis
    group = max(1, ((1 << 63) - 1) // (p * p))
    # a: [..., n, k] -> [..., n, k, 1]; b: [..., k, B] -> [..., 1, k, B]
    a = a[..., :, :, None]
    b = b[..., None, :, :]
    if k <= group:
        out = jnp.mod(jnp.sum(a * b, axis=-2), p)
    else:
        acc = None
        for start in range(0, k, group):
            part = jnp.sum(
                a[..., start : start + group, :] * b[..., start : start + group, :],
                axis=-2,
            )
            acc = part if acc is None else acc + jnp.mod(part, p)
            acc = jnp.mod(acc, p)
        out = acc
    if a_vec:
        out = out[..., 0, :]
    if b_vec:
        out = out[..., 0]
    return out


def uniform_mod(key, shape, m: int):
    """Uniform draws in [0, m) from threefry bits; m < 2^62.

    64 random bits reduced mod m: statistical distance from uniform is
    <= m / 2^64 (< 2^-33 for 31-bit moduli) — the TPU-native replacement for
    the reference's OsRng.gen_range (additive.rs:42-44, full.rs:25-27).
    """
    if not 0 < m < (1 << 62):
        raise ValueError(f"modulus {m} out of range for uniform_mod")
    bits = jax.random.bits(key, shape=shape + (2,), dtype=jnp.uint32)
    v = (bits[..., 0].astype(jnp.uint64) << jnp.uint64(32)) | bits[..., 1].astype(jnp.uint64)
    return jnp.mod(v, jnp.uint64(m)).astype(jnp.int64)


# ---------------------------------------------------------------------------
# NumPy mirrors (host oracle building blocks — bit-exact same algorithms)

def np_modmatmul(a: np.ndarray, b: np.ndarray, p: int) -> np.ndarray:
    if p >= MAX_MODULUS:
        raise ValueError(f"modulus {p} >= 2^31 unsupported by modmatmul")
    a = np.asarray(a, dtype=np.int64)
    b = np.asarray(b, dtype=np.int64)
    k = b.shape[-2] if b.ndim >= 2 else b.shape[0]  # contraction axis
    group = max(1, ((1 << 63) - 1) // (p * p))
    if k * p * p < (1 << 63):
        return np.matmul(a, b) % p
    b_vec = b.ndim == 1
    if b_vec:
        b = b[:, None]
    acc = None
    for start in range(0, k, group):
        part = np.matmul(a[..., start : start + group], b[..., start : start + group, :])
        acc = part % p if acc is None else (acc + part % p) % p
    return acc[..., 0] if b_vec else acc


def np_modsum(x: np.ndarray, m: int, axis=0) -> np.ndarray:
    x = np.asarray(x, dtype=np.int64)
    n = x.shape[axis]
    fan = max(2, ((1 << 63) - 1) // max(1, int(m) - 1))
    if n <= fan:
        return np.sum(x, axis=axis) % m
    x = np.moveaxis(x, axis, 0)
    acc = np.zeros(x.shape[1:], dtype=np.int64)
    for start in range(0, n, fan):
        part = np.sum(x[start : start + fan], axis=0) % m
        acc = (acc + part) % m  # both canonical: sum < 2m < 2^63
    return acc
