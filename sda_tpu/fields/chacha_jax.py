"""Device-side ChaCha20 mask expansion (CHACHA_PRG_V1, bit-exact).

SURVEY.md hard part (e): the ChaCha-seed masking path must stay
wire-compatible while the recipient's mask re-expansion — the reference's
recipient hot loop, O(participants x dimension) PRG work
(client/src/receive.rs:102-118) — moves onto the TPU. ChaCha20 is pure
uint32 add/xor/rotate, ideal VPU work: all blocks advance through the 20
rounds in parallel lanes.

Bit-exactness with the host spec (fields.chacha) includes its *rejection
sampling*: a u64 draw above the acceptance zone shifts every later output.
Rejection is data-dependent and therefore unjittable — but its probability
is < modulus/2^64 (< 2^-35 per draw). So the device path expands without
rejection, simultaneously checks whether any of the first `dimension`
draws would have been rejected, and in that (practically never hit) case
the caller replays on the host oracle. Outputs are identical to
``chacha.expand_mask`` in every case.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import chacha
from .chacha import _CONSTANTS

_U32 = jnp.uint32


def _rotl(x, n: int):
    return (x << _U32(n)) | (x >> _U32(32 - n))


def _quarter(s, a, b, c, d):
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)


@functools.partial(jax.jit, static_argnames=("nblocks",))
def chacha_block_words(seed_words, counter0, *, nblocks: int):
    """[nblocks, 16] uint32 keystream; mirrors chacha.chacha_block_words.

    seed_words: [8] uint32 key (zero-padded); counter0: scalar int32/uint32.
    """
    counters = jnp.asarray(counter0, _U32) + jnp.arange(nblocks, dtype=_U32)
    zeros = jnp.zeros((nblocks,), _U32)
    init = (
        [jnp.full((nblocks,), _U32(c)) for c in _CONSTANTS]
        + [jnp.broadcast_to(seed_words[i], (nblocks,)).astype(_U32) for i in range(8)]
        + [counters, zeros, zeros, zeros]
    )
    state = list(init)
    for _ in range(10):
        _quarter(state, 0, 4, 8, 12)
        _quarter(state, 1, 5, 9, 13)
        _quarter(state, 2, 6, 10, 14)
        _quarter(state, 3, 7, 11, 15)
        _quarter(state, 0, 5, 10, 15)
        _quarter(state, 1, 6, 11, 12)
        _quarter(state, 2, 7, 8, 13)
        _quarter(state, 3, 4, 9, 14)
    words = [s + i for s, i in zip(state, init)]
    return jnp.stack(words, axis=1)  # [nblocks, 16]


@functools.partial(jax.jit, static_argnames=("dimension", "modulus", "prg"))
def _expand_no_reject(seed_words, *, dimension: int, modulus: int, prg: str):
    """(mask [dimension] int64, any_rejected bool) — fast path.

    ``prg`` selects the stream: CHACHA_PRG_V1 (word[2i] = low half, zone
    floor(2^64/m)*m inclusive-below) or CHACHA_PRG_RAND03 (rand 0.3's
    next_u64: word[2i] = HIGH half, zone u64::MAX - u64::MAX % m
    exclusive — see fields.chacha.expand_mask_rand03).
    """
    # match the host oracle's first-iteration overdraw: ceil(d/8)+1 blocks
    nblocks = max(1, -(-dimension // 8) + 1)
    words = chacha_block_words(seed_words, 0, nblocks=nblocks).reshape(-1)
    even = words[0::2].astype(jnp.uint64)
    odd = words[1::2].astype(jnp.uint64)
    if prg == chacha.CHACHA_PRG_RAND03:
        v = (even << jnp.uint64(32)) | odd
        u64_max = (1 << 64) - 1
        zone_excl = jnp.uint64(u64_max - u64_max % modulus)
        first = v[:dimension]
        any_rejected = jnp.any(first >= zone_excl)
    elif prg == chacha.CHACHA_PRG_V1:
        v = (odd << jnp.uint64(32)) | even
        zone = jnp.uint64(((1 << 64) // modulus) * modulus - 1)
        first = v[:dimension]
        any_rejected = jnp.any(first > zone)
    else:
        raise ValueError(f"unknown ChaCha PRG {prg!r}")
    mask = jnp.mod(first, jnp.uint64(modulus)).astype(jnp.int64)
    return mask, any_rejected


def stream_u64_at(seed_words, counter0, *, dimension: int):
    """[S, 8] uint32 seeds -> [S, dimension] uint64 stream draws starting at
    u64-draw offset ``counter0 * 8`` (``dimension % 8 == 0``).

    The windowed form of the CHACHA_PRG_V1 stream for dim-sharded pod mode:
    each ChaCha block yields 8 u64 draws, so a device holding the dim window
    [8*c0, 8*c0 + dimension) expands blocks [c0, c0 + dimension/8).
    ``counter0`` may be traced (it is ``axis_index('d') * blocks_per_shard``
    under shard_map). Pod mode reduces draws mod m WITHOUT the host spec's
    rejection step — masks cancel within the round, so the aggregate is
    exact regardless; only the federated wire path needs rejection parity.
    """
    if dimension % 8:
        raise ValueError("dimension must be a multiple of 8 (one ChaCha block)")
    nblocks = dimension // 8

    def one(sw):
        words = chacha_block_words(sw, counter0, nblocks=nblocks).reshape(-1)
        lo = words[0::2].astype(jnp.uint64)
        hi = words[1::2].astype(jnp.uint64)
        return (hi << jnp.uint64(32)) | lo

    return jax.vmap(one)(seed_words)


def _modsum_i64(x, modulus: int, axis: int = 0):
    """Overflow-safe modular sum of int64 residues in [0, modulus).

    A flat ``sum() % m`` wraps int64 once n*(m-1) >= 2^63 (e.g. ~16k seeds
    at a 2^49 modulus); fold in chunks small enough that every partial sum
    provably fits, canonicalizing between levels — same shape of fix as
    fastfield.modsum32.
    """
    fan = max(2, ((1 << 63) - 1) // max(1, modulus - 1))
    x = jnp.moveaxis(jnp.asarray(x, jnp.int64), axis, 0)
    if x.shape[0] == 0:  # empty sum is the zero mask, like jnp.sum(axis=0)
        return jnp.zeros(x.shape[1:], jnp.int64)
    while x.shape[0] > 1:
        n = x.shape[0]
        chunk = min(fan, n)
        pad = (-n) % chunk
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], jnp.int64)], axis=0
            )
        x = x.reshape((x.shape[0] // chunk, chunk) + x.shape[1:])
        x = jnp.mod(jnp.sum(x, axis=1, dtype=jnp.int64), modulus)
    return x[0]


@functools.partial(jax.jit, static_argnames=("dimension", "modulus", "prg"))
def _combine_no_reject(seed_matrix, *, dimension: int, modulus: int, prg: str):
    """[S, 8] seeds -> (sum of masks mod m [dimension] int64, [S] rejected)."""
    masks, rejected = jax.vmap(
        lambda sw: _expand_no_reject(
            sw, dimension=dimension, modulus=modulus, prg=prg
        )
    )(seed_matrix)
    total = _modsum_i64(masks, modulus, axis=0)
    return total, rejected


def combine_masks(
    seeds, dimension: int, modulus: int, *, prg: str
) -> np.ndarray:
    """Sum of all seeds' expanded masks mod m — the recipient hot loop
    (receive.rs:102-118), every seed's 20-round expansion in parallel lanes.
    Bit-identical to summing the host expansion (``prg``-selected) per seed.
    ``prg`` is required: a defaulted stream choice could silently expand the
    wrong stream for a wire seed."""
    if modulus <= 0 or modulus >= (1 << 62):
        raise ValueError("modulus out of range")
    if prg not in chacha._EXPANDERS:
        raise ValueError(f"unknown ChaCha PRG {prg!r}")
    seed_matrix = np.zeros((len(seeds), 8), dtype=np.uint32)
    for i, seed in enumerate(seeds):
        if len(seed) > 8:
            raise ValueError("seed longer than 256 bits")
        for j, w in enumerate(seed):
            seed_matrix[i, j] = np.uint32(int(w) & 0xFFFFFFFF)
    total, rejected = _combine_no_reject(
        jnp.asarray(seed_matrix), dimension=dimension, modulus=modulus, prg=prg
    )
    rejected = np.asarray(rejected)
    if rejected.any():  # replay the affected seeds exactly on the host
        total = np.asarray(total, dtype=np.int64)
        for i in np.nonzero(rejected)[0]:
            seed = [int(w) for w in seeds[i]]
            wrong, _ = _expand_no_reject(
                jnp.asarray(seed_matrix[i]), dimension=dimension,
                modulus=modulus, prg=prg,
            )
            right = chacha.expand_mask_for(prg, seed, dimension, modulus)
            total = (total - np.asarray(wrong) + right) % modulus
        return total
    return np.asarray(total)


def expand_mask(
    seed: Sequence[int], dimension: int, modulus: int, *, prg: str
) -> np.ndarray:
    """Drop-in device-accelerated chacha.expand_mask / expand_mask_rand03
    (bit-identical to the ``prg``-selected host expansion; ``prg`` required
    for the same reason as combine_masks)."""
    if modulus <= 0 or modulus >= (1 << 62):
        raise ValueError("modulus out of range")
    if prg not in chacha._EXPANDERS:
        raise ValueError(f"unknown ChaCha PRG {prg!r}")
    if len(seed) > 8:
        raise ValueError("seed longer than 256 bits")
    seed_words = np.zeros(8, dtype=np.uint32)
    for i, w in enumerate(seed):
        seed_words[i] = np.uint32(w & 0xFFFFFFFF)
    mask, any_rejected = _expand_no_reject(
        jnp.asarray(seed_words), dimension=dimension, modulus=modulus, prg=prg
    )
    if bool(any_rejected):  # p < dimension * modulus / 2^64 — practically never
        return chacha.expand_mask_for(prg, seed, dimension, modulus)
    return np.asarray(mask)
