"""L1a: the TPU math core — Z_m/Z_p kernels, scheme matrices, PRGs."""

from . import chacha, numtheory, oracle
from .modular import (
    canon,
    modadd,
    modmatmul,
    modsub,
    modsum,
    np_modmatmul,
    np_modsum,
    uniform_mod,
)
from .sharing import (
    additive_share,
    additive_share_from_randomness,
    batch_columns,
    combine,
    packed_reconstruct,
    packed_share,
    packed_share_from_randomness,
    unbatch_columns,
)
