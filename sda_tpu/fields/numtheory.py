"""Host-side exact number theory: primes, roots of unity, NTT/Lagrange matrices.

Everything here runs in Python integers (exact, no overflow) and is cheap:
matrices are committee-sized (tens of rows), built once per scheme and cached.
The *device* side (``sda_tpu.fields.modular``) then applies them as batched
modular matmuls over millions of batch columns — that split is the central
TPU-first design decision: polynomial evaluation/interpolation of the packed
Shamir scheme (reference: external crate ``threshold-secret-sharing`` 0.2,
used via client/src/crypto/sharing/packed_shamir.rs:13-44) becomes a single
``[n, m2] @ [m2, B]`` matmul on the MXU instead of per-batch FFTs.

Scheme structure (reference protocol/src/crypto.rs:98-113):
- ``omega_secrets`` has power-of-2 order ``m2 = secret_count + privacy_threshold + 1``;
- ``omega_shares`` has power-of-3 order ``m3 = share_count + 1``;
- the share polynomial is the unique degree < m2 polynomial through
  ``(1, 0), (omega_secrets^1, secret_1), ..., (omega_secrets^k, secret_k),
  (omega_secrets^{k+1}, r_1), ...``;
- share i (1-based) is its value at ``omega_shares^i``.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# Primality and roots

_SMALL_PRIMES = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37]


def is_prime(n: int) -> bool:
    """Deterministic Miller-Rabin for n < 3.3e24 (covers all i64)."""
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n % p == 0:
            return n == p
    d, s = n - 1, 0
    while d % 2 == 0:
        d //= 2
        s += 1
    for a in _SMALL_PRIMES:
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(s - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def mod_inv(a: int, p: int) -> int:
    return pow(a % p, p - 2, p)


def element_of_order(order: int, p: int) -> int:
    """Find an element of exact multiplicative order ``order`` in Z_p*."""
    if (p - 1) % order != 0:
        raise ValueError(f"{order} does not divide p-1={p - 1}")
    # factor `order` (orders here are 2^a * 3^b, tiny)
    factors = set()
    o = order
    for f in (2, 3):
        while o % f == 0:
            factors.add(f)
            o //= f
    if o != 1:
        d = 2
        while d * d <= o:
            while o % d == 0:
                factors.add(d)
                o //= d
            d += 1
        if o > 1:
            factors.add(o)
    for g in range(2, p):
        w = pow(g, (p - 1) // order, p)
        if all(pow(w, order // f, p) != 1 for f in factors):
            return w
    raise ValueError("no element of requested order found")


def next_power(base: int, minimum: int) -> int:
    v = 1
    while v < minimum:
        v *= base
    return v


def find_prime_with_orders(order2: int, order3: int, min_bits: int = 0) -> int:
    """A prime p >= 2^min_bits with order2*order3 | p-1 (orders coprime).

    Prefers Solinas-form primes (p = 2^b - small delta) so device rounds hit
    the uint32 fast path (``fields.fastfield``); falls back to the smallest
    qualifying prime otherwise.
    """
    from . import fastfield

    step = order2 * order3
    # p = 2^b - delta >= 2^min_bits needs b > min_bits; fastfield caps b at 29
    for b in range(max(min_bits + 1, 20), 30):
        for delta in range(1, 1 << 13):
            p = (1 << b) - delta
            # step == 1 (no order constraints, e.g. BasicShamir primes) is
            # trivially satisfied; p % 1 == 0 would otherwise skip every
            # candidate and silently lose the Solinas fast path
            if p < (1 << min_bits) or (step > 1 and p % step != 1):
                continue
            if fastfield.supported(p) and is_prime(p):
                return p
    c = max(1, ((1 << min_bits) - 1) // step)
    while True:
        p = c * step + 1
        if p.bit_length() > 31:
            raise ValueError("no suitable prime below 2^31 (device kernel limit)")
        if p >= (1 << min_bits) and is_prime(p):
            return p
        c += 1


def validate_packed_scheme(secret_count, share_count, privacy_threshold,
                           prime_modulus, omega_secrets, omega_shares) -> None:
    """Check the algebraic preconditions of a PackedShamir parameter set."""
    m2 = secret_count + privacy_threshold + 1
    m3 = share_count + 1
    if m2 & (m2 - 1):
        raise ValueError(f"secret_count+privacy_threshold+1={m2} must be a power of 2")
    n3 = m3
    while n3 % 3 == 0:
        n3 //= 3
    if n3 != 1:
        raise ValueError(f"share_count+1={m3} must be a power of 3")
    if not is_prime(prime_modulus):
        raise ValueError(f"{prime_modulus} is not prime")
    if prime_modulus >= (1 << 31):
        raise ValueError(
            f"prime modulus {prime_modulus} >= 2^31: residues must fit 31 bits "
            "for the device limb kernels to stay exact"
        )
    p = prime_modulus
    if pow(omega_secrets, m2, p) != 1 or pow(omega_secrets, m2 // 2, p) == 1:
        raise ValueError("omega_secrets does not have exact order m2")
    if pow(omega_shares, m3, p) != 1 or pow(omega_shares, m3 // 3, p) == 1:
        raise ValueError("omega_shares does not have exact order m3")


def generate_packed_params(
    secret_count: int, share_count: int, min_modulus_bits: int = 0
) -> Tuple[int, int, int, int]:
    """Choose (privacy_threshold, prime, omega_secrets, omega_shares).

    ``share_count + 1`` must be a power of 3 (2, 8, 26, 80, ... clerks);
    the privacy threshold is maximised under the power-of-2 constraint:
    t = next_pow2(secret_count+2) - secret_count - 1 at least 1.
    Mirrors the parameter discipline tss users had to follow by hand.
    """
    m3 = share_count + 1
    v = m3
    while v % 3 == 0:
        v //= 3
    if v != 1:
        raise ValueError("share_count must be 3^a - 1 (2, 8, 26, 80, ...)")
    m2 = next_power(2, secret_count + 2)
    t = m2 - secret_count - 1
    if t >= share_count:
        raise ValueError(
            f"derived privacy threshold {t} >= share_count {share_count}; "
            "use more clerks or fewer packed secrets"
        )
    p = find_prime_with_orders(m2, m3, min_modulus_bits)
    w2 = element_of_order(m2, p)
    w3 = element_of_order(m3, p)
    return t, p, w2, w3


# ---------------------------------------------------------------------------
# Matrix builders (exact, host-side, cached per scheme)

def _ntt_matrix(omega: int, n: int, p: int) -> List[List[int]]:
    """V[i][j] = omega^(i*j) mod p — evaluation at the omega^i points."""
    pow_cache = [pow(omega, e, p) for e in range(n)]
    return [[pow_cache[(i * j) % n] for j in range(n)] for i in range(n)]


def _intt_matrix(omega: int, n: int, p: int) -> List[List[int]]:
    """Inverse NTT: (1/n) * omega^(-i*j); values at omega^i -> coefficients."""
    n_inv = mod_inv(n, p)
    w_inv = mod_inv(omega, p)
    pow_cache = [pow(w_inv, e, p) for e in range(n)]
    return [[n_inv * pow_cache[(i * j) % n] % p for j in range(n)] for i in range(n)]


@functools.lru_cache(maxsize=64)
def packed_share_matrix(
    secret_count: int,
    share_count: int,
    privacy_threshold: int,
    prime_modulus: int,
    omega_secrets: int,
    omega_shares: int,
) -> np.ndarray:
    """The [share_count, m2] matrix M with shares = M @ values (mod p).

    values = column vector [0; secrets (k); randomness (t)] — the polynomial's
    values at 1, omega_secrets^1..^{k+t}. M composes the inverse NTT (values ->
    coefficients, degree < m2) with evaluation at omega_shares^1..^n
    (coefficients zero-padded to m3). Share j (0-based row) is the value at
    omega_shares^{j+1}; the value at omega_shares^0 = 1 is the fixed 0 and is
    not a share.
    """
    validate_packed_scheme(secret_count, share_count, privacy_threshold,
                           prime_modulus, omega_secrets, omega_shares)
    p = prime_modulus
    m2 = secret_count + privacy_threshold + 1
    m3 = share_count + 1
    inv = _intt_matrix(omega_secrets, m2, p)          # [m2, m2]
    ev = _ntt_matrix(omega_shares, m3, p)             # [m3, m3]
    # compose: rows 1..m3-1 of (ev[:, :m2] @ inv)
    M = [
        [
            sum(ev[i][c] * inv[c][j] for c in range(m2)) % p
            for j in range(m2)
        ]
        for i in range(1, m3)
    ]
    out = np.array(M, dtype=np.int64)
    out.setflags(write=False)  # cached and shared; callers must not mutate
    return out


@functools.lru_cache(maxsize=64)
def basic_share_matrix(
    share_count: int, privacy_threshold: int, prime_modulus: int
) -> np.ndarray:
    """The [share_count, 2+t] matrix M with shares = M @ values (mod p) for
    classic Shamir (protocol BasicShamirSharing; reference declaration
    crypto.rs:89-95).

    values = [0 (fixed, keeps the packed-layout convention); secret;
    t random coefficients]. Share i (0-based row) is f(i+1) for
    f(x) = secret + sum_j r_j x^j — so M[i] = [0, 1, x_i, ..., x_i^t] with
    x_i = i + 1. No root-of-unity structure needed: any prime >
    share_count works (points 1..n stay distinct and nonzero).
    """
    n, t, p = share_count, privacy_threshold, prime_modulus
    if not 1 <= t < n:
        raise ValueError(f"privacy threshold {t} must be in [1, {n})")
    if p <= n:
        raise ValueError(f"prime {p} must exceed share_count {n}")
    M = [[0, 1] + [pow(i + 1, j, p) for j in range(1, t + 1)]
         for i in range(n)]
    out = np.array(M, dtype=np.int64)
    out.setflags(write=False)  # cached and shared; callers must not mutate
    return out


@functools.lru_cache(maxsize=256)
def basic_reconstruct_matrix(
    share_count: int, privacy_threshold: int, prime_modulus: int,
    indices: Tuple[int, ...],
) -> np.ndarray:
    """The [1, len(indices)+1] matrix L with [secret] = L @ [0; shares]:
    Lagrange interpolation at zero through points {i+1 for i in indices}.
    Any ``privacy_threshold + 1`` of the shares suffice; interpolating
    through a superset of surviving points yields the same degree-<=t
    polynomial, so larger sets stay exact."""
    n, t, p = share_count, privacy_threshold, prime_modulus
    if len(set(indices)) != len(indices):
        raise ValueError("duplicate share indices")
    if any(i < 0 or i >= n for i in indices):
        raise ValueError("share index out of range")
    if len(indices) < t + 1:
        raise ValueError(
            f"need at least {t + 1} shares to reconstruct, got {len(indices)}"
        )
    points = [i + 1 for i in indices]
    row = _lagrange_basis_row(points, 0, p)
    out = np.array([[0] + row], dtype=np.int64)
    out.setflags(write=False)
    return out


def share_matrix_for(scheme) -> np.ndarray:
    """Scheme-dispatched share matrix (PackedShamir | BasicShamir)."""
    if hasattr(scheme, "omega_secrets"):
        return packed_share_matrix(
            scheme.secret_count, scheme.share_count, scheme.privacy_threshold,
            scheme.prime_modulus, scheme.omega_secrets, scheme.omega_shares,
        )
    return basic_share_matrix(
        scheme.share_count, scheme.privacy_threshold, scheme.prime_modulus
    )


def reconstruct_matrix_for(scheme, indices: Tuple[int, ...]) -> np.ndarray:
    """Scheme-dispatched reconstruction matrix for surviving ``indices``."""
    if hasattr(scheme, "omega_secrets"):
        return packed_reconstruct_matrix(
            scheme.secret_count, scheme.share_count, scheme.privacy_threshold,
            scheme.prime_modulus, scheme.omega_secrets, scheme.omega_shares,
            tuple(indices),
        )
    return basic_reconstruct_matrix(
        scheme.share_count, scheme.privacy_threshold, scheme.prime_modulus,
        tuple(indices),
    )


def _lagrange_basis_row(points: Sequence[int], x: int, p: int) -> List[int]:
    """Lagrange basis weights l_j(x) for interpolation points ``points``."""
    n = len(points)
    row = []
    for j in range(n):
        num, den = 1, 1
        for m in range(n):
            if m == j:
                continue
            num = num * ((x - points[m]) % p) % p
            den = den * ((points[j] - points[m]) % p) % p
        row.append(num * mod_inv(den, p) % p)
    return row


@functools.lru_cache(maxsize=256)
def packed_reconstruct_matrix(
    secret_count: int,
    share_count: int,
    privacy_threshold: int,
    prime_modulus: int,
    omega_secrets: int,
    omega_shares: int,
    indices: Tuple[int, ...],
) -> np.ndarray:
    """The [secret_count, len(indices)+1] matrix L with secrets = L @ values.

    ``indices`` are surviving 0-based share indices (clerk committee
    positions); share i sits at point omega_shares^{i+1}. values = [0;
    shares at indices] — the leading zero is the implicit point-1 value, so
    column 0 multiplies 0 and exists only to keep the matmul uniform.
    Interpolates through ALL supplied points (any superset of a reconstructing
    set yields the same polynomial) and evaluates at omega_secrets^1..^k.
    Fault tolerance: any ``privacy_threshold + secret_count`` of the
    ``share_count`` shares suffice (crypto.rs:146-153).
    """
    p = prime_modulus
    k = secret_count
    if len(set(indices)) != len(indices):
        raise ValueError("duplicate share indices")
    if any(i < 0 or i >= share_count for i in indices):
        raise ValueError("share index out of range")
    if len(indices) < privacy_threshold + secret_count:
        raise ValueError(
            f"need at least {privacy_threshold + secret_count} shares to "
            f"reconstruct, got {len(indices)}"
        )
    points = [1] + [pow(omega_shares, i + 1, p) for i in indices]
    targets = [pow(omega_secrets, e, p) for e in range(1, k + 1)]
    L = [_lagrange_basis_row(points, x, p) for x in targets]
    out = np.array(L, dtype=np.int64)
    out.setflags(write=False)  # cached and shared; callers must not mutate
    return out
