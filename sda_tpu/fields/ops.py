"""Uniform field-kernel interface over the two device arithmetic paths.

Every aggregation-round body needs the same eight operations (canonicalize,
add, sub, axis-sum, uniform draws, matrix contraction, u64 reduction,
int64 export) in one of two implementations:

- the **uint32 Solinas fast path** (`fastfield`): canonical residues in
  uint32 lanes, shift/add reduction — for moduli of form 2^b - delta;
- the **generic int64 path** (`modular`): any modulus < 2^31 (matmul) or
  < 2^62 (elementwise), emulated 64-bit lanes on TPU.

``FieldOps.create`` picks the fast path when the modulus qualifies AND the
caller's cross-device sums provably fit uint32 (``cross_terms`` = the
maximum residues summed by a collective before the next canonicalize).
Results are bit-identical between paths (tests/test_fastfield.py); only
speed and dtype differ. The adapter collapses what used to be duplicated
``_local_round``/``_local_round_fast`` bodies in mesh.simpod.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import fastfield, modular


class FieldOps:
    """Field/ring ops mod ``m``; ``sp`` non-None selects the uint32 path.

    Note additive sharing only needs ring structure, so a *composite*
    Solinas-form modulus still rides the fast path — none of these ops
    divide. The packed-Shamir matmuls (which do need a prime) dispatch in
    mesh.simpod's share/reconstruct stages, not here.
    """

    __slots__ = ("m", "sp", "dtype")

    def __init__(self, m: int, sp: Optional[fastfield.SolinasPrime]):
        self.m = int(m)
        self.sp = sp
        self.dtype = jnp.uint32 if sp is not None else jnp.int64

    @classmethod
    def create(cls, modulus: int, *, cross_terms: int = 1) -> "FieldOps":
        sp = fastfield.SolinasPrime.try_from(modulus)
        if sp is not None and cross_terms * (modulus - 1) >= (1 << 32):
            sp = None  # collective partial sums could wrap uint32
        return cls(modulus, sp)

    # -- conversions ------------------------------------------------------
    def to_residues(self, inputs):
        """Any-integer inputs -> canonical residues in the working dtype."""
        if self.sp is not None:
            return fastfield.to_residues32(inputs, self.sp)
        return modular.canon(jnp.asarray(inputs, jnp.int64), self.m)

    def to_int64(self, x):
        return x.astype(jnp.int64)

    def from_u64(self, v):
        """uint64 stream draws -> canonical residues (no-reject reduction)."""
        r = jnp.mod(v, jnp.uint64(self.m))
        return r.astype(self.dtype)

    # -- arithmetic -------------------------------------------------------
    def canon(self, x):
        if self.sp is not None:
            return fastfield.canon32(x, self.sp)
        return modular.canon(x, self.m)

    def add(self, a, b):
        if self.sp is not None:
            return fastfield.modadd32(a, b, self.sp)
        return modular.modadd(a, b, self.m)

    def sub(self, a, b):
        if self.sp is not None:
            return fastfield.modsub32(a, b, self.sp)
        return modular.modsub(a, b, self.m)

    def sum(self, x, axis=0):
        if self.sp is not None:
            return fastfield.modsum32(x, self.sp, axis=axis)
        return modular.modsum(x, self.m, axis=axis)

    def uniform(self, key, shape):
        if self.sp is not None:
            return fastfield.uniform32(key, shape, self.sp)
        return modular.uniform_mod(key, tuple(shape), self.m)
