"""CPU oracle: numpy re-implementation of every field kernel, bit-exact.

The reference's math lives in scalar Rust loops (client/src/crypto/sharing/*,
the tss crate); this oracle mirrors those semantics in plain numpy so device
kernels can be asserted identical given identical randomness — the test
discipline SURVEY.md §4 calls out as missing upstream (sharing kernels there
are only covered via full-loop integration).

Outputs are canonical residues [0, m); the reference's possibly-negative
representatives (Rust `%` keeps sign, additive.rs:46-48) are congruent and
equal after the `positive()` lift (receive.rs:14-21).
"""

from __future__ import annotations

import numpy as np

from .modular import np_modmatmul, np_modsum
from . import numtheory


def batch_columns(secrets: np.ndarray, input_size: int) -> np.ndarray:
    d = secrets.shape[-1]
    B = -(-d // input_size)
    padded = np.zeros(secrets.shape[:-1] + (B * input_size,), dtype=np.int64)
    padded[..., :d] = secrets
    return np.moveaxis(padded.reshape(secrets.shape[:-1] + (B, input_size)), -1, -2)


def unbatch_columns(batched: np.ndarray, dimension: int) -> np.ndarray:
    out = np.moveaxis(batched, -2, -1)
    out = out.reshape(out.shape[:-2] + (-1,))
    return out[..., :dimension]


def additive_share_from_randomness(secrets, draws, modulus: int) -> np.ndarray:
    """[d] secrets + [n-1, d] draws -> [n, d] shares (additive.rs:32-52)."""
    secrets = np.asarray(secrets, dtype=np.int64)
    draws = np.asarray(draws, dtype=np.int64)
    last = (secrets - np_modsum(draws, modulus, axis=-2)) % modulus
    return np.concatenate([draws, last[..., None, :]], axis=-2)


def combine(shares, modulus: int) -> np.ndarray:
    # % first: np_modsum's overflow-exact fan assumes canonical residues,
    # and callers may feed unreduced values (e.g. Paillier-premixed sums).
    return np_modsum(np.asarray(shares, dtype=np.int64) % modulus, modulus, axis=0)


def packed_share_from_randomness(secrets, randomness, scheme) -> np.ndarray:
    """[d] secrets + [t, B] randomness -> [n, B] clerk share rows."""
    M = numtheory.share_matrix_for(scheme)
    sk = batch_columns(np.asarray(secrets, dtype=np.int64), scheme.secret_count)
    zeros = np.zeros(sk.shape[:-2] + (1,) + sk.shape[-1:], dtype=np.int64)
    values = np.concatenate([zeros, sk, np.asarray(randomness, dtype=np.int64)], axis=-2)
    return np_modmatmul(M, values, scheme.prime_modulus)


def packed_reconstruct(indices, shares, scheme, dimension: int) -> np.ndarray:
    """Surviving (indices, [r, B] share rows) -> [d] secrets."""
    L = numtheory.reconstruct_matrix_for(scheme, tuple(indices))
    shares = np.asarray(shares, dtype=np.int64)
    values = np.concatenate([np.zeros((1,) + shares.shape[1:], dtype=np.int64), shares], axis=0)
    return unbatch_columns(np_modmatmul(L, values, scheme.prime_modulus), dimension)
