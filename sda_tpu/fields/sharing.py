"""Device kernels for secret sharing: additive and packed Shamir.

The reference's batching layer (client/src/crypto/sharing/batched.rs:18-99)
chunks a d-vector into ceil(d/k) batches of k secrets, shares each batch,
and transposes shares per clerk. Here that whole layer is a reshape: the
batch axis becomes the matmul's column axis, so sharing a participant's
vector is ONE [n, m2] @ [m2, B] modular matmul and reconstruction is ONE
[k, r+1] @ [r+1, B] matmul — MXU-shaped, vmap-able over participants.

Functions are jit-compiled with scheme parameters static; canonical residues
[0, m) throughout (congruent to the reference's signed representatives, cf.
receive.rs:14-21 `positive()`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import fastfield
from ..obs import devprof
from .modular import modmatmul, modsub, modsum, uniform_mod


def batch_columns(secrets, input_size: int):
    """[d] -> [input_size, B] column-per-batch layout (zero-padded).

    Batch b holds secrets[b*k:(b+1)*k] (batched.rs:18-53 semantics).
    """
    d = secrets.shape[-1]
    B = -(-d // input_size)
    padded = jnp.zeros(secrets.shape[:-1] + (B * input_size,), secrets.dtype)
    padded = padded.at[..., :d].set(secrets)
    return jnp.moveaxis(
        padded.reshape(secrets.shape[:-1] + (B, input_size)), -1, -2
    )


def unbatch_columns(batched, dimension: int):
    """[k, B] -> [d], inverse of batch_columns (truncates padding)."""
    out = jnp.moveaxis(batched, -2, -1)
    out = out.reshape(out.shape[:-2] + (-1,))
    return out[..., :dimension]


# ---------------------------------------------------------------------------
# Additive sharing (reference: client/src/crypto/sharing/additive.rs)

@functools.partial(jax.jit, static_argnames=("modulus",))
def additive_share_from_randomness(secrets, draws, *, modulus: int):
    """[..., d] secrets + [..., n-1, d] draws -> [..., n, d] shares.

    Last share is secret minus the sum of the draws (additive.rs:32-52);
    split out so the CPU oracle can be fed identical randomness.
    """
    last = modsub(secrets, modsum(draws, modulus, axis=-2), modulus)
    return jnp.concatenate([draws, last[..., None, :]], axis=-2)


# devprof compiled-shape registry on the jit entry points: calls from
# inside an outer trace (the pod/streamed programs) pass through uncounted
# under a named scope; top-level calls (the federated client path) count
additive_share_from_randomness = devprof.instrument(
    "fields.additive_share", additive_share_from_randomness)


def additive_share(key, secrets, *, share_count: int, modulus: int):
    """[..., d] secrets -> [..., n, d] shares with fresh threefry draws."""
    d = secrets.shape[-1]
    draws = uniform_mod(key, secrets.shape[:-1] + (share_count - 1, d), modulus)
    return additive_share_from_randomness(secrets, draws, modulus=modulus)


@functools.partial(jax.jit, static_argnames=("modulus",))
def combine(shares, *, modulus: int):
    """Elementwise modular sum across the leading axis — the clerk hot kernel
    (combiner.rs:15-30) and the additive reconstructor (additive.rs:55-73)."""
    return modsum(shares, modulus, axis=0)


combine = devprof.instrument("fields.combine", combine)


# ---------------------------------------------------------------------------
# Packed Shamir (reference: packed_shamir.rs via the tss crate; matrices
# built host-side in sda_tpu.fields.numtheory)

@functools.partial(jax.jit, static_argnames=("prime", "secret_count"), donate_argnums=())
def packed_share_from_randomness(secrets, randomness, share_matrix, *, prime: int,
                                 secret_count: int):
    """Share [..., d] secrets given explicit [..., t, B] randomness.

    values column = [0; k secrets; t randomness]; shares = M @ values.
    Split out so the CPU oracle can be fed identical randomness for
    bit-exactness tests.
    """
    sk = batch_columns(secrets, secret_count)                    # [..., k, B]
    zeros = jnp.zeros(sk.shape[:-2] + (1,) + sk.shape[-1:], sk.dtype)
    values = jnp.concatenate([zeros, sk, randomness], axis=-2)   # [..., m2, B]
    return modmatmul(share_matrix, values, prime)                # [..., n, B]


packed_share_from_randomness = devprof.instrument(
    "fields.packed_share", packed_share_from_randomness)


def packed_share(key, secrets, share_matrix, *, prime: int, secret_count: int,
                 privacy_threshold: int):
    """Share with fresh threefry randomness; returns [..., n, B] clerk rows."""
    d = secrets.shape[-1]
    B = -(-d // secret_count)
    randomness = uniform_mod(
        key, secrets.shape[:-1] + (privacy_threshold, B), prime
    )
    return packed_share_from_randomness(
        secrets, randomness, share_matrix, prime=prime, secret_count=secret_count
    )


# ---------------------------------------------------------------------------
# uint32 Solinas fast variants (fields.fastfield) — same algebra, same
# results, ~half the HBM bytes and no emulated-s64 ops. Matrices stay
# host-side numpy so limb decomposition happens at trace time.

def packed_share32(key, secrets32, share_matrix_host, sp: "fastfield.SolinasPrime",
                   *, secret_count: int, privacy_threshold: int):
    """Canonical uint32 [..., d] secrets -> [..., n, B] canonical shares."""
    d = secrets32.shape[-1]
    B = -(-d // secret_count)
    randomness = fastfield.uniform32(
        key, secrets32.shape[:-1] + (privacy_threshold, B), sp
    )
    sk = batch_columns(secrets32, secret_count)                  # [..., k, B]
    zeros = jnp.zeros(sk.shape[:-2] + (1,) + sk.shape[-1:], sk.dtype)
    values = jnp.concatenate([zeros, sk, randomness], axis=-2)   # [..., m2, B]
    return fastfield.modmatmul32(share_matrix_host, values, sp)  # [..., n, B]


def packed_reconstruct32(shares32, recon_matrix_host, sp: "fastfield.SolinasPrime",
                         *, dimension: int):
    """[r, B] canonical uint32 clerk rows -> [d] canonical secrets."""
    zeros = jnp.zeros((1,) + shares32.shape[1:], shares32.dtype)
    values = jnp.concatenate([zeros, shares32], axis=0)          # [r+1, B]
    secrets = fastfield.modmatmul32(recon_matrix_host, values, sp)
    return unbatch_columns(secrets, dimension)


@functools.partial(jax.jit, static_argnames=("prime", "dimension"))
def packed_reconstruct(shares, recon_matrix, *, prime: int, dimension: int):
    """[r, B] surviving clerk share rows -> [d] secrets.

    recon_matrix is built for the surviving index set
    (numtheory.packed_reconstruct_matrix); the implicit point-1 zero row is
    prepended here.
    """
    zeros = jnp.zeros((1,) + shares.shape[1:], shares.dtype)
    values = jnp.concatenate([zeros, shares], axis=0)            # [r+1, B]
    secrets = modmatmul(recon_matrix, values, prime)             # [k, B]
    return unbatch_columns(secrets, dimension)


packed_reconstruct = devprof.instrument(
    "fields.packed_reconstruct", packed_reconstruct)
