"""uint32 Solinas-prime field kernels — the TPU fast path.

TPU has no native 64-bit integers: every s64 op XLA emulates costs several
s32 VPU ops, and s64 arrays burn double HBM bandwidth. The generic kernels
in ``modular.py`` pay both. This module removes them for primes of Solinas
form

    p = 2^b - delta,   20 <= b <= 29,   delta < 2^14,

where reduction is shift/add (``2^b ≡ delta (mod p)``) and every
intermediate provably fits uint32:

- values are canonical residues < p < 2^29 held in uint32 (HALF the bytes);
- ``v mod p`` for any v < 2^32 is ``q = v >> b; v - q*p`` (+ one
  conditional subtract), ~3 VPU ops — no 64-bit magic-multiply sequence;
- products a*b split into 15-bit limbs: 4 uint32 multiplies whose scale
  streams (2^30, 2^15, 1) recombine through the Solinas congruence with
  every partial sum < 2^32 (bounds in ``modmatmul32``).

``generate_packed_params`` prefers such primes, so packed-Shamir rounds hit
this path; arbitrary primes (e.g. the reference's p=433 conformance vector)
keep the generic ``modular.py`` kernels — results are bit-identical either
way (tests/test_fastfield.py checks against the NumPy oracle).

Reference semantics being accelerated: the share/clerk/reconstruct loops of
client/src/crypto/sharing/*.rs (see modular.py / SURVEY.md §2.2).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32
_LOW = 15  # low-limb width: limbs < 2^15 keep 15x15-bit products < 2^30


class SolinasPrime:
    """Parameter pack for p = 2^b - delta; ``try_from`` gates eligibility."""

    __slots__ = ("p", "b", "delta")

    def __init__(self, p: int, b: int, delta: int):
        self.p = p
        self.b = b
        self.delta = delta

    @staticmethod
    def try_from(p: int) -> Optional["SolinasPrime"]:
        b = p.bit_length()
        delta = (1 << b) - p
        if not (20 <= b <= 29):
            return None
        if delta >= (1 << 14):
            return None
        # canon32 does ONE conditional subtract after _reduce; its input
        # r < 2^b + (2^(32-b))*delta must stay < 2p
        if delta * (1 + (1 << (32 - b))) >= p:
            return None
        return SolinasPrime(p, b, delta)

    def __repr__(self):
        return f"SolinasPrime(2^{self.b} - {self.delta})"


def supported(p: int) -> bool:
    return SolinasPrime.try_from(p) is not None


# ---------------------------------------------------------------------------
# Scalar helpers (all uint32 lanes; sp.* are Python ints => XLA constants)

def _reduce(v, sp: SolinasPrime):
    """v < 2^32  ->  r ≡ v (mod p), r < p + 8*delta (< 2p)."""
    q = v >> np.uint32(sp.b)
    return v - q * np.uint32(sp.p)


def canon32(v, sp: SolinasPrime):
    """v < 2^32 -> canonical residue in [0, p)."""
    r = _reduce(jnp.asarray(v, _U32), sp)
    return jnp.where(r >= np.uint32(sp.p), r - np.uint32(sp.p), r)


def to_residues32(inputs, sp: SolinasPrime):
    """Any-integer inputs -> canonical uint32 residues mod p.

    uint32/int32 non-negative inputs skip the 64-bit pass entirely.
    """
    inputs = jnp.asarray(inputs)
    if inputs.dtype == jnp.uint32:
        return canon32(inputs, sp)
    if inputs.dtype == jnp.int32:
        bits = inputs.astype(jnp.uint32)  # two's complement: negatives ≡ v + 2^32
        r = canon32(bits, sp)
        r32 = jnp.uint32((1 << 32) % sp.p)
        return jnp.where(inputs < 0, modsub32(r, r32, sp), r)
    return jnp.mod(inputs.astype(jnp.int64), sp.p).astype(jnp.uint32)


def modadd32(a, b, sp: SolinasPrime):
    """Canonical a, b -> canonical a+b (sum < 2p < 2^30)."""
    s = a + b
    return jnp.where(s >= np.uint32(sp.p), s - np.uint32(sp.p), s)


def modsub32(a, b, sp: SolinasPrime):
    """Canonical a, b -> canonical a-b (uint32 wraparound + correction)."""
    d = a - b
    # underflow iff b > a: wrapped value >= 2^32 - p > p, add p back
    return jnp.where(a >= b, d, d + np.uint32(sp.p))


def _compose(t1, t0, sp: SolinasPrime):
    """t1*2^15 + t0 mod p -> canonical, for t1 < 2^31, t0 < 2^31."""
    t1 = canon32(t1, sp)                                     # < p < 2^b
    t1h = t1 >> np.uint32(sp.b - _LOW)                       # < 2^15
    t1l = t1 & np.uint32((1 << (sp.b - _LOW)) - 1)           # < 2^(b-15)
    # t1*2^15 = t1h*2^b + t1l*2^15 ≡ t1h*delta + t1l*2^15
    v = t0 + t1h * np.uint32(sp.delta) + (t1l << np.uint32(_LOW))
    # bound: 2^31 + 2^29 + 2^29 < 2^32
    return canon32(v, sp)


def mulmod32_const(x, c: int, sp: SolinasPrime):
    """Canonical x (< p) times Python-int constant c (< p), canonical out."""
    c = c % sp.p
    c15 = (c << _LOW) % sp.p
    xh = x >> np.uint32(_LOW)                                # < 2^(b-15) <= 2^14
    xl = x & np.uint32((1 << _LOW) - 1)                      # < 2^15
    # x*c = xh*(c*2^15) + xl*c; split both constants into 15-bit limbs
    t1 = xh * np.uint32(c15 >> _LOW) + xl * np.uint32(c >> _LOW)   # < 2^30
    t0 = xh * np.uint32(c15 & 0x7FFF) + xl * np.uint32(c & 0x7FFF)  # < 2^31
    return _compose(t1, t0, sp)


def modsum32(x, sp: SolinasPrime, axis: int = 0):
    """Canonical residues summed along ``axis`` -> canonical (clerk kernel).

    Tree reduction with a canonicalizing fold every ``fan`` terms, fan
    chosen so partial sums stay < 2^32 (fan*(p-1) < 2^32).
    """
    fan = (0xFFFFFFFF) // (sp.p - 1) if sp.p > 1 else 8
    fan = max(2, min(256, fan))
    x = jnp.asarray(x, _U32)
    x = jnp.moveaxis(x, axis, 0)
    while x.shape[0] > 1:
        n = x.shape[0]
        chunk = min(fan, n)
        pad = (-n) % chunk
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], _U32)], axis=0
            )
        x = x.reshape((x.shape[0] // chunk, chunk) + x.shape[1:])
        x = canon32(jnp.sum(x, axis=1, dtype=_U32), sp)
    return x[0]


def uniform32(key, shape, sp: SolinasPrime):
    """Uniform canonical residues from 64 random bits per element.

    (hi*2^32 + lo) mod p with exact constant-multiply reduction — same
    <= p/2^64 statistical distance as the generic uniform_mod.
    """
    bits = jax.random.bits(key, shape=tuple(shape) + (2,), dtype=_U32)
    hi = canon32(bits[..., 0], sp)
    lo = canon32(bits[..., 1], sp)
    r32 = (1 << 32) % sp.p
    return modadd32(mulmod32_const(hi, r32, sp), lo, sp)


# ---------------------------------------------------------------------------
# The contraction kernel: out = (M @ v) mod p, M a small host-side matrix

def modmatmul32(m_host: np.ndarray, v, sp: SolinasPrime):
    """[n, k] host matrix (ints mod p) times canonical [..., k, B] uint32.

    Builds the matrix limbs host-side (trace-time constants) and contracts
    via :func:`modmatmul32_limbs`.
    """
    m_host = np.asarray(m_host) % sp.p
    n, k = m_host.shape
    v = jnp.asarray(v, _U32)
    if v.shape[-2] != k:
        raise ValueError(f"contraction mismatch: M has k={k}, v has {v.shape[-2]}")

    low_mask = (1 << _LOW) - 1
    mh = jnp.asarray((m_host >> _LOW).astype(np.uint32))     # [n, k] < 2^14
    ml = jnp.asarray((m_host & low_mask).astype(np.uint32))  # [n, k] < 2^15
    return modmatmul32_limbs(mh, ml, v, sp)


def modmatmul32_limbs(mh, ml, v, sp: SolinasPrime):
    """Core contraction on pre-split matrix limbs (device arrays).

    ``mh``/``ml``: [n, k] uint32 high/low 15-bit limbs of a matrix of
    canonical residues; ``v``: canonical [..., k, B] uint32. Split out from
    :func:`modmatmul32` so Pallas kernels can take the limbs as inputs
    (kernels may not capture traced constants).

    Limb streams with per-stream overflow-safe fan-in (bounds for b <= 29,
    low limbs < 2^15, high limbs < 2^(b-15) <= 2^14):

      hh = mh*vh < 2^28   (scale 2^30)    hl/lh = *h**l < 2^29 (scale 2^15)
      ll = ml*vl < 2^30   (scale 1)

    Each stream folds (canonical reduce) whenever another chunk of terms
    would overflow uint32; the scale-2^30 stream re-enters through
    ``mulmod32_const(.., 2^30 mod p)``.
    """
    n, k = mh.shape
    low_mask = (1 << _LOW) - 1
    vh = v >> np.uint32(_LOW)                                # [..., k, B] < 2^14
    vl = v & np.uint32(low_mask)                             # [..., k, B] < 2^15

    hi_max = (1 << (sp.b - _LOW)) - 1
    bounds = {
        "hh": hi_max * hi_max,
        "hl": hi_max * low_mask,
        "ll": low_mask * low_mask,
    }
    fans = {s: max(1, 0xFFFFFFFF // bound) for s, bound in bounds.items()}
    # one chunking of the contraction axis serves all streams
    chunk = max(1, min(fans.values()))

    def stream(a_limbs, b_limbs):
        # a: [n, k]; b: [..., k, B] -> sum over k of a*b, folded per chunk.
        # Accumulated with explicit adds, not jnp.sum: Mosaic cannot lower
        # unsigned reductions, and k is tiny so the unrolled adds fuse the
        # same either way.
        acc = None
        for start in range(0, k, chunk):
            part = None
            for j in range(start, min(start + chunk, k)):
                term = a_limbs[:, j][:, None] * b_limbs[..., j, :][..., None, :]
                part = term if part is None else part + term  # [..., n, B]
            part = canon32(part, sp)
            acc = part if acc is None else modadd32(acc, part, sp)
        return acc                                           # canonical < p

    s_hh = stream(mh, vh)
    s_hl = stream(mh, vl)
    s_lh = stream(ml, vh)
    s_ll = stream(ml, vl)

    c30 = (1 << 30) % sp.p
    t0 = modadd32(s_ll, mulmod32_const(s_hh, c30, sp), sp)   # < p
    t1 = modadd32(s_hl, s_lh, sp)                            # < p
    return _compose(t1, t0, sp)                              # t1*2^15 + t0


# ---------------------------------------------------------------------------
# NumPy mirror (oracle for bit-exactness tests)

def np_modmatmul32(m_host: np.ndarray, v: np.ndarray, sp: SolinasPrime) -> np.ndarray:
    m = np.asarray(m_host, dtype=object) % sp.p
    vv = np.asarray(v, dtype=object)
    return (m @ vv % sp.p).astype(np.uint32)
