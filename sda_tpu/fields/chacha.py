"""ChaCha20-based deterministic mask PRG (host-side, vectorized numpy).

The reference's ChaCha masking scheme derives an O(d) mask from a <=256-bit
seed so participants upload O(1) mask data (client/src/crypto/masking/
chacha.rs:24-77, via rand 0.3's ChaChaRng). The exact rand-0.3 stream is not
reproduced here; sda-tpu pins its own versioned PRG spec (``CHACHA_PRG_V1``)
with the same interface and security properties:

- seed: list of u32 words (serialized as the i64 "mask" vector on the wire,
  chacha.rs:49-53 convention);
- key: seed words placed in key words 0..len-1, remaining words 0;
- state: RFC-7539 constants | key(8) | block counter (word 12, from 0) |
  words 13..15 zero; 20 rounds; output words little-endian;
- draw stream: consecutive u64 = (word[2i] as low, word[2i+1] as high);
- sample in [0, m): rejection below zone = floor(2^64/m)*m, then v % m.

Both participant (mask generation) and recipient (mask re-expansion — the
recipient hot loop, receive.rs:102-118) use this expansion, so the protocol
stays self-consistent; a native C++ implementation of the same spec lives in
sda_tpu/native.
"""

from __future__ import annotations

import secrets as _secrets
from typing import List, Sequence

import numpy as np

CHACHA_PRG_V1 = "sda-tpu/chacha20-prg/v1"

_CONSTANTS = np.array(
    [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32
)


def random_seed(seed_bitsize: int) -> List[int]:
    """Fresh OS-random seed of ceil(seed_bitsize/32) u32 words (chacha.rs:29-34)."""
    words = (seed_bitsize + 31) // 32
    if words > 8:
        raise ValueError("seed_bitsize > 256 unsupported: ChaCha20 keys hold 256 bits")
    return [int.from_bytes(_secrets.token_bytes(4), "little") for _ in range(words)]


def _rotl(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(state, a, b, c, d):
    state[a] += state[b]
    state[d] = _rotl(state[d] ^ state[a], 16)
    state[c] += state[d]
    state[b] = _rotl(state[b] ^ state[c], 12)
    state[a] += state[b]
    state[d] = _rotl(state[d] ^ state[a], 8)
    state[c] += state[d]
    state[b] = _rotl(state[b] ^ state[c], 7)


def chacha_block_words(seed: Sequence[int], counter0: int, nblocks: int) -> np.ndarray:
    """[nblocks, 16] u32 keystream words for block counters counter0..+nblocks.

    Vectorized: all blocks advance through the 20 rounds simultaneously.
    """
    if len(seed) > 8:
        raise ValueError(
            f"seed has {len(seed)} words; ChaCha20 keys hold at most 8 "
            "(256 bits) — longer seeds would silently lose entropy"
        )
    key = np.zeros(8, dtype=np.uint32)
    for i, w in enumerate(seed):
        key[i] = np.uint32(w & 0xFFFFFFFF)
    init = np.zeros((16, nblocks), dtype=np.uint32)
    init[0:4] = _CONSTANTS[:, None]
    init[4:12] = key[:, None]
    init[12] = (np.arange(counter0, counter0 + nblocks)).astype(np.uint32)
    state = init.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):
            # column rounds
            _quarter(state, 0, 4, 8, 12)
            _quarter(state, 1, 5, 9, 13)
            _quarter(state, 2, 6, 10, 14)
            _quarter(state, 3, 7, 11, 15)
            # diagonal rounds
            _quarter(state, 0, 5, 10, 15)
            _quarter(state, 1, 6, 11, 12)
            _quarter(state, 2, 7, 8, 13)
            _quarter(state, 3, 4, 9, 14)
        state += init
    return state.T  # [nblocks, 16]


def expand_mask(seed: Sequence[int], dimension: int, modulus: int) -> np.ndarray:
    """Deterministic mask vector in [0, m)^d from a seed (the PRG expansion).

    Rejection sampling on u64 draws; each 16-word block yields 8 draws.
    """
    if modulus <= 0 or modulus >= (1 << 62):
        raise ValueError("modulus out of range")
    m = np.uint64(modulus)
    zone = np.uint64(((1 << 64) // modulus) * modulus - 1)  # accept v <= zone
    out = np.empty(dimension, dtype=np.int64)
    filled = 0
    counter = 0
    # over-draw slightly; rejection probability is < m/2^64
    while filled < dimension:
        need = dimension - filled
        nblocks = max(1, -(-need // 8) + 1)
        words = chacha_block_words(seed, counter, nblocks).reshape(-1)
        counter += nblocks
        lo = words[0::2].astype(np.uint64)
        hi = words[1::2].astype(np.uint64)
        v = (hi << np.uint64(32)) | lo
        v = v[v <= zone]
        take = min(need, v.shape[0])
        out[filled : filled + take] = (v[:take] % m).astype(np.int64)
        filled += take
    return out
