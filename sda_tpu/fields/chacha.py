"""ChaCha20-based deterministic mask PRGs (host-side, vectorized numpy).

The reference's ChaCha masking scheme derives an O(d) mask from a <=256-bit
seed so participants upload O(1) mask data (client/src/crypto/masking/
chacha.rs:24-77, via rand 0.3's ChaChaRng). TWO streams are implemented over
the shared ChaCha20 block function, selected by the wire-visible ``prg`` tag
on the scheme (protocol.crypto.ChaChaMasking):

``CHACHA_PRG_RAND03`` (the default — what the bare Rust wire shape means):
the exact rand-0.3 ``ChaChaRng::from_seed(&[u32])`` + ``gen_range(0, m)``
stream the reference's masker draws, so a round mixed with a Rust peer
reveals the CORRECT aggregate. Per rand 0.3's chacha.rs and
distributions/range.rs:

- key: seed words into key words 0..len-1, remaining words 0; block counter
  is 128-bit (words 12..15, from 0) — identical to a word-12 counter below
  2^32 blocks; 20 rounds;
- draw stream: ``next_u64`` = (FIRST word as high) << 32 | (second as low);
- sample in [0, m): accept v < zone where zone = u64::MAX - u64::MAX % m,
  then v % m.

``CHACHA_PRG_V1`` (opt-in, tagged on the wire): sda-tpu's own versioned
spec — same block function, but u64 draws take word[2i] as the LOW half and
the acceptance zone is floor(2^64/m)*m (inclusive-below), which also
differs from rand 0.3 on power-of-two moduli.

Both participant (mask generation) and recipient (mask re-expansion — the
recipient hot loop, receive.rs:102-118) use the same expansion, so the
protocol stays self-consistent; native C++ implementations of both specs
live in sda_tpu/native, device (jax) implementations in fields.chacha_jax.
"""

from __future__ import annotations

import secrets as _secrets
from typing import List, Sequence

import numpy as np

CHACHA_PRG_V1 = "sda-tpu/chacha20-prg/v1"
#: the stream implied by the bare Rust wire shape (crypto.rs:53 documents
#: the scheme as `rand::chacha::ChaChaRng`); protocol.crypto pins the same
#: literals (duplicated to keep the wire layer import-free; a test asserts
#: they match)
CHACHA_PRG_RAND03 = "rand-0.3/chacharng"

_CONSTANTS = np.array(
    [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32
)


def random_seed(seed_bitsize: int) -> List[int]:
    """Fresh OS-random seed of ceil(seed_bitsize/32) u32 words (chacha.rs:29-34)."""
    words = (seed_bitsize + 31) // 32
    if words > 8:
        raise ValueError("seed_bitsize > 256 unsupported: ChaCha20 keys hold 256 bits")
    return [int.from_bytes(_secrets.token_bytes(4), "little") for _ in range(words)]


def _rotl(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(state, a, b, c, d):
    state[a] += state[b]
    state[d] = _rotl(state[d] ^ state[a], 16)
    state[c] += state[d]
    state[b] = _rotl(state[b] ^ state[c], 12)
    state[a] += state[b]
    state[d] = _rotl(state[d] ^ state[a], 8)
    state[c] += state[d]
    state[b] = _rotl(state[b] ^ state[c], 7)


def chacha_block_words(seed: Sequence[int], counter0: int, nblocks: int) -> np.ndarray:
    """[nblocks, 16] u32 keystream words for block counters counter0..+nblocks.

    Vectorized: all blocks advance through the 20 rounds simultaneously.
    """
    if len(seed) > 8:
        raise ValueError(
            f"seed has {len(seed)} words; ChaCha20 keys hold at most 8 "
            "(256 bits) — longer seeds would silently lose entropy"
        )
    key = np.zeros(8, dtype=np.uint32)
    for i, w in enumerate(seed):
        key[i] = np.uint32(w & 0xFFFFFFFF)
    init = np.zeros((16, nblocks), dtype=np.uint32)
    init[0:4] = _CONSTANTS[:, None]
    init[4:12] = key[:, None]
    init[12] = (np.arange(counter0, counter0 + nblocks)).astype(np.uint32)
    state = init.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):
            # column rounds
            _quarter(state, 0, 4, 8, 12)
            _quarter(state, 1, 5, 9, 13)
            _quarter(state, 2, 6, 10, 14)
            _quarter(state, 3, 7, 11, 15)
            # diagonal rounds
            _quarter(state, 0, 5, 10, 15)
            _quarter(state, 1, 6, 11, 12)
            _quarter(state, 2, 7, 8, 13)
            _quarter(state, 3, 4, 9, 14)
        state += init
    return state.T  # [nblocks, 16]


def expand_mask(seed: Sequence[int], dimension: int, modulus: int) -> np.ndarray:
    """Deterministic mask vector in [0, m)^d from a seed (the PRG expansion).

    Rejection sampling on u64 draws; each 16-word block yields 8 draws.
    """
    if modulus <= 0 or modulus >= (1 << 62):
        raise ValueError("modulus out of range")
    m = np.uint64(modulus)
    zone = np.uint64(((1 << 64) // modulus) * modulus - 1)  # accept v <= zone
    out = np.empty(dimension, dtype=np.int64)
    filled = 0
    counter = 0
    # over-draw slightly; rejection probability is < m/2^64
    while filled < dimension:
        need = dimension - filled
        nblocks = max(1, -(-need // 8) + 1)
        words = chacha_block_words(seed, counter, nblocks).reshape(-1)
        counter += nblocks
        lo = words[0::2].astype(np.uint64)
        hi = words[1::2].astype(np.uint64)
        v = (hi << np.uint64(32)) | lo
        v = v[v <= zone]
        take = min(need, v.shape[0])
        out[filled : filled + take] = (v[:take] % m).astype(np.int64)
        filled += take
    return out


def expand_mask_rand03(seed: Sequence[int], dimension: int, modulus: int) -> np.ndarray:
    """The exact rand-0.3 ChaChaRng mask stream (chacha.rs:37-41, 57-77).

    ``ChaChaRng::from_seed(&seed)`` then ``gen_range(0_i64, modulus)`` per
    element: u64 draws assemble the FIRST keystream word as the HIGH half
    (rand 0.3's default ``Rng::next_u64``), rejection accepts
    ``v < u64::MAX - u64::MAX % m`` (distributions/range.rs), result is
    ``v % m``. Each rejected draw consumes its two words, so the word
    pairing is positional and the expansion vectorizes exactly.
    """
    if modulus <= 0 or modulus >= (1 << 62):
        raise ValueError("modulus out of range")
    m = np.uint64(modulus)
    u64_max = (1 << 64) - 1
    zone_excl = np.uint64(u64_max - u64_max % modulus)  # accept v < zone
    out = np.empty(dimension, dtype=np.int64)
    filled = 0
    counter = 0
    while filled < dimension:
        need = dimension - filled
        nblocks = max(1, -(-need // 8) + 1)
        words = chacha_block_words(seed, counter, nblocks).reshape(-1)
        counter += nblocks
        hi = words[0::2].astype(np.uint64)
        lo = words[1::2].astype(np.uint64)
        v = (hi << np.uint64(32)) | lo
        v = v[v < zone_excl]
        take = min(need, v.shape[0])
        out[filled : filled + take] = (v[:take] % m).astype(np.int64)
        filled += take
    return out


_EXPANDERS = {
    CHACHA_PRG_V1: expand_mask,
    CHACHA_PRG_RAND03: expand_mask_rand03,
}


def expand_mask_for(
    prg: str, seed: Sequence[int], dimension: int, modulus: int
) -> np.ndarray:
    """PRG-tag-dispatched expansion; unknown tags fail loudly — an
    unrecognized stream must never silently alias another one (that is
    exactly the wrong-aggregate hazard the tag exists to prevent)."""
    try:
        fn = _EXPANDERS[prg]
    except KeyError:
        raise ValueError(f"unknown ChaCha PRG {prg!r}") from None
    return fn(seed, dimension, modulus)
