"""Fused Pallas kernel: mask + share + participant-combine in one HBM pass.

The XLA fast path (fields.fastfield) still materializes the [P, n, B] share
tensor in HBM between the share matmul and the clerk combine — for the
flagship config that's ~2GB of write+read traffic. This kernel fuses the
participant loop: for each dimension tile it draws the masks and share
randomness on-core (pltpu PRNG), forms each participant's shares in VMEM,
and folds them straight into [n, TB] accumulators. HBM traffic drops to
one read of the inputs plus accumulator-sized writes.

Algebra is the uint32 Solinas fast field (see fastfield.py — same bounds,
same helpers; fastfield's jnp ops compose inside Pallas kernels). The
share matrix M is host-side, so every multiply in the unrolled row loop is
a constant mulmod.

Randomness: `internal` mode uses the TPU per-core PRNG
(pltpu.prng_random_bits) seeded per (seed, tile); masks cancel within the
round, so the round stays exact. `external` mode takes pre-drawn bits as
an input — it exists so the arithmetic is bit-checkable under
``interpret=True`` on CPU (the TPU PRNG primitive is hardware-only) and is
also what a protocol-grade deployment would use to inject threefry/ChaCha
streams (reference mask PRGs: client/src/crypto/masking/*.rs).

Opt-in: `single_chip_round_pallas` is selected by bench/driver code when
SDA_PALLAS=1; the XLA paths remain the default until the kernel wins on
real hardware.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fastfield
from .fastfield import SolinasPrime, canon32, modadd32, modsub32, mulmod32_const
from . import numtheory
from .sharing import batch_columns, unbatch_columns

_U32 = jnp.uint32


def _uniform_from_bits(hi_bits, lo_bits, sp: SolinasPrime):
    """Two uint32 draws -> canonical uniform residue (fastfield.uniform32)."""
    hi = canon32(hi_bits, sp)
    lo = canon32(lo_bits, sp)
    r32 = (1 << 32) % sp.p
    return modadd32(mulmod32_const(hi, r32, sp), lo, sp)


def _share_rows_const(values_rows, m_host_row, sp: SolinasPrime):
    """Sum_j M[i][j]*values[j] for one output row, all constants.

    Kept for reference/AB-testing: operates on [1, tile] row slices, which
    uses 1 of 8 VPU sublanes; the default kernel path calls
    fastfield.modmatmul32 on the full [m2-1, tile] block instead.
    """
    acc = None
    for coeff, row in zip(m_host_row, values_rows):
        if coeff % sp.p == 0:
            continue
        term = mulmod32_const(row, int(coeff), sp)
        acc = term if acc is None else modadd32(acc, term, sp)
    if acc is None:
        acc = jnp.zeros_like(values_rows[0])
    return acc


def _participant_tile(pb: int, rows_per_participant: int, tile: int) -> int:
    """Participants per VMEM block, sized so the (double-buffered) input
    blocks stay ~3MB total. ``rows_per_participant`` counts every uint32
    row the grid streams per participant: k for the x block, plus 2*draws
    bit rows in external-bits mode (which therefore tiles more finely)."""
    cap = max(1, 3_000_000 // (rows_per_participant * tile * 4))
    return max(pb, (cap // pb) * pb)


def _balanced_tiling(P: int, pb: int, tile_cap: int):
    """(p_tile, P_eff): spread P over equal tiles instead of padding to a
    whole multiple of tile_cap (P=113 at cap 112 pads to 128, not 224)."""
    if P <= tile_cap:
        p_tile = -(-P // pb) * pb
        return p_tile, p_tile
    ntiles = -(-P // tile_cap)
    p_tile = -(-P // (ntiles * pb)) * pb
    return p_tile, ntiles * p_tile


def fused_mask_share_combine(
    x_cols,
    seed,
    sp: SolinasPrime,
    m_host: np.ndarray,
    privacy_threshold: int,
    masked: bool,
    tile: int = 512,
    external_bits=None,
    interpret: bool = False,
    p_block: int = 16,
    p_tile: Optional[int] = None,
    tree_fold: bool = False,
):
    """[P, k, B] canonical uint32 columns -> ([n, B] combined shares,
    [k, B] mask totals).

    external_bits: optional [P, 2*(k+t) or 2*t, B] uint32 pre-drawn bits
    (2 words per drawn residue; mask rows first when masked) — used for
    interpret-mode tests and injectable PRG streams.

    ``p_block`` participants fold per loop step (fewer, larger PRNG draws
    and one matmul per block); it shrinks to a divisor of P when needed.
    ``p_tile`` (a multiple of the effective p_block dividing P; derived
    from the VMEM budget when None) sets how many participants each
    grid-axis-1 block streams through VMEM. The mod-p algebra is exact,
    so neither size ever changes results.

    ``tree_fold`` replaces the per-slice participant fold (adds on
    [rows, TB] slices, rows = k or t of 8 sublanes per vreg) with a
    halving tree over the flat [pb*rows, TB] block — every add at full
    sublane density, log2(pb) rounds. Bit-identical output (mod-p sums
    are order-free; canon cadence keeps raw partials < 2^32). Applied
    only when the effective p_block is a power of two >= 2; otherwise
    the slice fold runs as before.
    """
    P, k, B = x_cols.shape
    n, m2 = m_host.shape
    t = privacy_threshold
    if m2 != 1 + k + t:
        raise ValueError(f"share matrix width {m2} != 1+k+t={1 + k + t}")
    if B % tile:
        raise ValueError(f"B={B} must be divisible by tile={tile}")
    pb = max(1, min(int(p_block), P))
    if P % pb:  # keep the accept-any-P contract: shrink to a divisor
        pb = math.gcd(pb, P)
    draws = (k + t) if masked else t
    internal = external_bits is None
    # participants stream through VMEM in tiles of p_tile along a second
    # (reduction) grid axis — holding all P in one block OOMs VMEM beyond
    # a few hundred participants (external-bits mode carries 2*draws extra
    # rows per participant and tiles more finely)
    rows = k if internal else k + 2 * draws
    if p_tile is None:
        p_tile = min(P, _participant_tile(pb, rows, tile))
        p_tile = math.gcd(p_tile, P) if P % p_tile else p_tile
    p_tile = int(p_tile)
    if P % p_tile or p_tile % pb:
        raise ValueError(
            f"p_tile={p_tile} must divide P={P} and be a multiple of "
            f"p_block={pb}"
        )

    def kernel(*refs):
        if internal:
            seed_ref, x_ref, mh_ref, ml_ref, shares_ref, masktot_ref = refs
        else:
            seed_ref, x_ref, mh_ref, ml_ref, bits_ref, shares_ref, masktot_ref = refs
        if internal:
            # one distinct stream per (dim tile, participant tile); Mosaic
            # caps prng_seed at 2 values, so flatten the grid coordinates
            pltpu.prng_seed(
                seed_ref[0],
                pl.program_id(0) * jnp.int32(P // p_tile) + pl.program_id(1),
            )

        # raw uint32 partial sums stay exact for `fan` canonical residues
        fan = max(1, 0xFFFFFFFF // (sp.p - 1))
        # tree mode: raw-add levels between canons (2^L canonical terms
        # stay < 2^32); slice-fold applies when pb is not a power of two
        use_tree = tree_fold and pb >= 2 and (pb & (pb - 1)) == 0
        max_lvl = max(1, int(math.floor(math.log2(fan))))

        def fold_slices(get, count):
            """Σ of ``get(i)`` (canonical [r, TB]) for i < count: raw adds,
            canonicalizing every ``fan`` terms."""
            acc, partial, cnt = None, None, 0
            for i in range(count):
                sl = get(i)
                partial = sl if partial is None else partial + sl
                cnt += 1
                if cnt == fan or i == count - 1:
                    pc = canon32(partial, sp)
                    acc = pc if acc is None else modadd32(acc, pc, sp)
                    partial, cnt = None, 0
            return acc

        def tree_fold_block(arr, group_rows):
            """Σ of the stacked [group_rows, TB] slices in ``arr`` by
            halving the FULL block — dense sublanes, log2(m) rounds."""
            m = arr.shape[0] // group_rows
            lvl = 0
            while m > 1:
                h = m // 2
                arr = arr[: h * group_rows] + arr[h * group_rows:]
                m = h
                lvl += 1
                if lvl == max_lvl or m == 1:
                    arr = canon32(arr, sp)
                    lvl = 0
            return arr

        def fold_block(arr, group_rows):
            """Σ of the pb stacked [group_rows, TB] slices (canonical)."""
            if use_tree:
                return tree_fold_block(arr, group_rows)
            return fold_slices(
                lambda i: arr[i * group_rows: (i + 1) * group_rows], pb)

        def draw_sum(rows, row0, p0):
            """Σ over the pb participants of [rows, TB] uniform residues."""
            if internal:
                bits = pltpu.bitcast(
                    pltpu.prng_random_bits((2 * pb * rows, tile)), _U32
                )
                hi = bits[: pb * rows, :]
                lo = bits[pb * rows :, :]
                res = _uniform_from_bits(hi, lo, sp)          # [pb*rows, TB]
                return fold_block(res, rows)
            blk = bits_ref[pl.ds(p0, pb)]                     # [pb, 2*draws, TB]
            hi = blk[:, 2 * row0 : 2 * row0 + rows, :]
            lo = blk[:, 2 * row0 + rows : 2 * (row0 + rows), :]
            res = _uniform_from_bits(hi, lo, sp)              # [pb, rows, TB]
            if use_tree:
                return tree_fold_block(res.reshape(pb * rows, tile), rows)
            return fold_slices(lambda i: res[i], pb)

        # matrix limb columns: first k drive the (masked) secrets, last t
        # the share randomness
        mh_k, mh_t = mh_ref[...][:, :k], mh_ref[...][:, k:]
        ml_k, ml_t = ml_ref[...][:, :k], ml_ref[...][:, k:]

        # the participant axis (grid dim 1) revisits the same output block:
        # zero it on the first visit, accumulate on the rest
        @pl.when(pl.program_id(1) == 0)
        def _init():
            shares_ref[...] = jnp.zeros_like(shares_ref)
            masktot_ref[...] = jnp.zeros_like(masktot_ref)

        def body(b_ix, carry):
            # share-combine is LINEAR: the clerk-combined output
            # Σ_p M @ values_p equals M @ (Σ_p values_p), so participants
            # fold with cheap adds FIRST and the matmul runs once per fold
            # block — per-participant share rows are never materialized
            # (in the distributed protocol they live on the participants'
            # own devices; a chip computing the aggregate needs only their
            # sum). Bit-exact vs the per-participant XLA path given the
            # same bits: mod-p arithmetic is exact, so fold order is free.
            p0 = b_ix * np.int32(pb)
            x_blk = x_ref[pl.ds(p0, pb)]                      # [pb, k, TB]
            # canon at first touch: the folds' raw-add bounds need terms
            # < p, and the docstring contract (canonical inputs) is
            # otherwise unenforced
            if use_tree:
                xsum = tree_fold_block(
                    canon32(x_blk, sp).reshape(pb * k, tile), k)  # [k, TB]
            else:
                xsum = fold_slices(
                    lambda i: canon32(x_blk[i], sp), pb)      # [k, TB]
            if masked:
                masksum = draw_sum(k, 0, p0)                  # [k, TB]
                values_k = modadd32(xsum, masksum, sp)
                masktot_ref[...] = modadd32(masktot_ref[...], masksum, sp)
                randsum = draw_sum(t, k, p0)
            else:
                values_k = xsum
                randsum = draw_sum(t, 0, p0)
            contrib = modadd32(
                fastfield.modmatmul32_limbs(mh_k, ml_k, values_k, sp),
                fastfield.modmatmul32_limbs(mh_t, ml_t, randsum, sp),
                sp,
            )                                                 # [n, TB]
            shares_ref[...] = modadd32(shares_ref[...], contrib, sp)
            return carry  # int32 zero: Mosaic cannot legalize an i64 carry

        # int32 bounds AND carry: under x64, Python-int bounds make the loop
        # index i64, which Mosaic cannot legalize
        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(p_tile // pb), body, jnp.int32(0)
        )

    # host-side limb split of the active share-matrix columns (minus the
    # fixed zero column 0); tiny [n, m2-1] blocks, same in every grid step
    m_active = np.asarray(m_host)[:, 1:] % sp.p
    mh_np = (m_active >> 15).astype(np.uint32)
    ml_np = (m_active & 0x7FFF).astype(np.uint32)

    # grid dim 0: dim tiles; grid dim 1 (innermost): participant tiles
    # streamed through the same output block
    grid = (B // tile, P // p_tile)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                     # seed
        pl.BlockSpec((p_tile, k, tile), lambda i, j: (j, 0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(mh_np.shape, lambda i, j: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec(ml_np.shape, lambda i, j: (0, 0), memory_space=pltpu.VMEM),
    ]
    args = [jnp.asarray([seed], jnp.int32), x_cols,
            jnp.asarray(mh_np), jnp.asarray(ml_np)]
    if not internal:
        in_specs.append(
            pl.BlockSpec((p_tile, 2 * draws, tile), lambda i, j: (j, 0, i),
                         memory_space=pltpu.VMEM)
        )
        args.append(external_bits)
    out_specs = [
        pl.BlockSpec((n, tile), lambda i, j: (0, i), memory_space=pltpu.VMEM),
        pl.BlockSpec((k, tile), lambda i, j: (0, i), memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n, B), _U32),
        jax.ShapeDtypeStruct((k, B), _U32),
    ]
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )
    # trace the kernel with x64 OFF: under the framework's global x64 the
    # BlockSpec index maps and loop indices become i64, which Mosaic cannot
    # legalize (func.return (i64) lowering error on real TPU); every value
    # in the kernel is explicitly uint32/int32 so semantics are unchanged.
    # jax.enable_x64 graduated from jax.experimental after 0.4; take
    # whichever this jax has
    _enable_x64 = getattr(jax, "enable_x64", None) \
        or jax.experimental.enable_x64
    with _enable_x64(False):
        return call(*args)


def single_chip_round_pallas(
    sharing_scheme,
    masking_scheme=None,
    tile: Optional[int] = None,
    interpret: bool = False,
    external_bits_fn=None,
    p_block: int = 16,
    p_tile: Optional[int] = None,
    dim_tile: Optional[int] = None,
    tree_fold: bool = False,
):
    """Drop-in alternative to mesh.single_chip_round on the fused kernel.

    Requires a Solinas prime. external_bits_fn(key, P, draws, B) -> uint32
    bits array enables deterministic/interpret-mode testing. ``dim_tile``
    processes the dimension in fixed-width tiles via ``lax.scan`` — one
    complete kernel round per tile — mirroring mesh.single_chip_round's
    dim-tiled schedule (the full-width program measured superlinear in d
    on chip; see that docstring).
    """
    from ..protocol import FullMasking, NoMasking

    s = sharing_scheme
    masking = masking_scheme or NoMasking()
    if not isinstance(masking, (NoMasking, FullMasking)):
        raise ValueError("pallas round masking: None or Full")
    if isinstance(masking, FullMasking) and masking.modulus != s.prime_modulus:
        raise ValueError("masking modulus must equal the sharing prime")
    sp = SolinasPrime.try_from(s.prime_modulus)
    if sp is None:
        raise ValueError(f"prime {s.prime_modulus} is not Solinas-form")
    masked = isinstance(masking, FullMasking)
    # scheme-dispatched matrices: PackedShamir (NTT) or BasicShamir
    # (Vandermonde/Lagrange, k=1) — the kernel is layout-agnostic
    m_host = numtheory.share_matrix_for(s)
    l_host = numtheory.reconstruct_matrix_for(s, tuple(range(s.share_count)))
    k = s.secret_count
    t = s.privacy_threshold
    draws = (k + t) if masked else t

    def one_tile(inputs, key):
        P, d = inputs.shape
        x = fastfield.to_residues32(inputs, sp)
        x_cols = batch_columns(x, k)                               # [P, k, B0]
        pb = max(1, min(p_block, P))
        B0 = x_cols.shape[-1]
        # lane-dim tile: multiples of 128 lanes; large tiles amortize the
        # grid-step overhead, small B avoids padding waste
        TB = tile if tile is not None else (
            2048 if B0 >= 2048 else max(128, -(-B0 // 128) * 128)
        )
        # pad the participant axis to a balanced tiling (zero rows
        # aggregate as zero; their masks cancel)
        rows = k if external_bits_fn is None else k + 2 * draws
        if p_tile is None:
            ptile_eff, P_eff = _balanced_tiling(
                P, pb, _participant_tile(pb, rows, TB)
            )
        else:
            ptile_eff = int(p_tile)
            P_eff = -(-P // ptile_eff) * ptile_eff
        if P_eff > P:
            x_cols = jnp.pad(x_cols, ((0, P_eff - P), (0, 0), (0, 0)))
        pad = (-B0) % TB
        if pad:
            x_cols = jnp.pad(x_cols, ((0, 0), (0, 0), (0, pad)))
        B = B0 + pad
        seed = jax.random.randint(key, (), 0, np.int32(2**31 - 1), dtype=jnp.int32)
        ext = None
        if external_bits_fn is not None:
            ext = external_bits_fn(key, P_eff, draws, B)
        shares, mask_tot = fused_mask_share_combine(
            x_cols, seed, sp, m_host, t, masked,
            tile=TB, external_bits=ext, interpret=interpret, p_block=pb,
            p_tile=ptile_eff, tree_fold=tree_fold,
        )
        from .sharing import packed_reconstruct32

        total = packed_reconstruct32(shares[:, :B0], l_host, sp, dimension=d)
        if masked:
            mask_flat = unbatch_columns(mask_tot[:, :B0], d)
            total = modsub32(total, mask_flat, sp)
        return total.astype(jnp.int64)

    if dim_tile is None:
        return one_tile

    import math

    from .dimtile import scan_dim_tiles

    grain = k * 8 // math.gcd(k, 8)
    return scan_dim_tiles(
        lambda blk, round_key, tile_key, i, width: one_tile(blk, tile_key),
        grain, dim_tile)
