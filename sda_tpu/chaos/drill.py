"""The chaos drill: one full federated round over real HTTP under fault
injection — the executable proof behind ``sda-sim --chaos``.

Everything hostile is injected deterministically through the failpoint
registry (``sda_tpu.chaos``):

- the HTTP dispatch 500s a seeded fraction of all requests
  (``http.server.request``);
- one response is dropped AFTER the server processed it
  (``http.server.response``) — the lost-ack case create-once retries must
  absorb;
- the store rejects the first participation create
  (``store.create_participation``);
- one clerk dies right after pulling its job (``clerk.abandon_job``);
  job leasing (``SdaServer.clerking_lease_seconds``) reissues the
  abandoned job to the clerk's next live poll.

The round must still reveal the bit-exact sum; the returned report carries
every ``chaos.*`` / ``http.retry.*`` / ``server.job.*`` counter so the
injection schedule is auditable — plus the round's trace timeline
(``sda_tpu.obs``): the whole drill runs under one ``round`` span, every
failpoint trigger lands as a span event, and the report's critical path
shows which injected fault lengthened the round.
"""

from __future__ import annotations

import time
from typing import List

from .. import chaos, obs
from ..utils import metrics


def run_chaos_drill(
    participants: int = 6,
    dim: int = 4,
    *,
    rate: float = 0.15,
    seed: int = 0,
    lease_seconds: float = 0.75,
    timeout_s: float = 60.0,
    store: str = "memory",
    store_path=None,
    extra_spec: str = None,
) -> dict:
    """Run one full aggregation round over HTTP under injected faults.

    Returns the report dict (``exact``, ``injected_ratio``, counters...).
    Requires libsodium (real sealed-box crypto, as in production rounds).
    """
    import numpy as np

    from ..client import SdaClient
    from ..crypto import MemoryKeystore, sodium
    from ..http import SdaHttpClient, SdaHttpServer
    from ..protocol import (
        Aggregation,
        AggregationId,
        FullMasking,
        PackedShamirSharing,
        SodiumEncryption,
    )
    from ..server import new_jsonfs_server, new_memory_server, new_sqlite_server

    if not sodium.available():
        raise RuntimeError("the chaos drill needs libsodium (real crypto round)")

    # the golden 8-clerk packed-Shamir committee (tests/test_fault_tolerance):
    # threshold 7 of 8, so the abandoned job is LIVENESS-critical only via
    # reissue when every other result is present
    scheme = PackedShamirSharing(
        secret_count=3, share_count=8, privacy_threshold=4,
        prime_modulus=433, omega_secrets=354, omega_shares=150,
    )

    obs.reset_all()
    chaos.reset()

    if store == "memory":
        service_impl = new_memory_server()
    elif store == "sqlite":
        service_impl = new_sqlite_server(store_path or ":memory:")
    elif store == "jsonfs":
        if store_path is None:
            raise ValueError("store='jsonfs' needs store_path")
        service_impl = new_jsonfs_server(store_path)
    else:
        raise ValueError(f"unknown store {store!r}")
    service_impl.server.clerking_lease_seconds = lease_seconds

    http_server = SdaHttpServer(service_impl, bind="127.0.0.1:0")
    http_server.start_background()
    try:
        # ONE round span ties every role together: participant uploads,
        # server handling (joined via traceparent), clerk jobs (joined via
        # the enqueue-time job link), and the recipient reveal
        with obs.span("round", attributes={"profile": "chaos",
                                           "participants": participants,
                                           "seed": seed}):
            def new_client():
                keystore = MemoryKeystore()
                proxy = SdaHttpClient(
                    http_server.address,
                    token="chaos-drill-token",
                    # fast, deterministic-budget retries: the drill injects a
                    # bounded failure schedule, so a handful of quick attempts
                    # always clears it
                    max_retries=8, backoff_base=0.01, backoff_cap=0.1,
                )
                agent = SdaClient.new_agent(keystore)
                return SdaClient(agent, keystore, proxy)

            # -- clean setup (no injection yet: the drill targets the round)
            recipient = new_client()
            recipient.upload_agent()
            recipient_key = recipient.new_encryption_key()
            recipient.upload_encryption_key(recipient_key)

            # the recipient owns a key too, so it is a committee candidate —
            # track every key-holding client by id and let the election decide
            candidates = {recipient.agent.id: recipient}
            for _ in range(scheme.share_count):
                clerk = new_client()
                clerk.upload_agent()
                clerk.upload_encryption_key(clerk.new_encryption_key())
                candidates[clerk.agent.id] = clerk

            agg = Aggregation(
                id=AggregationId.random(),
                title="chaos-drill",
                vector_dimension=dim,
                modulus=scheme.prime_modulus,
                recipient=recipient.agent.id,
                recipient_key=recipient_key,
                masking_scheme=FullMasking(scheme.prime_modulus),
                committee_sharing_scheme=scheme,
                recipient_encryption_scheme=SodiumEncryption(),
                committee_encryption_scheme=SodiumEncryption(),
            )
            recipient.upload_aggregation(agg)
            recipient.begin_aggregation(agg.id)
            committee = recipient.service.get_committee(recipient.agent, agg.id)
            clerks: List[SdaClient] = [
                candidates[cid] for cid, _ in committee.clerks_and_keys
            ]

            # -- arm the failpoints, then run the whole round under fire --
            chaos.configure("http.server.request", error=True, rate=rate,
                            seed=seed)
            chaos.configure("http.server.response", drop=True, times=1,
                            seed=seed)
            chaos.configure("store.create_participation", error=True, times=1,
                            seed=seed)
            chaos.configure("clerk.abandon_job", drop=True, times=1, seed=seed)
            if extra_spec:
                chaos.configure_from_spec(extra_spec, seed=seed)

            rng = np.random.default_rng(seed)
            inputs = rng.integers(0, scheme.prime_modulus,
                                  size=(participants, dim), dtype=np.int64)
            for row in inputs:
                participant = new_client()
                participant.upload_agent()
                participant.participate([int(x) for x in row], agg.id)
            recipient.end_aggregation(agg.id)  # snapshot + job fan-out

            # clerks keep polling until EVERY job has a result — waiting for
            # the full committee (not just reconstruction_threshold) is what
            # forces the abandoned job through the lease-expiry reissue path
            deadline = time.monotonic() + timeout_s
            ready = False
            while time.monotonic() < deadline:
                for clerk in clerks:
                    clerk.run_chores(-1)
                status = recipient.service.get_aggregation_status(
                    recipient.agent, agg.id
                )
                if (
                    status is not None
                    and status.snapshots
                    and status.snapshots[0].number_of_clerking_results
                    >= scheme.share_count
                ):
                    ready = True
                    break
                time.sleep(min(0.1, lease_seconds / 4))

            exact = False
            if ready:
                output = recipient.reveal_aggregation(agg.id)
                expected = inputs.sum(axis=0) % scheme.prime_modulus
                exact = bool((output.positive().values == expected).all())
    finally:
        # snapshot the schedule, then disarm BEFORE shutdown so teardown
        # requests aren't chaos'd
        failpoint_report = chaos.report()
        chaos.reset()
        http_server.shutdown()

    from ..loadgen import latency_report_ms as _latency_report_ms

    counters = metrics.counter_report()
    injected = sum(v for k, v in counters.items() if k.startswith("chaos."))
    # request-level failure accounting: dispatch 500s and store faults are
    # already inside http.request (they produce a counted 500 reply);
    # dropped responses bail out before the counter, so add them back
    failed_requests = sum(
        v for k, v in counters.items()
        if k.startswith(("chaos.http.server.", "chaos.store."))
    )
    dropped = counters.get("chaos.http.server.response", 0)
    requests_total = counters.get("http.request", 0) + dropped
    # the round timeline: slowest-first, so [0] is the drill's round trace
    # (every span shares its trace id); chaos_events names each injection
    # and the span it hit, critical_path the chain that set round duration
    timelines = obs.round_timelines()
    report = {
        "mode": f"chaos drill over HTTP ({store} store)",
        "participants": participants,
        "dim": dim,
        "clerks": scheme.share_count,
        "rate": rate,
        "seed": seed,
        "lease_seconds": lease_seconds,
        "ready": ready,
        "exact": exact,
        "injected_faults": injected,
        "failed_requests": failed_requests,
        "injected_ratio": round(failed_requests / max(1, requests_total), 4),
        "failpoints": failpoint_report or None,
        "counters": {
            k: v for k, v in counters.items()
            if k.startswith(("chaos.", "http.retry.", "http.status.",
                             "server.job.", "server.snapshot."))
        },
        # per-route server latency under fire: the tail the retry budget
        # has to ride out (loadgen measures the same table under load)
        "latency_ms": _latency_report_ms(),
        "trace": timelines[0] if timelines else None,
    }
    return report
