"""The chaos drill: one full federated round over real HTTP under fault
injection — the executable proof behind ``sda-sim --chaos``.

Everything hostile is injected deterministically through the failpoint
registry (``sda_tpu.chaos``):

- the HTTP dispatch 500s a seeded fraction of all requests
  (``http.server.request``);
- one response is dropped AFTER the server processed it
  (``http.server.response``) — the lost-ack case create-once retries must
  absorb;
- the store rejects the first participation create
  (``store.create_participation``);
- one clerk dies right after pulling its job (``clerk.abandon_job``);
  job leasing (``SdaServer.clerking_lease_seconds``) reissues the
  abandoned job to the clerk's next live poll.

The round must still reveal the bit-exact sum; the returned report carries
every ``chaos.*`` / ``http.retry.*`` / ``server.job.*`` counter so the
injection schedule is auditable — plus the round's trace timeline
(``sda_tpu.obs``): the whole drill runs under one ``round`` span, every
failpoint trigger lands as a span event, and the report's critical path
shows which injected fault lengthened the round.
"""

from __future__ import annotations

import tempfile
import time
from typing import List

from .. import chaos, obs
from ..utils import metrics


def golden_packed_scheme():
    """THE drill committee: 8-clerk packed Shamir, threshold 7-of-8,
    p=433, omega=354/150 (tests/test_fault_tolerance's golden config).
    One definition — the chaos drill, the load drill and the tree drill
    all compare bit-exactness against rounds built from this exact
    scheme, so it must never drift between them."""
    from ..protocol import PackedShamirSharing

    return PackedShamirSharing(
        secret_count=3, share_count=8, privacy_threshold=4,
        prime_modulus=433, omega_secrets=354, omega_shares=150,
    )


def run_chaos_drill(
    participants: int = 6,
    dim: int = 4,
    *,
    rate: float = 0.15,
    seed: int = 0,
    lease_seconds: float = 0.75,
    timeout_s: float = 60.0,
    store: str = "memory",
    store_path=None,
    extra_spec=None,
    dead_clerks: int = 0,
    dead_participants: int = 0,
    sharing: str = "packed",
    clerking_deadline_s: float = 1.5,
    sweep_interval_s: float = 0.2,
    brownout_s: float = 0.0,
    churn_rate: float = 0.0,
    async_http: bool = False,
) -> dict:
    """Run one full aggregation round over HTTP under injected faults.

    ``dead_clerks`` / ``dead_participants`` arm the PERMANENT-death
    failpoints (``clerk.dies`` / ``participant.dies``, kind ``kill``):
    unlike every transient failpoint above, the first K agents to hit the
    point latch dead for the rest of the drill. With dead clerks the
    round lifecycle supervisor (``server/lifecycle.py``) is armed — a
    clerking deadline plus an in-process sweeper — and the drill asserts
    the protocol's terminal verdict instead of hanging: packed Shamir
    degrades to the surviving quorum and still reveals bit-exactly;
    additive sharing (``sharing="additive"``) reaches ``failed`` with a
    machine-readable reason, surfaced through the typed
    ``RoundFailed`` raised by ``SdaClient.await_result``.

    ``brownout_s`` arms the GRAY-failure recovery drill: mid-clerking,
    the store's job-poll and result-write paths brown out (seeded
    elevated error rate + latency, ``chaos brownout`` kind) for that many
    seconds, behind a store circuit breaker (``server/breaker.py``) that
    must trip OPEN — shedding 503 + Retry-After instead of queueing —
    half-open on probes, and CLOSE once the window heals. The round must
    still reveal bit-exactly, and the report's ``breaker`` block records
    ``time_to_recover_s`` (MTTR: first trip -> final recovery), the
    fixed-seed record ci.sh feeds the bench regression gate.

    ``churn_rate`` arms the DEVICE-churn drill (the participant-plane
    mirror of the gray-failure drills): a seeded fraction of participants
    departs mid-round per :func:`sda_tpu.chaos.churn_schedule` — sealing
    and journaling their participation
    (``client/journal.ParticipationJournal``), then crashing either
    before the upload or in the lost-ack window right after the server
    stored it — and every departure later REJOINS as a fresh client
    process resuming from the journal. Exactly-once ingestion must make
    the round reveal bit-exactly with ZERO double-counted participations:
    pre-upload crashes land on resume as first arrivals, mid-upload
    crashes as byte-identical replays (``server.participation.replayed``).
    The drill also runs one deliberate equivocation probe (the first
    churned agent re-participates with fresh randomness): the server must
    reject it with ``ParticipationConflict``
    (``server.participation.equivocation``), and
    ``equivocations_undetected`` must stay 0.

    ``extra_spec`` is one spec string or a list of them (the repeatable
    ``--chaos-spec`` flag), merged with conflict rejection.

    ``async_http`` serves the drill on the asyncio event-loop plane
    (``http/aserver.py``) instead of thread-per-connection — the SAME
    fixed seed must produce a bit-exact reveal and identical
    ``server.participation.*`` counters on both planes (the ci.sh A/B
    step pins it; docs/scaling.md).

    Returns the report dict (``exact``, ``injected_ratio``, the round's
    lifecycle history, counters...). Requires libsodium (real sealed-box
    crypto, as in production rounds).
    """
    import numpy as np

    from ..client import SdaClient
    from ..client.journal import ParticipationJournal
    from ..crypto import MemoryKeystore, sodium
    from ..http import SdaHttpClient, server_class
    from ..protocol import (
        AdditiveSharing,
        Aggregation,
        AggregationId,
        FullMasking,
        PackedShamirSharing,
        ParticipationConflict,
        RoundFailed,
        ServerError,
        SodiumEncryption,
    )
    from ..server import new_jsonfs_server, new_memory_server, new_sqlite_server
    from ..server import lifecycle

    if not sodium.available():
        raise RuntimeError("the chaos drill needs libsodium (real crypto round)")

    if sharing == "additive":
        # n-of-n additive sharing: computationally cheap, zero tolerance
        # for clerk loss — the scheme the failed-round path exists for
        scheme = AdditiveSharing(share_count=8, modulus=433)
        modulus = scheme.modulus
    elif sharing == "packed":
        # the golden committee (module-level golden_packed_scheme):
        # threshold 7 of 8, so the abandoned job is LIVENESS-critical
        # only via reissue when every other result is present — and
        # exactly one PERMANENTLY dead clerk still leaves a
        # reconstructing quorum
        scheme = golden_packed_scheme()
        modulus = scheme.prime_modulus
    else:
        raise ValueError(f"unknown sharing {sharing!r}")

    obs.reset_all()
    chaos.reset()

    if store == "memory":
        service_impl = new_memory_server()
    elif store == "sqlite":
        service_impl = new_sqlite_server(store_path or ":memory:")
    elif store == "jsonfs":
        if store_path is None:
            raise ValueError("store='jsonfs' needs store_path")
        service_impl = new_jsonfs_server(store_path)
    else:
        raise ValueError(f"unknown store {store!r}")
    service_impl.server.clerking_lease_seconds = lease_seconds

    breaker = None
    if brownout_s:
        # the brownout-survival plane under test: a shared breaker over
        # the whole backend, tuned to trip within a handful of failed
        # store ops and probe on a sub-second cadence (the drill's
        # brownout windows are short)
        from ..server.breaker import CircuitBreaker, wrap_server_stores

        breaker = wrap_server_stores(service_impl.server, CircuitBreaker(
            threshold=3, recovery_s=0.25, budget_rate=4.0))

    sweeper = None
    if dead_clerks:
        # the supervisor plane: a clerking deadline so dead-clerk
        # detection has a clock, and a sweeper to run the diagnosis
        service_impl.server.round_deadlines = lifecycle.RoundDeadlines(
            clerking_s=clerking_deadline_s)
        sweeper = lifecycle.RoundSweeper(
            service_impl.server, interval_s=sweep_interval_s).start()

    # the churned devices' journal: a real directory, because the whole
    # point is surviving process death — rejoined clients read it cold
    journal_dir = tempfile.TemporaryDirectory(prefix="sda-churn-journal-")

    http_server = server_class(async_http)(service_impl, bind="127.0.0.1:0")
    http_server.start_background()
    try:
        # ONE round span ties every role together: participant uploads,
        # server handling (joined via traceparent), clerk jobs (joined via
        # the enqueue-time job link), and the recipient reveal
        with obs.span("round", attributes={"profile": "chaos",
                                           "participants": participants,
                                           "seed": seed}):
            def new_proxy():
                return SdaHttpClient(
                    http_server.address,
                    token="chaos-drill-token",
                    # fast, deterministic-budget retries: the drill injects a
                    # bounded failure schedule, so a handful of quick attempts
                    # always clears it. A brownout window is a SUSTAINED
                    # outage, so that mode gets the budget to ride it out
                    # (Retry-After hints from the open breaker pace the
                    # attempts)
                    max_retries=24 if brownout_s else 8,
                    backoff_base=0.01,
                    backoff_cap=0.25 if brownout_s else 0.1,
                )

            def new_client():
                keystore = MemoryKeystore()
                agent = SdaClient.new_agent(keystore)
                return SdaClient(agent, keystore, new_proxy())

            # -- clean setup (no injection yet: the drill targets the round)
            recipient = new_client()
            recipient.upload_agent()
            recipient_key = recipient.new_encryption_key()
            recipient.upload_encryption_key(recipient_key)

            # the recipient owns a key too, so it is a committee candidate —
            # track every key-holding client by id and let the election decide
            candidates = {recipient.agent.id: recipient}
            for _ in range(scheme.share_count):
                clerk = new_client()
                clerk.upload_agent()
                clerk.upload_encryption_key(clerk.new_encryption_key())
                candidates[clerk.agent.id] = clerk

            agg = Aggregation(
                id=AggregationId.random(),
                title="chaos-drill",
                vector_dimension=dim,
                modulus=modulus,
                recipient=recipient.agent.id,
                recipient_key=recipient_key,
                masking_scheme=FullMasking(modulus),
                committee_sharing_scheme=scheme,
                recipient_encryption_scheme=SodiumEncryption(),
                committee_encryption_scheme=SodiumEncryption(),
            )
            recipient.upload_aggregation(agg)
            recipient.begin_aggregation(agg.id)
            committee = recipient.service.get_committee(recipient.agent, agg.id)
            clerks: List[SdaClient] = [
                candidates[cid] for cid, _ in committee.clerks_and_keys
            ]

            # -- arm the failpoints, then run the whole round under fire --
            chaos.configure("http.server.request", error=True, rate=rate,
                            seed=seed)
            chaos.configure("http.server.response", drop=True, times=1,
                            seed=seed)
            chaos.configure("store.create_participation", error=True, times=1,
                            seed=seed)
            chaos.configure("clerk.abandon_job", drop=True, times=1, seed=seed)
            if dead_clerks:
                # permanent death: the first K clerks to poll latch dead —
                # their jobs are never worked, only diagnosed (lifecycle)
                chaos.configure("clerk.dies", kill=True, times=dead_clerks,
                                seed=seed)
            if dead_participants:
                chaos.configure("participant.dies", kill=True,
                                times=dead_participants, seed=seed)
            if extra_spec:
                specs = ([extra_spec] if isinstance(extra_spec, str)
                         else list(extra_spec))
                chaos.configure_from_specs(specs, seed=seed)

            rng = np.random.default_rng(seed)
            inputs = rng.integers(0, modulus,
                                  size=(participants, dim), dtype=np.int64)
            churn_plan = (chaos.churn_schedule(participants, churn_rate,
                                               seed=seed)
                          if churn_rate else None)
            journal = (ParticipationJournal(journal_dir.name)
                       if churn_rate else None)
            # a dead participant never contributes: the healthy-reference
            # sum covers exactly the rows that actually reached the round
            alive_rows = []
            departed = []  # (agent, row): crashed devices awaiting rejoin
            for i, row in enumerate(inputs):
                participant = new_client()
                participant.upload_agent()
                plan = churn_plan[i] if churn_plan else None
                if plan and plan["departs"]:
                    # the sporadic device: seal + journal, then crash at
                    # the scheduled point — BEFORE any upload, or in the
                    # lost-ack window right after the server stored the
                    # bundle (the device never learns it landed)
                    participation = participant.new_participation(
                        [int(x) for x in row], agg.id)
                    journal.record(participation)
                    if plan["phase"] == "mid-upload":
                        participant.upload_participation(participation)
                    metrics.count("participant.departed")
                    departed.append((participant.agent, row))
                    # the departure WILL land: every plan entry rejoins,
                    # and resume re-uploads the journaled bytes below
                    alive_rows.append(row)
                    continue
                participant.participate([int(x) for x in row], agg.id,
                                        journal=journal)
                if not participant._dead:
                    alive_rows.append(row)

            # -- rejoin: each departed device comes back as a FRESH client
            # process (new transport, empty keystore — resume needs only
            # the journaled bytes and the agent identity) and re-uploads
            # verbatim: pre-upload crashes arrive for the first time,
            # mid-upload crashes replay byte-identically
            resumed = 0
            equivocations_undetected = 0
            resume_started = time.perf_counter()
            for agent, _row in departed:
                rejoined = SdaClient(agent, MemoryKeystore(), new_proxy())
                resumed += rejoined.resume(journal)
            time_to_resume_s = time.perf_counter() - resume_started
            if departed:
                # the equivocation probe: the first churned agent tries to
                # participate AGAIN with fresh randomness and a different
                # input — exactly the double-count the exactly-once plane
                # exists to stop. Detection = typed ParticipationConflict.
                agent, row = departed[0]
                probe = SdaClient(agent, MemoryKeystore(), new_proxy())
                try:
                    # upload directly (not participate()): the probe is an
                    # upload-level attack and must reach the server even
                    # when a leftover participant.dies kill budget would
                    # silently swallow a participate() call
                    probe.upload_participation(probe.new_participation(
                        [int(x + 1) % modulus for x in row], agg.id))
                except ParticipationConflict:
                    pass  # detected: counted server-side as equivocation
                else:
                    equivocations_undetected += 1
            recipient.end_aggregation(agg.id)  # snapshot + job fan-out

            brownout_started = None
            if brownout_s:
                # the store browns out MID-CLERKING: fan-out is durable,
                # the committee is about to hammer the job-poll and
                # result-write paths — elevated error rate + latency for
                # the seeded window, breaker in front
                brownout_started = time.monotonic()
                chaos.configure("store.poll_clerking_job", brownout=0.01,
                                rate=0.85, window=brownout_s, seed=seed)
                chaos.configure("store.create_clerking_result",
                                brownout=0.01, rate=0.85,
                                window=brownout_s, seed=seed)

            def round_state():
                try:
                    return recipient.service.get_round_status(
                        recipient.agent, agg.id)
                except Exception:  # chaos'd poll: state is best-effort
                    return None

            # clerks keep polling until the round's completion condition:
            # with NO dead clerks, EVERY job has a result — waiting for
            # the full committee (not just reconstruction_threshold) is
            # what forces the abandoned job through the lease-expiry
            # reissue path. With dead clerks, the supervisor's verdict is
            # the exit: degraded + a reconstructing quorum, or terminal
            # failed (additive) — deterministically, instead of hanging.
            threshold = scheme.reconstruction_threshold
            deadline = time.monotonic() + timeout_s
            ready = False
            final_round = None
            while time.monotonic() < deadline:
                for clerk in clerks:
                    try:
                        clerk.run_chores(-1)
                    except ServerError:
                        # a brownout window can outlast even the padded
                        # transport retry budget: the clerk is fine, the
                        # dependency is not — come back next pass
                        metrics.count("clerk.chores.transient")
                try:
                    status = recipient.service.get_aggregation_status(
                        recipient.agent, agg.id
                    )
                except ServerError:
                    metrics.count("recipient.status.transient")
                    status = None
                results = (status.snapshots[0].number_of_clerking_results
                           if status is not None and status.snapshots else 0)
                if not dead_clerks and results >= scheme.share_count:
                    ready = True
                    break
                if dead_clerks:
                    final_round = round_state()
                    if final_round is not None:
                        if final_round.state == "failed":
                            break
                        if (final_round.state == "degraded"
                                and results >= threshold):
                            ready = True
                            break
                time.sleep(min(0.1, lease_seconds / 4))

            exact = False
            failure = None
            if ready:
                # the lifecycle-aware blocking reveal: returns the output,
                # or raises the typed verdict with the server's diagnosis
                output = recipient.await_result(
                    agg.id, deadline=max(1.0, deadline - time.monotonic()))
                expected = (np.stack(alive_rows).sum(axis=0) % modulus
                            if alive_rows else np.zeros(dim, dtype=np.int64))
                exact = bool((output.positive().values == expected).all())
            elif dead_clerks:
                try:
                    recipient.await_result(agg.id, deadline=1.0,
                                           poll_interval=0.05)
                except RoundFailed as e:  # RoundExpired is a subclass
                    failure = {
                        "type": type(e).__name__,
                        "state": e.state,
                        "reason": e.reason,
                        "dead_clerks": [str(c) for c in e.dead_clerks],
                    }
            final_round = round_state() or final_round
            # zero-double-count audit: the aggregation-wide admitted count
            # must equal the unique devices that ever landed — a surplus
            # is a double count, the exact failure exactly-once ingestion
            # exists to make impossible
            admitted = None
            try:
                final_status = recipient.service.get_aggregation_status(
                    recipient.agent, agg.id)
                if final_status is not None:
                    admitted = final_status.number_of_participations
            except Exception:  # chaos'd poll: the audit is best-effort
                pass
    finally:
        # snapshot the schedule, then disarm BEFORE shutdown so teardown
        # requests aren't chaos'd
        failpoint_report = chaos.report()
        chaos.reset()
        if sweeper is not None:
            sweeper.stop()
        http_server.shutdown()
        journal_dir.cleanup()

    from ..loadgen import latency_report_ms as _latency_report_ms

    counters = metrics.counter_report()
    injected = sum(v for k, v in counters.items() if k.startswith("chaos."))
    # request-level failure accounting: dispatch 500s and store faults are
    # already inside http.request (they produce a counted 500 reply);
    # dropped responses bail out before the counter, so add them back
    failed_requests = sum(
        v for k, v in counters.items()
        if k.startswith(("chaos.http.server.", "chaos.store."))
    )
    dropped = counters.get("chaos.http.server.response", 0)
    requests_total = counters.get("http.request", 0) + dropped
    # the round timeline: slowest-first, so [0] is the drill's round trace
    # (every span shares its trace id); chaos_events names each injection
    # and the span it hit, critical_path the chain that set round duration
    timelines = obs.round_timelines()

    def _phase_gap(history, start_state, end_state):
        """Server-stamped seconds between two lifecycle transitions."""
        stamps = {state: ts for state, ts in (history or [])}
        if start_state in stamps and end_state in stamps:
            return round(stamps[end_state] - stamps[start_state], 4)
        return None

    round_history = (final_round.history
                     if dead_clerks and final_round is not None else None)
    breaker_report = breaker.report() if breaker is not None else None
    pickup_summary = metrics.histogram_report("server.job.pickup").get(
        "server.job.pickup")
    report = {
        "mode": (f"chaos drill over HTTP ({store} store, "
                 f"{'async' if async_http else 'threaded'} plane)"),
        "http_plane": "async" if async_http else "threaded",
        "participants": participants,
        "dim": dim,
        "clerks": scheme.share_count,
        "sharing": sharing,
        "dead_clerks": dead_clerks,
        "dead_participants": dead_participants,
        "rate": rate,
        "seed": seed,
        "lease_seconds": lease_seconds,
        "ready": ready,
        "exact": exact,
        # round lifecycle verdict (server/lifecycle.py): terminal state,
        # transition history with server-side stamps, and the diagnosis —
        # plus the BENCH-style detection latencies the regress gate
        # tracks advisory (ci.sh dead-clerk drill)
        "round_state": (final_round.state
                        if final_round is not None else None),
        "round_reason": (final_round.reason
                         if final_round is not None else None),
        "round_dead_clerks": ([str(c) for c in final_round.dead_clerks]
                              if final_round is not None else None),
        "round_history": round_history,
        "time_to_degraded_s": _phase_gap(round_history, "clerking",
                                         "degraded"),
        "time_to_failed_s": _phase_gap(round_history, "clerking", "failed"),
        # brownout-recovery verdict (server/breaker.py): how long the
        # store was effectively down from the breaker's point of view —
        # first trip to final recovery, the MTTR headline ci.sh records
        "brownout_s": brownout_s or None,
        "breaker": breaker_report,
        "time_to_recover_s": (breaker_report or {}).get("time_to_recover_s"),
        # device-churn verdict (exactly-once participation plane): every
        # departure rejoined and landed exactly once — mid-upload crashes
        # as byte-identical replays, the equivocation probe rejected, and
        # the admitted count exactly the unique-device count
        "churn_rate": churn_rate or None,
        "participants_churned": len(departed),
        "participants_resumed": resumed,
        "participations_replayed": counters.get(
            "server.participation.replayed", 0),
        "equivocations_detected": counters.get(
            "server.participation.equivocation", 0),
        "equivocations_undetected": equivocations_undetected,
        "admitted_participations": admitted,
        "double_counted": (None if admitted is None
                           else admitted - len(alive_rows)),
        "time_to_resume_s": (round(time_to_resume_s, 4)
                             if churn_rate else None),
        "failure": failure,
        "injected_faults": injected,
        "failed_requests": failed_requests,
        "injected_ratio": round(failed_requests / max(1, requests_total), 4),
        "failpoints": failpoint_report or None,
        "counters": {
            k: v for k, v in counters.items()
            if k.startswith(("chaos.", "http.retry.", "http.status.",
                             "server.job.", "server.snapshot.",
                             "server.participation.", "participant.",
                             "server.store.breaker.", "server.fleet."))
        },
        # per-route server latency under fire: the tail the retry budget
        # has to ride out (loadgen measures the same table under load)
        "latency_ms": _latency_report_ms(),
        # enqueue->lease latency (server.job.pickup): the long-poll
        # plane's headline metric, surfaced here so the chaos drill's
        # fixed-seed A/B carries it too (docs/load.md)
        "job_pickup_ms": ({
            "count": int(pickup_summary["count"]),
            "p50_ms": round(pickup_summary["p50"] * 1e3, 3),
            "p99_ms": round(pickup_summary["p99"] * 1e3, 3),
        } if pickup_summary else None),
        "trace": timelines[0] if timelines else None,
    }
    return report
