"""Deterministic chaos layer: seedable failpoint injection.

SDA's premise is surviving weak, sporadic devices (PAPER.md), so the
failure modes themselves must be first-class and *reproducible*. This
package is the injection side of that story: a process-global registry of
named failpoints, each with a deterministic trigger schedule, hooked into
the store backends (``store.*``), the HTTP dispatch (``http.server.*``)
and the clerk loop (``clerk.*``). The recovery side lives in
``http/client.py`` (retrying transport) and ``server/core.py`` +
the store backends (clerking-job lease/reissue); ``docs/robustness.md``
has the full catalog.

Design follows the classic failpoint idiom (FreeBSD ``fail(9)``, Rust's
``fail-rs``): production code calls ``chaos.fail("name")`` at a choke
point; the call is a near-free no-op until a test or the ``sda-sim
--chaos`` profile configures that name with an action:

    chaos.configure("store.create_participation", error=True, times=2)
    chaos.configure("http.server.request", error=True, rate=0.15, seed=7)
    chaos.configure("http.server.request", delay=0.05, every=3)
    chaos.configure("http.server.response", drop=True, times=1)

Beyond the crisp single-shot kinds (error/delay/drop/kill), three GRAY
failure kinds model the degradation that dominates production fleets
("The Tail at Scale", Dean & Barroso, CACM 2013) — a dependency that is
slow-but-alive, browning out, or reachable from some peers only:

    # elevated latency + elevated error rate for a bounded window
    chaos.configure("store.poll_clerking_job", brownout=0.02, rate=0.7,
                    window=5.0, seed=7)
    # repeating brownout cycles: `window` seconds down, `up` seconds fine
    chaos.configure("store.poll_clerking_job", flap=0.02, rate=0.7,
                    window=1.0, up=2.0, seed=7)
    # scoped connectivity loss: only the process whose chaos identity is
    # "w0" (chaos.set_identity) sees its store ops fail, healing after 3 s
    chaos.configure("store.create_clerking_result", partition=True,
                    node="w0", window=3.0)

Brownout/flap hits inside the down window raise the injected error with
probability ``rate`` and stall ``delay`` seconds otherwise; outside the
window they are clean no-ops (and do not consume triggers). A partition
raises on every in-window hit whose scope matches: ``node=`` matches the
process-global identity (``set_identity``, set by ``sdad --node-id``),
``agent=`` matches the caller id the call site passes via
``evaluate(..., ctx={"agent": ...})``.

Determinism: each failpoint owns a ``random.Random`` seeded from
``(seed, name)`` and all trigger decisions are functions of the hit index
only, taken under one lock — the same hit sequence always produces the
same injection schedule, so a failing chaos run replays exactly. The
gray kinds keep that discipline for the per-hit error/delay choice; only
the window boundary itself is wall-clock (anchored at arming time).

Every trigger is counted under ``chaos.<name>`` in ``utils/metrics.py``;
``report()`` additionally returns per-point hit/trigger tallies.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional

from ..protocol import ServerError
from ..utils import metrics


class InjectedFault(ServerError):
    """The default injected error: an ``SdaError`` so the HTTP seam maps it
    to a 500 (a transient server-side failure, exactly what the retrying
    transport must absorb)."""


class PartitionedFault(InjectedFault):
    """A partition-kind injection: the scoped peer cannot reach the seam.
    Still a ``ServerError`` (HTTP 500 / retried) — a partitioned client
    cannot tell a dead dependency from an unreachable one."""


class Action:
    """What a triggered failpoint asks the call site to do.

    ``kind`` is one of ``"error"`` (raise ``exc``), ``"delay"`` (sleep
    ``delay_s`` then proceed), ``"drop"`` (transport-level: abort the
    connection / abandon the unit of work — only meaningful at call sites
    that know how, e.g. the HTTP handler or the clerk loop), or
    ``"kill"`` (permanent death: the agent whose loop hit the failpoint
    latches dead for the rest of the drill — unlike every other kind,
    which is transient, the call site never retries or recovers; see
    ``SdaClient.clerk_once`` / ``participate``), or ``"taint"``
    (adversarial-input corruption: the call site perturbs the data it
    was about to emit — e.g. ``participant.taint_shares`` lifts share
    vectors out of the field — instead of failing; only call sites that
    know how to corrupt express it).
    """

    __slots__ = ("kind", "exc", "delay_s")

    def __init__(self, kind: str, exc: Optional[BaseException] = None,
                 delay_s: float = 0.0):
        self.kind = kind
        self.exc = exc
        self.delay_s = delay_s

    def __repr__(self):
        return f"Action({self.kind!r})"


#: What primitive action kinds each gray (composite) kind realizes into.
_COMPOSITE_KINDS = {
    "brownout": ("error", "delay"),
    "flap": ("error", "delay"),
    "partition": ("error",),
}


class _Failpoint:
    def __init__(self, name: str, *, error=None, delay=None, drop=False,
                 kill=False, taint=False, brownout=None, flap=None,
                 partition=False,
                 rate: Optional[float] = None, times: Optional[int] = None,
                 every: Optional[int] = None, after: int = 0, seed: int = 0,
                 window: Optional[float] = None, up: Optional[float] = None,
                 node: Optional[str] = None, agent: Optional[str] = None):
        if sum(x is not None and x is not False
               for x in (error, delay, brownout, flap)) \
                + bool(drop) + bool(kill) + bool(taint) \
                + bool(partition) != 1:
            raise ValueError(f"failpoint {name!r}: exactly one of error/"
                             "delay/drop/kill/taint/brownout/flap/partition "
                             "must be set")
        if every is not None and every < 1:
            raise ValueError(f"failpoint {name!r}: every must be >= 1")
        self.name = name
        if kill:
            self.kind = "kill"
        elif taint:
            self.kind = "taint"
        elif drop:
            self.kind = "drop"
        elif partition:
            self.kind = "partition"
        elif flap is not None:
            self.kind = "flap"
        elif brownout is not None:
            self.kind = "brownout"
        elif delay is not None:
            self.kind = "delay"
        else:
            self.kind = "error"
        # error=True means "use the default injected fault"
        self.exc_factory = (
            (error if callable(error) else (lambda: error))
            if error is not None and error is not True
            else (lambda: PartitionedFault(
                f"chaos: partitioned at {name}"))
            if self.kind == "partition"
            else (lambda: InjectedFault(f"chaos: injected failure at {name}"))
        )
        self.delay_s = float(delay or brownout or flap or 0.0)
        # gray-kind rate is the ERROR fraction inside the down window (the
        # rest of the hits stall instead); default 0.5 keeps both symptoms
        # visible. Classic kinds keep the historical always-trigger default.
        if rate is None:
            rate = 0.5 if self.kind in ("brownout", "flap") else 1.0
        self.rate = float(rate)
        self.times = times
        self.every = every
        self.after = int(after)
        if self.kind == "flap" and (not window or up is None):
            raise ValueError(f"failpoint {name!r}: flap needs window= "
                             "(down seconds) and up= (healthy seconds)")
        if self.kind == "brownout" and not window:
            raise ValueError(f"failpoint {name!r}: brownout needs window= "
                             "(down seconds)")
        self.window_s = None if window is None else float(window)
        self.up_s = None if up is None else float(up)
        #: partition scope: restrict triggering to the process whose chaos
        #: identity is ``node`` and/or to call sites whose ctx carries
        #: ``agent`` — None matches everything
        self.node = node
        self.agent = agent
        #: window anchor: gray kinds degrade from the moment they are armed
        self.armed_at = time.time()
        # per-point RNG keyed on (seed, name): schedules are independent
        # across failpoints and reproducible for a given hit order
        self.rng = random.Random(f"{seed}:{name}")
        self.hits = 0
        self.triggers = 0

    def expressible(self, kinds) -> bool:
        """Whether a call site restricted to ``kinds`` can perform this
        point's action at all (composite kinds need every primitive they
        may realize into, so the seeded schedule stays site-independent)."""
        if kinds is None:
            return True
        needed = _COMPOSITE_KINDS.get(self.kind, (self.kind,))
        return all(k in kinds for k in needed)

    def _in_window(self, now: float) -> bool:
        """Whether a gray kind is currently in its DOWN phase."""
        elapsed = now - self.armed_at
        if self.kind == "flap":
            return elapsed % (self.window_s + self.up_s) < self.window_s
        if self.window_s is None:
            return True  # unbounded (partition without window=): heals
            # only on clear()
        return elapsed < self.window_s

    def _scope_matches(self, ctx, identity) -> bool:
        if self.node is not None and self.node != identity:
            return False
        if self.agent is not None:
            return str((ctx or {}).get("agent")) == self.agent
        return True

    def should_trigger(self) -> bool:
        """Decide for the current hit; caller holds the registry lock."""
        hit = self.hits
        self.hits += 1
        if hit < self.after:
            return False
        if self.times is not None and self.triggers >= self.times:
            return False
        if self.every is not None and (hit - self.after) % self.every != 0:
            return False
        if self.rate < 1.0 and self.rng.random() >= self.rate:
            return False
        self.triggers += 1
        return True

    def action(self) -> Action:
        if self.kind in ("error", "partition"):
            return Action("error", exc=self.exc_factory())
        if self.kind == "delay":
            return Action("delay", delay_s=self.delay_s)
        return Action(self.kind)  # "drop"/"kill"/"taint": no payload

    def realize(self, now: float, ctx, identity) -> Optional[Action]:
        """The full per-hit decision (caller holds the registry lock):
        classic kinds keep the historic should_trigger/action split; gray
        kinds additionally gate on the window and scope — an out-of-window
        or out-of-scope hit is a clean no-op that consumes NOTHING, so the
        seeded schedule describes only the degraded phase."""
        if self.kind in ("brownout", "flap"):
            if not self._in_window(now):
                return None
            hit = self.hits
            self.hits += 1
            if hit < self.after:
                return None
            if self.times is not None and self.triggers >= self.times:
                return None
            if self.every is not None and (hit - self.after) % self.every:
                return None
            self.triggers += 1
            # seeded per-hit split: error with probability `rate`, stall
            # otherwise — both symptoms of one browning-out dependency
            if self.rng.random() < self.rate:
                return Action("error", exc=self.exc_factory())
            return Action("delay", delay_s=self.delay_s)
        if self.kind == "partition":
            if not self._scope_matches(ctx, identity) \
                    or not self._in_window(now):
                return None
            if not self.should_trigger():
                return None
            return Action("error", exc=self.exc_factory())
        if not self.should_trigger():
            return None
        return self.action()


class FailpointRegistry:
    """Thread-safe named-failpoint store. One process-global instance
    (module-level ``configure``/``fail``/... below) serves both sides of
    an in-process round; tests may build private registries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._points: Dict[str, _Failpoint] = {}
        #: process identity for partition scoping (``sdad --node-id``)
        self._identity: Optional[str] = None

    def set_identity(self, node_id: Optional[str]) -> None:
        """Name this process for ``partition`` scoping: a spec with
        ``node=w0`` triggers only in the process whose identity is w0 —
        how one fleet-wide spec partitions exactly one worker from the
        shared store."""
        self._identity = node_id

    def configure(self, name: str, **kwargs) -> None:
        """(Re)arm a failpoint; see module docstring for the knobs."""
        point = _Failpoint(name, **kwargs)
        with self._lock:
            self._points[name] = point

    def clear(self, name: Optional[str] = None) -> None:
        """Disarm one failpoint, or all of them."""
        with self._lock:
            if name is None:
                self._points.clear()
            else:
                self._points.pop(name, None)

    def active(self) -> bool:
        return bool(self._points)

    def evaluate(self, name: str, kinds=None, ctx=None) -> Optional[Action]:
        """Return the action if ``name`` is armed and triggers this hit,
        else None. Counts ``chaos.<name>`` on trigger. The un-armed path
        is one dict lookup — cheap enough for hot paths.

        ``kinds`` restricts the action kinds the call site can express
        (e.g. the clerk loop only understands ``drop``); an armed
        failpoint of another kind is ignored WITHOUT consuming a hit or
        trigger, so the schedule and counters never claim an injection
        that could not happen. ``ctx`` carries call-site scope facts
        (currently ``{"agent": id}``) that ``partition`` specs match."""
        point = self._points.get(name)
        if point is None:
            return None
        if not point.expressible(kinds):
            return None
        now = time.time()
        with self._lock:
            # re-check: a concurrent clear() may have raced the lookup
            if self._points.get(name) is not point:
                return None
            action = point.realize(now, ctx, self._identity)
            if action is None:
                return None
        metrics.count(f"chaos.{name}")
        # stamp the injection on the active span (no-op without one): a
        # trace timeline then shows WHICH injected fault hit WHICH round.
        # fault.kind/fault.site are the structured tags sda-trace explain
        # joins on; the bare "kind" attr stays for older consumers.
        from .. import obs
        from ..obs import recorder, trace

        obs.add_event(f"chaos.{name}", kind=action.kind,
                      **{"fault.kind": action.kind, "fault.site": name})
        ctx_span = trace.current_span()
        recorder.record({
            "t": "fault",
            "site": name,
            "kind": action.kind,
            "node": self._identity,
            "trace": ctx_span.trace_id if ctx_span else None,
            "span": ctx_span.span_id if ctx_span else None,
        })
        return action

    def fail(self, name: str) -> Optional[Action]:
        """The standard injection hook: raise on ``error``, sleep on
        ``delay``. ``drop`` is transport-level and inexpressible here, so
        a drop-armed point is ignored unconsumed (use ``evaluate`` with
        ``kinds`` at call sites that can drop)."""
        action = self.evaluate(name, kinds=("error", "delay"))
        if action is None:
            return None
        if action.kind == "error":
            raise action.exc
        time.sleep(action.delay_s)
        return action

    def report(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                name: {"hits": p.hits, "triggers": p.triggers}
                for name, p in sorted(self._points.items())
            }


#: The process-global registry every built-in hook consults.
registry = FailpointRegistry()

configure = registry.configure
clear = registry.clear
evaluate = registry.evaluate
fail = registry.fail
report = registry.report
set_identity = registry.set_identity


def reset() -> None:
    """Disarm everything — test-teardown hygiene (the identity is config,
    not schedule state: it survives)."""
    registry.clear()


def churn_schedule(agents: int, rate: float, seed: int = 0,
                   epoch: Optional[int] = None) -> list:
    """Seeded per-agent device-churn plan — the sporadic-device model
    (PAPER.md's weak phones) made deterministic, the same ``(seed, name)``
    RNG discipline every failpoint keeps.

    Each of ``agents`` entries decides whether that agent DEPARTS during
    its participation (probability ``rate``) and, for departures, at
    which crash point — alternating deterministically by departure
    ordinal so any plan with at least one departure exercises both:

    - ``"mid-upload"`` (first, third, ... departure): the crash lands
      AFTER the server durably stored the bundle but BEFORE the device
      learned of it — the lost-ack window, the ``kill`` analog of the
      ``http.server.response`` drop. The rejoin's journal resume is a
      byte-identical replay (``server.participation.replayed``).
    - ``"pre-upload"`` (second, fourth, ...): the crash lands after
      sealing + journaling but before any upload; the rejoin's resume is
      the bundle's FIRST arrival.

    Every departure rejoins (``"rejoins": True``) — permanent death
    already has its own failpoint (``participant.dies``, kind ``kill``)
    and composes freely with this plan. Drills iterate the plan; the
    drill, not this schedule, performs the crash/rejoin, which keeps the
    plan reusable by both ``sda-sim --chaos --churn`` and the loadgen
    churn knob (docs/robustness.md).

    ``epoch`` folds a round/epoch index into the RNG key, so a recurring
    workload (the FL scenario's R rounds, a soak's epochs) gets an
    independent-but-reproducible availability plan per round from ONE
    seed — who is offline in round 3 does not depend on who was offline
    in round 2, but both replay exactly."""
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"churn rate {rate} outside [0, 1]")
    key = f"{seed}:churn" if epoch is None else f"{seed}:churn:{int(epoch)}"
    rng = random.Random(key)
    plan = []
    departures = 0
    for index in range(agents):
        departs = rng.random() < rate
        phase = None
        if departs:
            phase = "mid-upload" if departures % 2 == 0 else "pre-upload"
            departures += 1
        plan.append({"index": index, "departs": departs, "phase": phase,
                     "rejoins": departs})
    return plan


# adversarial-input poisoning (seeded attacker populations) shares the
# chaos namespace: same determinism discipline, different threat model
from .poison import (POISON_KINDS, corrupt_delta,  # noqa: E402,F401
                     parse_poison_kind, poison_schedule)

#: spec keys -> coercion; None means "keep the string"
_SPEC_KEYS = {
    "rate": float, "times": int, "every": int, "after": int,
    "for": float, "up": float, "node": None, "agent": None,
}


def parse_spec(spec: str, seed: int = 0) -> Dict[str, dict]:
    """Parse a compact failpoint spec into ``{name: configure-kwargs}``
    WITHOUT arming anything (CLI / env friendly):

        "http.server.request=error,rate=0.15;clerk.dies=kill,times=1"
        "store.poll_clerking_job,store.create_clerking_result=\
brownout:0.02,rate=0.7,for=5"
        "store.create_participation=partition,node=w0,for=3"

    Each ``;``-separated entry is ``names=kind[,key=value...]`` where
    ``names`` may be several comma-separated failpoint names sharing one
    action (the ``,`` before the first ``=`` separates targets; after it,
    keys). Kinds: error | delay:SECONDS | drop | kill | taint |
    brownout:SECONDS | flap:SECONDS | partition. Keys:
    rate/times/every/after plus the
    gray-kind window ``for=SECONDS``, flap's healthy phase ``up=SECONDS``,
    and partition scope ``node=``/``agent=``.

    Naming the same failpoint twice IN ONE parse is a conflict and raises
    — two actions cannot share one choke point; ``configure_from_specs``
    extends that check across multiple ``--chaos-spec`` flags."""
    out: Dict[str, dict] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        names, _, rest = entry.partition("=")
        if not rest:
            raise ValueError(f"chaos spec entry {entry!r}: expected name=kind[,...]")
        parts = rest.split(",")
        kind = parts[0].strip()
        kwargs: dict = {"seed": seed}
        if kind == "error":
            kwargs["error"] = True
        elif kind == "drop":
            kwargs["drop"] = True
        elif kind == "kill":
            kwargs["kill"] = True
        elif kind == "taint":
            kwargs["taint"] = True
        elif kind == "partition":
            kwargs["partition"] = True
        elif kind.startswith("delay:"):
            kwargs["delay"] = float(kind.split(":", 1)[1])
        elif kind.startswith("brownout:"):
            kwargs["brownout"] = float(kind.split(":", 1)[1])
        elif kind.startswith("flap:"):
            kwargs["flap"] = float(kind.split(":", 1)[1])
        else:
            raise ValueError(f"chaos spec entry {entry!r}: unknown kind {kind!r}")
        for part in parts[1:]:
            key, _, value = part.strip().partition("=")
            coerce = _SPEC_KEYS.get(key, ...)
            if coerce is ...:
                raise ValueError(f"chaos spec entry {entry!r}: unknown key {key!r}")
            # "for" is the spec spelling of the window (python keyword)
            kwargs["window" if key == "for" else key] = (
                value if coerce is None else coerce(value))
        for name in names.split(","):
            name = name.strip()
            if not name:
                raise ValueError(f"chaos spec entry {entry!r}: empty "
                                 "failpoint name")
            if name in out:
                raise ValueError(
                    f"chaos spec conflict: failpoint {name!r} armed twice "
                    f"(second action {kind!r}) — one choke point takes "
                    "exactly one action; merge or drop one entry")
            out[name] = kwargs
    return out


def configure_from_spec(spec: str, seed: int = 0) -> None:
    """Parse ``spec`` (see :func:`parse_spec`) and arm every entry."""
    for name, kwargs in parse_spec(spec, seed=seed).items():
        configure(name, **kwargs)


def configure_from_specs(specs, seed: int = 0) -> None:
    """Arm several spec strings (repeated ``--chaos-spec`` flags) as one
    composed drill — brownout + kill + partition in one invocation —
    rejecting any failpoint named by more than one spec with a clear
    error that says WHICH flag collided."""
    seen: Dict[str, int] = {}
    parsed = []
    for ix, spec in enumerate(specs):
        entries = parse_spec(spec, seed=seed)
        for name in entries:
            if name in seen:
                raise ValueError(
                    f"chaos spec conflict: failpoint {name!r} is armed by "
                    f"--chaos-spec #{seen[name] + 1} and #{ix + 1}; one "
                    "choke point takes exactly one action")
            seen[name] = ix
        parsed.append(entries)
    for entries in parsed:
        for name, kwargs in entries.items():
            configure(name, **kwargs)
