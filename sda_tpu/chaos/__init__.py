"""Deterministic chaos layer: seedable failpoint injection.

SDA's premise is surviving weak, sporadic devices (PAPER.md), so the
failure modes themselves must be first-class and *reproducible*. This
package is the injection side of that story: a process-global registry of
named failpoints, each with a deterministic trigger schedule, hooked into
the store backends (``store.*``), the HTTP dispatch (``http.server.*``)
and the clerk loop (``clerk.*``). The recovery side lives in
``http/client.py`` (retrying transport) and ``server/core.py`` +
the store backends (clerking-job lease/reissue); ``docs/robustness.md``
has the full catalog.

Design follows the classic failpoint idiom (FreeBSD ``fail(9)``, Rust's
``fail-rs``): production code calls ``chaos.fail("name")`` at a choke
point; the call is a near-free no-op until a test or the ``sda-sim
--chaos`` profile configures that name with an action:

    chaos.configure("store.create_participation", error=True, times=2)
    chaos.configure("http.server.request", error=True, rate=0.15, seed=7)
    chaos.configure("http.server.request", delay=0.05, every=3)
    chaos.configure("http.server.response", drop=True, times=1)

Determinism: each failpoint owns a ``random.Random`` seeded from
``(seed, name)`` and all trigger decisions are functions of the hit index
only, taken under one lock — the same hit sequence always produces the
same injection schedule, so a failing chaos run replays exactly.

Every trigger is counted under ``chaos.<name>`` in ``utils/metrics.py``;
``report()`` additionally returns per-point hit/trigger tallies.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional

from ..protocol import ServerError
from ..utils import metrics


class InjectedFault(ServerError):
    """The default injected error: an ``SdaError`` so the HTTP seam maps it
    to a 500 (a transient server-side failure, exactly what the retrying
    transport must absorb)."""


class Action:
    """What a triggered failpoint asks the call site to do.

    ``kind`` is one of ``"error"`` (raise ``exc``), ``"delay"`` (sleep
    ``delay_s`` then proceed), ``"drop"`` (transport-level: abort the
    connection / abandon the unit of work — only meaningful at call sites
    that know how, e.g. the HTTP handler or the clerk loop), or
    ``"kill"`` (permanent death: the agent whose loop hit the failpoint
    latches dead for the rest of the drill — unlike every other kind,
    which is transient, the call site never retries or recovers; see
    ``SdaClient.clerk_once`` / ``participate``). ``times=K`` kills the
    first K distinct agents to hit the point, since a latched-dead agent
    stops consuming hits.
    """

    __slots__ = ("kind", "exc", "delay_s")

    def __init__(self, kind: str, exc: Optional[BaseException] = None,
                 delay_s: float = 0.0):
        self.kind = kind
        self.exc = exc
        self.delay_s = delay_s

    def __repr__(self):
        return f"Action({self.kind!r})"


class _Failpoint:
    def __init__(self, name: str, *, error=None, delay=None, drop=False,
                 kill=False, rate: float = 1.0, times: Optional[int] = None,
                 every: Optional[int] = None, after: int = 0, seed: int = 0):
        if sum(x is not None and x is not False for x in (error, delay)) \
                + bool(drop) + bool(kill) != 1:
            raise ValueError(f"failpoint {name!r}: exactly one of "
                             "error/delay/drop/kill must be set")
        if every is not None and every < 1:
            raise ValueError(f"failpoint {name!r}: every must be >= 1")
        self.name = name
        if kill:
            self.kind = "kill"
        elif drop:
            self.kind = "drop"
        elif delay is not None:
            self.kind = "delay"
        else:
            self.kind = "error"
        # error=True means "use the default injected fault"
        self.exc_factory = (
            (lambda: InjectedFault(f"chaos: injected failure at {name}"))
            if error is True or error is None
            else (error if callable(error) else (lambda: error))
        )
        self.delay_s = float(delay or 0.0)
        self.rate = float(rate)
        self.times = times
        self.every = every
        self.after = int(after)
        # per-point RNG keyed on (seed, name): schedules are independent
        # across failpoints and reproducible for a given hit order
        self.rng = random.Random(f"{seed}:{name}")
        self.hits = 0
        self.triggers = 0

    def should_trigger(self) -> bool:
        """Decide for the current hit; caller holds the registry lock."""
        hit = self.hits
        self.hits += 1
        if hit < self.after:
            return False
        if self.times is not None and self.triggers >= self.times:
            return False
        if self.every is not None and (hit - self.after) % self.every != 0:
            return False
        if self.rate < 1.0 and self.rng.random() >= self.rate:
            return False
        self.triggers += 1
        return True

    def action(self) -> Action:
        if self.kind == "error":
            return Action("error", exc=self.exc_factory())
        if self.kind == "delay":
            return Action("delay", delay_s=self.delay_s)
        return Action(self.kind)  # "drop" or "kill": no payload


class FailpointRegistry:
    """Thread-safe named-failpoint store. One process-global instance
    (module-level ``configure``/``fail``/... below) serves both sides of
    an in-process round; tests may build private registries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._points: Dict[str, _Failpoint] = {}

    def configure(self, name: str, **kwargs) -> None:
        """(Re)arm a failpoint; see module docstring for the knobs."""
        point = _Failpoint(name, **kwargs)
        with self._lock:
            self._points[name] = point

    def clear(self, name: Optional[str] = None) -> None:
        """Disarm one failpoint, or all of them."""
        with self._lock:
            if name is None:
                self._points.clear()
            else:
                self._points.pop(name, None)

    def active(self) -> bool:
        return bool(self._points)

    def evaluate(self, name: str, kinds=None) -> Optional[Action]:
        """Return the action if ``name`` is armed and triggers this hit,
        else None. Counts ``chaos.<name>`` on trigger. The un-armed path
        is one dict lookup — cheap enough for hot paths.

        ``kinds`` restricts the action kinds the call site can express
        (e.g. the clerk loop only understands ``drop``); an armed
        failpoint of another kind is ignored WITHOUT consuming a hit or
        trigger, so the schedule and counters never claim an injection
        that could not happen."""
        point = self._points.get(name)
        if point is None:
            return None
        if kinds is not None and point.kind not in kinds:
            return None
        with self._lock:
            # re-check: a concurrent clear() may have raced the lookup
            if self._points.get(name) is not point or not point.should_trigger():
                return None
            action = point.action()
        metrics.count(f"chaos.{name}")
        # stamp the injection on the active span (no-op without one): a
        # trace timeline then shows WHICH injected fault hit WHICH round
        from .. import obs

        obs.add_event(f"chaos.{name}", kind=action.kind)
        return action

    def fail(self, name: str) -> Optional[Action]:
        """The standard injection hook: raise on ``error``, sleep on
        ``delay``. ``drop`` is transport-level and inexpressible here, so
        a drop-armed point is ignored unconsumed (use ``evaluate`` with
        ``kinds`` at call sites that can drop)."""
        action = self.evaluate(name, kinds=("error", "delay"))
        if action is None:
            return None
        if action.kind == "error":
            raise action.exc
        time.sleep(action.delay_s)
        return action

    def report(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                name: {"hits": p.hits, "triggers": p.triggers}
                for name, p in sorted(self._points.items())
            }


#: The process-global registry every built-in hook consults.
registry = FailpointRegistry()

configure = registry.configure
clear = registry.clear
evaluate = registry.evaluate
fail = registry.fail
report = registry.report


def reset() -> None:
    """Disarm everything — test-teardown hygiene."""
    registry.clear()


def configure_from_spec(spec: str, seed: int = 0) -> None:
    """Arm failpoints from a compact string (CLI / env friendly):

        "http.server.request=error,rate=0.15;clerk.dies=kill,times=1"

    Each ``;``-separated entry is ``name=kind[,key=value...]`` with kind in
    error|delay:SECONDS|drop|kill and keys rate/times/every/after.
    """
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition("=")
        if not rest:
            raise ValueError(f"chaos spec entry {entry!r}: expected name=kind[,...]")
        parts = rest.split(",")
        kind = parts[0].strip()
        kwargs: dict = {"seed": seed}
        if kind == "error":
            kwargs["error"] = True
        elif kind == "drop":
            kwargs["drop"] = True
        elif kind == "kill":
            kwargs["kill"] = True
        elif kind.startswith("delay:"):
            kwargs["delay"] = float(kind.split(":", 1)[1])
        else:
            raise ValueError(f"chaos spec entry {entry!r}: unknown kind {kind!r}")
        for part in parts[1:]:
            key, _, value = part.strip().partition("=")
            if key not in ("rate", "times", "every", "after"):
                raise ValueError(f"chaos spec entry {entry!r}: unknown key {key!r}")
            kwargs[key] = float(value) if key == "rate" else int(value)
        configure(name.strip(), **kwargs)
