"""Seeded poisoning populations: adversarial-INPUT chaos.

The failpoint registry models infrastructure failure (errors, delays,
death); this module models the other production threat a million-device
FL service faces — devices that run the protocol *correctly* but feed it
*malicious* inputs (PAPER.md's threat model is honest-but-curious, so
the reveal proves the sum is exact without saying anything about whether
the summands are honest). Poisoning keeps the chaos layer's determinism
discipline: attacker selection is a pure function of ``(seed, epoch)``
exactly like :func:`~sda_tpu.chaos.churn_schedule`, so a poisoned drill
replays bit-for-bit and an A/B against the clean run is meaningful.

Three attack kinds, each a corruption of the float model delta BEFORE
``FixedPointCodec.quantize`` (the attacker runs the standard client
stack — masking, sharing and the bit-exact reveal are untouched, which
is exactly why the protocol layer cannot catch this alone):

- ``boost:FACTOR`` — scale the delta by FACTOR (model-replacement /
  boosting attacks; negative factors flip AND amplify, the classic
  untargeted "push the global model away" move).
- ``signflip`` — negate the delta (gradient-ascent attacker; alias of
  ``boost:-1``).
- ``backdoor:TRIGGER_DIM`` — train on trigger-stamped inputs relabeled
  to class 0 (targeted attack; the corruption happens in the attacker's
  local TRAINING data, so the submitted delta is a genuinely-trained
  backdoor direction — see ``fl/data.py:apply_backdoor_trigger``).

Defenses live where the data flows: the codec clamps adversarial floats
and enforces an L2 norm bound by construction (``models/encoding.py``),
clerks count out-of-field share values (``clerk.share.out_of_range``),
and tree mode's root can take a trimmed mean over leaf subtotals
(``tree/round.py``). ``docs/robustness.md`` has the failure matrix.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

__all__ = ["parse_poison_kind", "poison_schedule", "corrupt_delta",
           "POISON_KINDS"]

#: the attack kinds ``--poison-kind`` accepts (spec grammar in parens)
POISON_KINDS = ("boost:FACTOR", "signflip", "backdoor:TRIGGER_DIM")


def parse_poison_kind(spec: str) -> Dict[str, object]:
    """Parse a ``--poison-kind`` spec into ``{"kind", "factor",
    "trigger_dim"}`` with typed errors (the same compact-grammar style as
    :func:`~sda_tpu.chaos.parse_spec`).

        parse_poison_kind("boost:-8")     -> kind=boost, factor=-8.0
        parse_poison_kind("signflip")     -> kind=signflip
        parse_poison_kind("backdoor:17")  -> kind=backdoor, trigger_dim=17
    """
    spec = (spec or "").strip()
    kind, _, arg = spec.partition(":")
    if kind == "signflip":
        if arg:
            raise ValueError(
                f"poison kind {spec!r}: signflip takes no argument")
        return {"kind": "signflip", "factor": -1.0, "trigger_dim": None}
    if kind == "boost":
        if not arg:
            raise ValueError(
                f"poison kind {spec!r}: boost needs a factor (boost:FACTOR)")
        try:
            factor = float(arg)
        except ValueError:
            raise ValueError(
                f"poison kind {spec!r}: boost factor {arg!r} is not a number")
        if factor == 1.0:
            raise ValueError(
                f"poison kind {spec!r}: boost:1 is the identity, not an "
                "attack")
        return {"kind": "boost", "factor": factor, "trigger_dim": None}
    if kind == "backdoor":
        if not arg:
            raise ValueError(
                f"poison kind {spec!r}: backdoor needs a trigger dimension "
                "(backdoor:TRIGGER_DIM)")
        try:
            trigger_dim = int(arg)
        except ValueError:
            raise ValueError(
                f"poison kind {spec!r}: trigger dim {arg!r} is not an int")
        if trigger_dim < 0:
            raise ValueError(
                f"poison kind {spec!r}: trigger dim must be >= 0")
        return {"kind": "backdoor", "factor": None,
                "trigger_dim": trigger_dim}
    raise ValueError(
        f"unknown poison kind {spec!r}; expected one of "
        f"{', '.join(POISON_KINDS)}")


def poison_schedule(agents: int, rate: float, seed: int = 0,
                    epoch: Optional[int] = None) -> List[dict]:
    """Seeded per-agent attacker plan — ``churn_schedule``'s exact
    ``(seed, epoch)`` RNG discipline applied to adversary selection:
    each of ``agents`` entries decides whether that agent is an ATTACKER
    this epoch (probability ``rate``). ``epoch`` folds the round index
    into the key so a recurring workload draws an independent-but-
    reproducible attacker set per round from one seed — who attacks in
    round 3 does not depend on round 2, but both replay exactly. The
    poison key is disjoint from the churn key, so churn + poison compose
    from one seed without correlating.

    The plan says WHO attacks; the drill (``fl/scenario.py``) applies
    the corruption, which keeps the plan reusable and the corruption
    testable in isolation (:func:`corrupt_delta`)."""
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"poison rate {rate} outside [0, 1]")
    key = f"{seed}:poison" if epoch is None else f"{seed}:poison:{int(epoch)}"
    rng = random.Random(key)
    return [{"index": index, "attacker": rng.random() < rate}
            for index in range(agents)]


def corrupt_delta(delta: np.ndarray, kind: Dict[str, object]) -> np.ndarray:
    """Apply a parsed attack kind to a float model delta. ``backdoor``
    is a no-op here — its corruption happens at training time (stamped,
    relabeled local data), so the delta is already the attack."""
    delta = np.asarray(delta)
    if kind["kind"] in ("boost", "signflip"):
        return delta * np.asarray(kind["factor"], dtype=delta.dtype)
    return delta
