"""Async event-loop HTTP plane — ``SdaAsyncHttpServer``.

The thread-per-connection plane (``http/server.py``) pays one OS thread
per *connection*; at the paper's deployment scale (millions of sporadic
devices against one broker, PAPER.md) that is tens of thousands of idle
stacks parked in ``readline``. This plane puts connections on an asyncio
event loop instead:

- **idle costs nothing**: a keep-alive socket between requests, or a
  clerk parked on a long-poll (``GET /v1/clerking-jobs?wait=S``), holds a
  coroutine — no thread, no stack;
- **handling is unchanged**: each request's auth/admission/service work
  runs on a bounded executor through the exact same shared dispatch core
  (``http/base.py``) the threaded plane uses — same route table, same
  admission ordering (tenant budget -> in-flight cap -> per-agent
  bucket), same chaos failpoint names, same span/`X-Request-Id`
  semantics, same drain contract. Fixed-seed drills are bit-exact across
  planes (ci.sh A/B step);
- **bodies stream**: request bodies are pulled by the handler on demand
  (admission sheds before a byte of body is read, exactly like the
  threaded plane) and hot-route binary uploads feed the incremental
  ``bincodec.FeedDecoder`` chunk by chunk — per-connection memory is
  O(frame), not O(body), for dim-1e8 uploads;
- **long-polls park on the loop**: a clerk waiting for work costs one
  subscription on the in-process job wakeup (``server/wakeup.py``) and
  one parked coroutine. Snapshot fan-out / lease handback / lease recall
  wake it immediately; cross-worker events degrade to the re-check tick.

Select with ``sdad --async``. Public surface mirrors ``SdaHttpServer``
(``address``/``start_background``/``serve_forever``/``drain``/
``shutdown``/``statusz``/``configure_admission``/``status_counts``/
``active_requests``) so every driver — fleet, loadgen, drills — can swap
planes with one flag. See docs/scaling.md (capacity table) and
docs/http.md (long-poll contract).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import logging
import os
import socket
import threading
import time
from http.client import responses as _STATUS_REASONS
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..protocol import AgentId, InvalidRequest
from ..protocol import bincodec
from ..server import SdaServerService
from ..server.routing import NODE_HEADER
from ..utils import metrics
from ..utils.env import env_float
from .. import chaos, obs
from ..obs import recorder
from . import base
from .admission import AdmissionControl, TENANT_HEADER
from .server import trace_log

log = logging.getLogger(__name__)

#: Bound on a request line / single header line (StreamReader limit).
_MAX_LINE = 65536
_MAX_HEADERS = 100
#: Streaming body chunk (matches the threaded plane's rfile reads).
_BODY_CHUNK = 65536
#: Per-chunk body read budget. Body reads run on the bounded executor
#: (handler threads); without a bound, one client advertising a
#: Content-Length and never sending the bytes pins an executor thread
#: forever — enough such sockets freeze the whole plane. Per-64KiB-chunk,
#: so any client sustaining > ~2 KiB/s is unaffected.
_BODY_READ_TIMEOUT = 30.0
#: Whole-body budget floor rate: the per-chunk bound alone still lets a
#: client TRICKLE a huge advertised body and pin an executor thread for
#: hours (executor-cap connections freeze the plane). The total read
#: budget is ``_BODY_READ_TIMEOUT + content_length / _BODY_MIN_RATE`` —
#: a dim-1e8 upload gets proportional time, a troller's 100 MB
#: Content-Length caps its occupancy at ~2 minutes.
_BODY_MIN_RATE = 1024 * 1024  # bytes/s


def _worker_count() -> int:
    configured = int(env_float("SDA_ASYNC_WORKERS", 0))
    if configured > 0:
        return configured
    return min(32, (os.cpu_count() or 2) * 8)


class _Headers:
    """Case-insensitive header view (first value wins, like the threaded
    plane's ``email.message`` headers for our routes)."""

    __slots__ = ("_d",)

    def __init__(self):
        self._d = {}

    def add(self, name: str, value: str) -> None:
        self._d.setdefault(name.lower(), value)

    def get(self, name: str, default=None):
        return self._d.get(name.lower(), default)


class _AsyncExchange:
    """Transport adapter for ``base.dispatch`` on the event-loop plane.

    Handler code runs on an executor thread; body bytes are pulled from
    the connection's StreamReader on demand via
    ``run_coroutine_threadsafe`` — so admission sheds before any body
    read, and streamed binary uploads never materialize whole."""

    __slots__ = ("server", "loop", "reader", "client_ip", "method", "path",
                 "query", "headers", "remaining", "t0", "request_id", "span",
                 "shed", "route_path", "counted", "close_connection",
                 "admitted", "_body_deadline")

    def __init__(self, server, loop, reader, client_ip, method, path, query,
                 headers, content_length):
        self.server = server
        self.loop = loop
        self.reader = reader
        self.client_ip = client_ip
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.remaining = content_length
        self.t0 = time.perf_counter()
        self.request_id = None
        self.span = None
        self.shed = False
        self.route_path = path or "/"
        self.counted = False
        self.close_connection = False
        self.admitted = False
        self._body_deadline = None

    # -- body (pulled from the loop, consumed on the executor) ----------
    def _read_chunk(self, n: int) -> bytes:
        # total-body budget: per-chunk alone lets a trickler pin this
        # executor thread for hours (see _BODY_MIN_RATE)
        if self._body_deadline is None:
            self._body_deadline = (time.monotonic() + _BODY_READ_TIMEOUT
                                   + self.remaining / _BODY_MIN_RATE)
        budget = min(_BODY_READ_TIMEOUT,
                     self._body_deadline - time.monotonic())
        if budget <= 0:
            self.close_connection = True
            raise InvalidRequest("request body read timed out")
        future = asyncio.run_coroutine_threadsafe(
            self.reader.readexactly(n), self.loop)
        try:
            return future.result(timeout=budget)
        except concurrent.futures.TimeoutError as e:
            future.cancel()
            self.close_connection = True
            raise InvalidRequest("request body read timed out") from e
        except (asyncio.IncompleteReadError, ConnectionError,
                RuntimeError) as e:  # RuntimeError: loop torn down mid-read
            self.close_connection = True
            raise InvalidRequest("truncated request body") from e

    def raw_body(self) -> bytes:
        out = []
        while self.remaining:
            n = min(_BODY_CHUNK, self.remaining)
            chunk = self._read_chunk(n)
            self.remaining -= len(chunk)
            out.append(chunk)
        return b"".join(out)

    def json_body(self):
        raw = self.raw_body()
        if not raw:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise InvalidRequest(f"malformed JSON body: {e}")

    def hot_body(self, expect_tag, from_obj):
        """Same contract as the threaded ``_hot_body``: negotiated binary
        streams through the incremental decoder, JSON falls back to the
        buffered parse; decode errors -> 400 after the body is consumed
        (keep-alive framing survives)."""
        ctype = (self.headers.get("Content-Type") or "")
        is_bin = (self.server.bin_codec and
                  ctype.split(";")[0].strip().lower() == bincodec.CONTENT_TYPE)
        if not is_bin:
            metrics.count("http.codec.json.in")
            return from_obj(self.json_body())
        metrics.count("http.codec.bin.in")
        decoder = bincodec.FeedDecoder(expect_tag)
        try:
            while self.remaining:
                chunk = self._read_chunk(min(_BODY_CHUNK, self.remaining))
                self.remaining -= len(chunk)
                decoder.feed(chunk)
            return decoder.finish()
        except ValueError:
            # leave self.remaining for the writer's bounded drain
            raise

    # -- identity -------------------------------------------------------
    def header(self, name: str):
        return self.headers.get(name)

    def credentials(self) -> Optional[Tuple[AgentId, str]]:
        return base.parse_basic_auth(self.headers.get("Authorization"))

    def agent_key(self) -> str:
        creds = self.credentials()
        if creds is not None:
            return str(creds[0])
        return self.client_ip

    def tenant_key(self) -> Optional[str]:
        return base.tenant_key(self.headers.get(TENANT_HEADER))

    def accepts_bin(self) -> bool:
        return (self.server.bin_codec
                and bincodec.CONTENT_TYPE in (self.headers.get("Accept") or ""))


class SdaAsyncHttpServer:
    """Event-loop HTTP server over an SdaServerService — the asyncio twin
    of :class:`~sda_tpu.http.server.SdaHttpServer` (same constructor, same
    public surface, same wire behavior; ``sdad --async``)."""

    def __init__(
        self,
        service: SdaServerService,
        bind: str = "127.0.0.1:8888",
        *,
        max_inflight: Optional[int] = None,
        rate_limit: Optional[float] = None,
        rate_burst: float = 8.0,
        tenant_rate: Optional[float] = None,
        tenant_burst: float = 32.0,
        metrics_endpoint: bool = False,
        statusz_endpoint: bool = False,
        trace_log: bool = False,
        bin_codec: bool = True,
        node_id: Optional[str] = None,
        fleet_peers: Optional[int] = None,
    ):
        host, _, port = bind.partition(":")
        # bind synchronously so .address is valid before the loop spins up
        # (every driver reads it right after construction)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port or 8888)))
        self._sock.listen(1024)
        self.sda_service = service
        self.bin_codec = bin_codec
        self.metrics_enabled = metrics_endpoint
        self.trace_log = trace_log
        self.node_id = node_id
        self.fleet_peers = fleet_peers
        service.server.node_id = node_id
        if fleet_peers is not None:
            metrics.gauge_set("fleet.peers", fleet_peers)
        self.admission = AdmissionControl(
            max_inflight=max_inflight, rate=rate_limit, burst=rate_burst,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
        )
        self.statusz_fn = self.statusz if statusz_endpoint else None
        self.draining = False
        self.stats_lock = threading.Lock()
        self._status_counts: dict = {}
        self._active_requests = 0
        self._started_at = time.time()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._aserver: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=_worker_count(),
            thread_name_prefix="sda-async-http")
        self._stopped = threading.Event()
        self._shut_down = False

    # -- public surface (mirrors SdaHttpServer) -------------------------
    @property
    def address(self) -> str:
        host, port = self._sock.getsockname()[:2]
        return f"http://{host}:{port}"

    @property
    def status_counts(self) -> dict:
        with self.stats_lock:
            return dict(self._status_counts)

    @property
    def active_requests(self) -> int:
        with self.stats_lock:
            return self._active_requests

    def configure_admission(self, max_inflight=None, rate_limit=None,
                            rate_burst=None, tenant_rate=None,
                            tenant_burst=None) -> None:
        self.admission.configure(
            max_inflight=max_inflight, rate=rate_limit, burst=rate_burst,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
        )

    def statusz(self) -> dict:
        return base.build_statusz(
            self.sda_service, node_id=self.node_id, admission=self.admission,
            started_at=self._started_at, status_counts=self.status_counts,
            plane="async",
        )

    def start_background(self) -> "SdaAsyncHttpServer":
        loop = asyncio.new_event_loop()
        self._loop = loop
        started = threading.Event()

        async def _start():
            self._aserver = await asyncio.start_server(
                self._serve_conn, sock=self._sock, limit=_MAX_LINE)
            started.set()

        def _run():
            asyncio.set_event_loop(loop)
            loop.create_task(_start())
            try:
                loop.run_forever()
            finally:
                # drain pending callbacks, then close for real
                try:
                    pending = asyncio.all_tasks(loop)
                    for task in pending:
                        task.cancel()
                    if pending:
                        loop.run_until_complete(asyncio.gather(
                            *pending, return_exceptions=True))
                finally:
                    loop.close()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="sda-async-http-loop")
        self._thread.start()
        if not started.wait(timeout=10):
            raise RuntimeError("async HTTP server failed to start")
        return self

    def serve_forever(self):
        self.start_background()
        self._stopped.wait()

    def drain(self, grace_s: float = 10.0) -> dict:
        """Same drain contract as the threaded plane (docs/scaling.md):
        flip draining FIRST (fresh requests on live connections answer
        503 + ``Connection: close``), wake every parked long-poll so it
        finishes immediately, stop accepting, wait out in-flight work,
        hand held leases back, close. ``leaked`` must be 0."""
        self.draining = True
        wakeup = getattr(self.sda_service.server, "job_wakeup", None)
        if wakeup is not None:
            wakeup.notify_all()
        if self._loop is not None and self._aserver is not None:
            def _stop_accepting():
                if self._aserver is not None:
                    self._aserver.close()
            self._loop.call_soon_threadsafe(_stop_accepting)
        deadline = time.monotonic() + grace_s
        while self.active_requests and time.monotonic() < deadline:
            time.sleep(0.02)
        stranded = self.active_requests
        summary = base.drain_summary(self.sda_service, node_id=self.node_id,
                                     stranded=stranded)
        self.shutdown()
        return summary

    def shutdown(self):
        if self._shut_down:
            return
        self._shut_down = True
        loop = self._loop
        if loop is not None and loop.is_running():
            def _close_all():
                if self._aserver is not None:
                    self._aserver.close()
                for writer in list(self._writers):
                    try:
                        writer.close()
                    except Exception:
                        pass
                loop.stop()
            loop.call_soon_threadsafe(_close_all)
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                log.warning("async HTTP loop did not stop within 5s; "
                            "leaking daemon thread %s", self._thread.name)
                metrics.count("http.shutdown.leaked")
        self._executor.shutdown(wait=False)
        try:
            self._sock.close()
        except OSError:
            pass
        self._stopped.set()

    # -- connection handling --------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter):
        self._writers.add(writer)
        peer = writer.get_extra_info("peername") or ("?",)
        client_ip = str(peer[0])
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ValueError, asyncio.LimitOverrunError):
                    # oversized request line: answer like the threaded
                    # plane (BaseHTTPRequestHandler's 414), so a typed
                    # client fails fast instead of retrying a severed
                    # connection to its deadline
                    return await self._bail(writer, 414,
                                            "request line too long")
                except ConnectionError:
                    return
                if not line or line in (b"\r\n", b"\n"):
                    if not line:
                        return  # clean EOF between requests
                    continue
                try:
                    request = line.decode("latin-1").rstrip("\r\n")
                    method, raw_path, version = request.split(" ", 2)
                except ValueError:
                    return await self._bail(writer, 400, "malformed request line")
                if not version.startswith("HTTP/1."):
                    return await self._bail(writer, 505, "unsupported version")
                headers = _Headers()
                for _ in range(_MAX_HEADERS):
                    try:
                        hline = await reader.readline()
                    except (ValueError, asyncio.LimitOverrunError):
                        # oversized header line: threaded plane's 431
                        return await self._bail(
                            writer, 431, "header line too long")
                    except ConnectionError:
                        return
                    if hline in (b"\r\n", b"\n", b""):
                        break
                    name, sep, value = hline.decode("latin-1").partition(":")
                    if sep:
                        headers.add(name.strip(), value.strip())
                else:
                    return await self._bail(writer, 400, "too many headers")
                content_length = base.parse_content_length(
                    headers.get("Content-Length"))
                if content_length < 0:
                    return await self._bail(writer, 400, "bad Content-Length")
                if "chunked" in (headers.get("Transfer-Encoding") or "").lower():
                    return await self._bail(writer, 400,
                                            "chunked bodies unsupported")
                url = urlparse(raw_path)
                rx = _AsyncExchange(
                    self, asyncio.get_running_loop(), reader, client_ip,
                    method.upper(), url.path.rstrip("/"),
                    parse_qs(url.query), headers, content_length)
                with self.stats_lock:
                    self._active_requests += 1
                try:
                    close = await self._handle_request(rx, writer)
                finally:
                    with self.stats_lock:
                        self._active_requests -= 1
                want_close = (close or rx.close_connection
                              or version == "HTTP/1.0"
                              or (headers.get("Connection") or "")
                              .lower() == "close")
                if want_close:
                    return
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _bail(self, writer, status: int, reason: str):
        """Protocol-level garbage: answer once and sever (no keep-alive —
        framing can no longer be trusted)."""
        body = json.dumps({"error": reason}).encode()
        head = (f"HTTP/1.1 {status} "
                f"{_STATUS_REASONS.get(status, 'Error')}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n")
        try:
            writer.write(head.encode("latin-1") + body)
            await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass

    async def _handle_request(self, rx: _AsyncExchange, writer) -> bool:
        """One request: sync pipeline (span/admission/dispatch, via the
        shared core) on the executor, long-poll parks on the loop, reply
        written here. Returns True when the connection must close."""
        loop = asyncio.get_running_loop()
        parked = False
        try:
            reply = await loop.run_in_executor(
                self._executor, self._pipeline_sync, rx)
            if reply.park is not None:
                parked = True
                try:
                    reply = await self._park(rx, reply.park)
                finally:
                    # the admission in-flight slot covers the parked time
                    # (same as the threaded plane, where blocking_park runs
                    # inside the admission finally): a parked clerk IS
                    # in-flight work that max_inflight must bound
                    if rx.admitted:
                        self.admission.release()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # pipeline crash outside dispatch's mapping
            log.exception("unexpected async-plane error")
            reply = base.Reply(500, {"error": f"{type(e).__name__}: {e}"},
                               close=True)
        close = await self._write_reply(rx, writer, reply)
        span = rx.span
        if span is not None:
            if parked:
                # the span object closed when the sync pipeline returned,
                # before the park — stretch its duration over the parked
                # time so cross-plane trace timelines agree (the threaded
                # plane holds its span open through blocking_park)
                span.duration_s = time.perf_counter() - rx.t0
                # the flight recorder already spooled the short pre-park
                # span at close; re-spool the amended one — forensics
                # dedupes by span id keeping the longest duration
                recorder.amend_span(span)
            if self.trace_log:
                trace_log.info(
                    "trace %s %s %s status=%s request_id=%s",
                    span.trace_id, rx.method, rx.route_path,
                    span.attributes.get("http.status"), rx.request_id)
        return close

    def _pipeline_sync(self, rx: _AsyncExchange):
        """The executor half — a faithful mirror of the threaded plane's
        ``_route_inner``: draining check, observability endpoints,
        request-id hygiene, server span, admission ordering, dispatch."""
        method, path = rx.method, rx.path
        rx.request_id = base.request_id(rx.headers.get(obs.REQUEST_ID_HEADER))
        # draining + the admission/tracing-exempt observability
        # endpoints, shared with the threaded plane
        pre = base.preroute_reply(self, method, path)
        if pre is not None:
            return pre

        label = base.route_label(method, rx.route_path)
        parent = obs.parse_traceparent(rx.headers.get(obs.TRACEPARENT_HEADER))
        span_attributes = {"http.method": method, "http.route": label,
                           "request_id": rx.request_id}
        if self.node_id:
            span_attributes["node_id"] = self.node_id
        # the trace_log line is emitted by _handle_request AFTER the
        # reply is written (and any park resolved) so it carries the
        # final http.status, exactly like the threaded plane's
        with obs.span(
            f"http.server {label}", parent=parent, kind="server",
            attributes=span_attributes,
        ) as server_span:
            rx.span = server_span
            if self.admission.enabled:
                shed = self.admission.admit(rx.agent_key(),
                                            tenant_key=rx.tenant_key())
                if shed is not None:
                    rx.shed = True
                    server_span.set_attribute("shed", shed.reason)
                    return base.Reply(
                        shed.status,
                        {"error": f"throttled: {shed.reason}"},
                        retry_after=shed.retry_after)
                try:
                    reply = base.dispatch(self.sda_service, rx)
                    if reply.park is not None:
                        # long-poll park: keep the slot held across
                        # the park; _handle_request releases it when
                        # the park resolves
                        rx.admitted = True
                    return reply
                finally:
                    if not rx.admitted:
                        self.admission.release()
            return base.dispatch(self.sda_service, rx)

    async def _park(self, rx: _AsyncExchange, park) -> base.Reply:
        """The event-loop park: one wakeup subscription + one waiting
        coroutine per parked long-poll — NO thread. Re-polls ride the
        executor; the tick covers cross-worker arrivals and lease expiry;
        drain wakes everyone with 503 + Connection: close."""
        loop = asyncio.get_running_loop()
        wakeup = getattr(self.sda_service.server, "job_wakeup", None)
        tick = base.park_tick(self.sda_service, self.fleet_peers)
        if wakeup is None:
            tick = base.longpoll_tick()  # no wakeup: tick IS the poll
        if rx.span is not None:
            rx.span.set_attribute("longpoll.parked", True)
        while True:
            if self.draining:
                metrics.count("http.drain.longpoll_woken")
                return base.draining_reply()
            event = asyncio.Event()
            sub = None
            if wakeup is not None:
                sub = wakeup.subscribe(
                    str(park.caller.id),
                    callback=lambda: loop.call_soon_threadsafe(event.set))
            try:
                reply = await loop.run_in_executor(
                    self._executor, base.poll_parked_job,
                    self.sda_service, park)
                if reply is not None:
                    return reply
                remaining = max(0.0, park.give_up_at - time.monotonic())
                timeout = remaining if tick is None else min(tick, remaining)
                try:
                    await asyncio.wait_for(event.wait(), timeout)
                except asyncio.TimeoutError:
                    pass
            finally:
                if sub is not None:
                    wakeup.unsubscribe(sub)

    async def _write_reply(self, rx: _AsyncExchange, writer,
                           reply: base.Reply) -> bool:
        """The async mirror of the threaded ``_reply``: response chaos
        failpoint, bounded unread-body drain, per-request counters and
        latency histograms, then the wire bytes. Returns close verdict."""
        if reply.span_attrs and rx.span is not None:
            for key, value in reply.span_attrs.items():
                rx.span.set_attribute(key, value)
        # failpoint: the service call already happened — dropping HERE
        # simulates a lost response; delay stalls the ack instead
        action = chaos.evaluate("http.server.response",
                                kinds=("drop", "delay"))
        if action is not None:
            if action.kind == "drop":
                log.info("%s %s -> chaos-dropped response",
                         rx.method, rx.path)
                return True
            await asyncio.sleep(action.delay_s)
        if reply.drop:
            log.info("%s %s -> chaos-dropped connection", rx.method, rx.path)
            return True
        # unread body bytes would be parsed as the next request line on
        # this keep-alive connection: drain them, bounded — a client that
        # advertised a body and never sends it forfeits the connection
        if rx.remaining:
            try:
                await asyncio.wait_for(self._discard_body(rx), timeout=5.0)
            except (asyncio.TimeoutError, ConnectionError,
                    asyncio.IncompleteReadError):
                rx.close_connection = True
        status = reply.status
        if reply.raw is not None:
            body = reply.raw
        else:
            body = (b"" if reply.obj is None
                    else json.dumps(reply.obj).encode("utf-8"))
        dt_ms = (time.perf_counter() - rx.t0) * 1e3
        if status >= 400:
            log.info("%s %s -> %d (%.1fms) request_id=%s",
                     rx.method, rx.path, status, dt_ms, rx.request_id)
        else:
            log.info("%s %s -> %d (%.1fms)", rx.method, rx.path, status,
                     dt_ms)
        span = rx.span
        if span is not None and "http.status" not in span.attributes:
            span.set_attribute("http.status", status)
        if not rx.counted:
            rx.counted = True
            with self.stats_lock:
                self._status_counts[status] = \
                    self._status_counts.get(status, 0) + 1
            metrics.count("http.request")
            metrics.count(f"http.status.{status}")
            if rx.shed:
                metrics.observe("http.latency.shed", dt_ms / 1e3)
            else:
                label = base.route_label(rx.method, rx.route_path)
                metrics.observe(f"http.latency.{label}", dt_ms / 1e3)
        close = reply.close or rx.close_connection
        head = [f"HTTP/1.1 {status} {_STATUS_REASONS.get(status, 'OK')}"]
        if rx.request_id:
            head.append(f"{obs.REQUEST_ID_HEADER}: {rx.request_id}")
        if self.node_id:
            head.append(f"{NODE_HEADER}: {self.node_id}")
        if self.bin_codec:
            head.append(f"{bincodec.CODECS_HEADER}: bin")
        if reply.headers:
            for key, value in reply.headers.items():
                head.append(f"{key}: {value}")
        if reply.resource_not_found:
            head.append("X-Resource-Not-Found: true")
        if reply.retry_after is not None:
            head.append(f"Retry-After: {max(0.0, reply.retry_after):.3f}")
        if close and not (reply.headers or {}).get("Connection"):
            head.append("Connection: close")
        head.append(f"Content-Type: {reply.content_type}")
        head.append(f"Content-Length: {len(body)}")
        payload = ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body
        try:
            writer.write(payload)
            await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            return True
        return close

    async def _discard_body(self, rx: _AsyncExchange):
        while rx.remaining:
            chunk = await rx.reader.read(min(_BODY_CHUNK, rx.remaining))
            if not chunk:
                rx.close_connection = True
                return
            rx.remaining -= len(chunk)
