"""Transport-neutral core shared by BOTH HTTP planes.

The serving plane has two transports — the thread-per-connection
``SdaHttpServer`` (``http/server.py``) and the asyncio event-loop
``SdaAsyncHttpServer`` (``http/aserver.py``) — that must stay
*semantically identical*: same route table, same error mapping, same
admission ordering, same chaos failpoint names, same long-poll contract,
same ``/statusz`` document. Everything that could drift between them
lives here exactly once:

- the route-template registry and ``route_label`` (latency-histogram
  cardinality bound),
- ``dispatch``: the whole route table, auth, hot-body codec negotiation
  and the exception->status mapping, operating on a small transport
  adapter (``rx``) and returning a :class:`Reply` for the transport to
  write,
- the long-poll clerking contract (``GET /v1/clerking-jobs?wait=S``):
  wait clamping, the park marker, the blocking park loop the threaded
  plane uses, and the shared empty/job reply shapes,
- the ``/statusz`` document builder and the drain summary, so
  fleet-mode counter aggregation reads the same fields off either plane.

A transport adapter (``rx``) provides: ``method``, ``path``, ``query``
(parse_qs dict), ``header(name)``, ``json_body()``,
``hot_body(expect_tag, from_obj)``, ``accepts_bin()``,
``credentials()``, ``agent_key()``.
"""

from __future__ import annotations

import base64
import logging
import re
import time
from typing import Optional

from .. import chaos, obs
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    Participation,
    ParticipationConflict,
    PermissionDenied,
    Profile,
    SdaError,
    Snapshot,
    SnapshotId,
    StoreUnavailable,
    signed_encryption_key_from_obj,
)
from ..protocol import bincodec
from ..server import auth_token
from ..utils import metrics

log = logging.getLogger(__name__)

_ID = r"[0-9a-fA-F-]{36}"

#: Every route template the dispatcher matches, ids collapsed to ``{id}``.
#: Latency histograms are keyed by template (low cardinality by
#: construction); anything else becomes ``unmatched`` so a scanner probing
#: random paths cannot grow the histogram registry without bound.
ROUTE_TEMPLATES = frozenset({
    "/v1/ping",
    "/v1/agents/me",
    "/v1/agents/{id}",
    "/v1/agents/me/profile",
    "/v1/agents/{id}/profile",
    "/v1/agents/me/keys",
    "/v1/agents/any/keys/{id}",
    "/v1/aggregations",
    "/v1/aggregations/{id}",
    "/v1/aggregations/{id}/committee/suggestions",
    "/v1/aggregations/implied/committee",
    "/v1/aggregations/{id}/committee",
    "/v1/aggregations/participations",
    "/v1/aggregations/{id}/status",
    "/v1/aggregations/{id}/round",
    "/v1/aggregations/implied/snapshot",
    "/v1/aggregations/any/jobs",
    "/v1/clerking-jobs",
    "/v1/aggregations/implied/jobs/{id}/result",
    "/v1/aggregations/{id}/snapshots/{id}/result",
    "/metrics",
    "/statusz",
})
_ID_RE = re.compile(_ID)
#: Charset a client-supplied X-Request-Id / X-SDA-Tenant must satisfy to
#: be used (response-header injection hygiene, bucket-key hygiene).
REQUEST_ID_RE = re.compile(r"[A-Za-z0-9._-]+")


def route_label(method: str, path: str) -> str:
    """``GET /v1/agents/3f2a... -> "GET:/v1/agents/{id}"`` — the
    per-route key under ``http.latency.<route>``."""
    template = _ID_RE.sub("{id}", path)
    if template not in ROUTE_TEMPLATES:
        return f"{method}:unmatched"
    return f"{method}:{template}"


# ---------------------------------------------------------------------------
# Long-poll contract knobs — server-layer policy (they bound the
# in-process ``await_clerking_job`` seam too), re-exported here for the
# transports. See server/wakeup.py.

from ..server.wakeup import (  # noqa: E402
    LONGPOLL_MAX_S,
    LONGPOLL_TICK_S,
    clamp_wait,
    longpoll_tick,
)


# ---------------------------------------------------------------------------
# Request-identity hygiene — shared by both transport adapters so the
# planes' admission keys and echoed headers cannot drift.

def parse_basic_auth(header_value) -> Optional[tuple]:
    """``Authorization: Basic ...`` -> ``(AgentId, token)``, or None for
    anything missing or malformed (the dispatcher decides the 401)."""
    header = header_value or ""
    if not header.startswith("Basic "):
        return None
    try:
        decoded = base64.b64decode(header[6:]).decode("utf-8")
        agent_id, _, token = decoded.partition(":")
        return AgentId(agent_id), token
    except (ValueError, UnicodeDecodeError):
        return None


def tenant_key(header_value) -> Optional[str]:
    """Per-tenant admission key from the CLAIMED ``X-SDA-Tenant`` header
    (unverified, same trust model as the agent key): token charset +
    bounded length so a hostile value cannot grow the bucket dict with
    junk or smuggle bytes."""
    claimed = header_value or ""
    if claimed and len(claimed) <= 64 and REQUEST_ID_RE.fullmatch(claimed):
        return claimed
    return None


def parse_content_length(header_value) -> int:
    """``Content-Length`` -> byte count, or -1 for anything unusable
    (garbage, negative). One parser for every call site on both planes:
    a negative length fed to a blocking read means read-to-EOF, the
    thread-pinning stall class each caller must refuse in its own way
    (400 pre-dispatch, sever on the drain path)."""
    try:
        length = int(header_value or 0)
    except (TypeError, ValueError):
        return -1
    return length if length >= 0 else -1


def request_id(header_value) -> str:
    """Correlation id: reuse the client's ``X-Request-Id``, mint one
    else. The value is echoed into a response header, so a hostile one
    must not smuggle CRLFs or unbounded bytes: token charset, capped
    length."""
    claimed = header_value or ""
    if claimed and len(claimed) <= 64 and REQUEST_ID_RE.fullmatch(claimed):
        return claimed
    return obs.new_request_id()


# ---------------------------------------------------------------------------
# Replies

class Reply:
    """A fully-decided response for the transport to write."""

    __slots__ = ("status", "obj", "raw", "content_type", "headers",
                 "resource_not_found", "retry_after", "close", "drop",
                 "park", "span_attrs")

    def __init__(self, status: int = 200, obj=None, *, raw=None,
                 content_type: str = "application/json", headers=None,
                 resource_not_found: bool = False, retry_after=None,
                 close: bool = False, drop: bool = False, park=None,
                 span_attrs=None):
        self.status = status
        self.obj = obj
        self.raw = raw
        self.content_type = content_type
        self.headers = headers
        self.resource_not_found = resource_not_found
        self.retry_after = retry_after
        #: ask the transport to close the connection after replying
        self.close = close
        #: chaos "drop": sever the connection WITHOUT any response bytes
        self.drop = drop
        #: long-poll park marker (ParkForJob): the transport must wait
        #: and re-poll instead of writing this reply
        self.park = park
        self.span_attrs = span_attrs


class ParkForJob:
    """A long-poll that found no job on the immediate check: park until
    wakeup/tick/drain/deadline, re-polling through the service seam."""

    __slots__ = ("caller", "accepts_bin", "give_up_at")

    def __init__(self, caller: Agent, accepts_bin: bool, give_up_at: float):
        self.caller = caller
        self.accepts_bin = accepts_bin
        self.give_up_at = give_up_at


def option_reply(obj, headers=None) -> Reply:
    if obj is None:
        return Reply(404, {"error": "resource not found"},
                     resource_not_found=True)
    return Reply(200, obj.to_obj(), headers=headers)


def job_reply(job, accepts_bin: bool) -> Reply:
    """The clerking-job poll response, shared by the legacy immediate
    route and the long-poll route on both planes: empty-queue answers the
    ``X-Resource-Not-Found`` 404 (client maps it to None), a job rides
    the negotiated codec plus the ``X-Trace-Context`` link the round's
    snapshot recorded at enqueue time."""
    headers = None
    if job is not None:
        link = obs.job_link(str(job.id))
        if link is not None:
            headers = {obs.TRACE_CONTEXT_HEADER: obs.format_traceparent(link)}
    if job is not None and accepts_bin:
        metrics.count("http.codec.bin.out")
        return Reply(200, raw=bincodec.encode_clerking_job(job),
                     content_type=bincodec.CONTENT_TYPE, headers=headers)
    return option_reply(job, headers=headers)


def draining_reply() -> Reply:
    """503 + ``Connection: close``: what a draining worker answers — both
    to fresh requests on established keep-alive connections and to
    parked long-polls it wakes (docs/scaling.md drain contract)."""
    return Reply(503, {"error": "draining"}, retry_after=1.0, close=True,
                 headers={"Connection": "close"})


def error_reply(e: BaseException) -> Reply:
    """The exception -> status mapping, shared by the dispatch table and
    the park re-poll loops (which run outside dispatch's try block)."""
    if isinstance(e, InvalidCredentials):
        return Reply(401, {"error": str(e)})
    if isinstance(e, PermissionDenied):
        return Reply(403, {"error": str(e)})
    if isinstance(e, (InvalidRequest, ValueError, KeyError, TypeError)):
        return Reply(400, {"error": f"{type(e).__name__}: {e}"})
    if isinstance(e, NotFound):
        return Reply(404, {"error": str(e)}, resource_not_found=True)
    if isinstance(e, ParticipationConflict):
        # exactly-once ingestion rejected an equivocating upload: 409
        # is TERMINAL for the retrying transport (re-sending the same
        # conflicting bytes can never succeed), unlike the transient
        # 5xx/429 family. No stack trace — detection is the feature
        # working, and a buggy device would flood the log.
        return Reply(409, {"error": str(e)})
    if isinstance(e, StoreUnavailable):
        # breaker-open shed (server/breaker.py): the store was never
        # touched — 503 + Retry-After, same contract as admission
        # sheds, so the retrying transport backs off and resubmits.
        # No stack trace: an open breaker shedding is WORKING, and a
        # brownout would otherwise flood the log at request rate.
        metrics.count("http.store_unavailable")
        return Reply(503, {"error": str(e)}, retry_after=e.retry_after,
                     span_attrs={"store_unavailable": True})
    if isinstance(e, SdaError):
        log.exception("server error")
        return Reply(500, {"error": str(e)})
    log.exception("unexpected server error")
    return Reply(500, {"error": f"{type(e).__name__}: {e}"})


def preroute_reply(server, method: str, path: str) -> Optional[Reply]:
    """The pre-dispatch decisions both planes must make identically:
    a draining worker turns every fresh request away before any
    auth/store work, and the observability endpoints (``/metrics``,
    ``/statusz``) answer exempt from admission and tracing (scrapes must
    land during the exact overload they diagnose; a scrape loop would
    churn the span ring buffer). Returns None for ordinary requests.
    ``server`` is the plane object (``SdaHttpServer`` /
    ``SdaAsyncHttpServer``): same attribute names on both."""
    if getattr(server, "draining", False):
        metrics.count("http.drain.rejected")
        return draining_reply()
    if method == "GET" and path == "/metrics":
        if not getattr(server, "metrics_enabled", False):
            return Reply(404, {"error": "metrics endpoint disabled "
                                        "(sdad --metrics)"})
        node_id = getattr(server, "node_id", None)
        return Reply(
            200, raw=metrics.prometheus_text(
                labels={"node_id": node_id} if node_id else None
            ).encode("utf-8"),
            content_type="text/plain; version=0.0.4; charset=utf-8")
    if method == "GET" and path == "/statusz":
        statusz = getattr(server, "statusz_fn", None)
        if statusz is None:
            return Reply(404, {"error": "statusz endpoint disabled "
                                        "(sdad --statusz)"})
        return Reply(200, statusz())
    return None


# ---------------------------------------------------------------------------
# Dispatch — the single route table

def dispatch(service, rx) -> Reply:
    """Route one request through the service seam; never raises for
    request-level trouble (the mapping above decides the status)."""
    try:
        return _dispatch_inner(service, rx)
    except Exception as e:  # mapped, connection survives; KeyboardInterrupt
        # and SystemExit propagate so shutdown isn't answered as a 500
        return error_reply(e)


def _authenticate(service, rx) -> Agent:
    creds = rx.credentials()
    if creds is None:
        raise InvalidCredentials("missing Basic auth")
    return service.server.check_auth_token(auth_token(*creds))


def _create_agent(service, rx) -> Reply:
    """Agent self-registration also records the presented token
    (lib.rs:192-201)."""
    creds = rx.credentials()
    if creds is None:
        raise InvalidCredentials("agent creation requires Basic auth")
    agent_id, token = creds
    if not token:
        raise InvalidCredentials("empty token")
    agent = Agent.from_obj(rx.json_body())
    if agent.id != agent_id:
        raise PermissionDenied("auth username must match agent id")
    # record-or-verify the token before the ACL'd create
    try:
        known = service.server.check_auth_token(auth_token(agent_id, token))
    except InvalidCredentials:
        if service.server.auth_tokens_store.get_auth_token(agent_id) \
                is not None:
            raise  # token exists but differs: reject
        known = None
    if known is None:
        service.server.upsert_auth_token(auth_token(agent_id, token))
    service.create_agent(agent, agent)
    return Reply(201)


def _dispatch_inner(service, rx) -> Reply:
    method, path, query = rx.method, rx.path, rx.query

    def m(pattern):
        return re.fullmatch(pattern, path)

    # failpoint: transient transport trouble BEFORE any service work —
    # injected 500s, response delays, or hard connection drops. The
    # claimed agent id rides the ctx so a `partition` spec can sever
    # exactly one agent<->server pair (agent=<id>)
    action = chaos.evaluate(
        "http.server.request",
        ctx={"agent": rx.agent_key()} if chaos.registry.active() else None)
    if action is not None:
        if action.kind == "error":
            return Reply(500, {"error": str(action.exc)})
        if action.kind == "drop":
            return Reply(drop=True)
        time.sleep(action.delay_s)  # "delay": proceed after the stall

    if method == "GET" and path == "/v1/ping":
        return Reply(200, service.ping().to_obj())

    if method == "POST" and path == "/v1/agents/me":
        return _create_agent(service, rx)

    caller = _authenticate(service, rx)

    if r := m(rf"/v1/agents/({_ID})/profile"):
        if method == "GET":
            return option_reply(
                service.get_profile(caller, AgentId(r.group(1))))
    if method == "POST" and path == "/v1/agents/me/profile":
        profile = Profile.from_obj(rx.json_body())
        service.upsert_profile(caller, profile)
        return Reply(200)
    if r := m(rf"/v1/agents/any/keys/({_ID})"):
        if method == "GET":
            return option_reply(
                service.get_encryption_key(
                    caller, EncryptionKeyId(r.group(1))))
    if method == "POST" and path == "/v1/agents/me/keys":
        key = signed_encryption_key_from_obj(rx.json_body())
        service.create_encryption_key(caller, key)
        return Reply(201)
    if r := m(rf"/v1/agents/({_ID})"):
        if method == "GET":
            return option_reply(
                service.get_agent(caller, AgentId(r.group(1))))

    if path == "/v1/aggregations" and method == "GET":
        title = query.get("title", [None])[0]
        recipient = query.get("recipient", [None])[0]
        ids = service.list_aggregations(
            caller,
            filter=title,
            recipient=None if recipient is None else AgentId(recipient),
        )
        return Reply(200, [str(i) for i in ids])
    if path == "/v1/aggregations" and method == "POST":
        agg = Aggregation.from_obj(rx.json_body())
        service.create_aggregation(caller, agg)
        return Reply(201)
    if r := m(rf"/v1/aggregations/({_ID})/committee/suggestions"):
        if method == "GET":
            candidates = service.suggest_committee(
                caller, AggregationId(r.group(1)))
            return Reply(200, [c.to_obj() for c in candidates])
    if path == "/v1/aggregations/implied/committee" and method == "POST":
        committee = Committee.from_obj(rx.json_body())
        service.create_committee(caller, committee)
        return Reply(201)
    if r := m(rf"/v1/aggregations/({_ID})/committee"):
        if method == "GET":
            return option_reply(
                service.get_committee(caller, AggregationId(r.group(1))))
    if path == "/v1/aggregations/participations" and method == "POST":
        participation = rx.hot_body(
            bincodec.TAG_PARTICIPATION, Participation.from_obj)
        service.create_participation(caller, participation)
        return Reply(201)
    if r := m(rf"/v1/aggregations/({_ID})/status"):
        if method == "GET":
            return option_reply(
                service.get_aggregation_status(
                    caller, AggregationId(r.group(1))))
    if r := m(rf"/v1/aggregations/({_ID})/round"):
        if method == "GET":
            # round lifecycle state (server/lifecycle.py): what a
            # blocking client polls instead of result_ready alone —
            # terminal failed/expired states carry the diagnosis
            return option_reply(
                service.get_round_status(caller, AggregationId(r.group(1))))
    if path == "/v1/aggregations/implied/snapshot" and method == "POST":
        snap = Snapshot.from_obj(rx.json_body())
        service.create_snapshot(caller, snap)
        return Reply(201)
    if path == "/v1/aggregations/any/jobs" and method == "GET":
        # the legacy immediate-return poll: old peers and clerk_once
        job = service.get_clerking_job(caller, caller.id)
        return job_reply(job, rx.accepts_bin())
    if path == "/v1/clerking-jobs" and method == "GET":
        # long-poll job delivery (docs/http.md): try once; empty + a
        # positive wait parks the request on the in-process job wakeup
        # (the transport decides HOW to park — a blocked thread on the
        # threaded plane, a coroutine await on the async plane)
        raw_wait = query.get("wait", ["0"])[0]
        try:
            wait_s = clamp_wait(float(raw_wait))
        except (TypeError, ValueError):
            raise InvalidRequest(f"malformed wait={raw_wait!r}")
        job = service.get_clerking_job(caller, caller.id)
        if job is not None or wait_s <= 0:
            return job_reply(job, rx.accepts_bin())
        return Reply(park=ParkForJob(
            caller, rx.accepts_bin(), time.monotonic() + wait_s))
    if r := m(rf"/v1/aggregations/implied/jobs/({_ID})/result"):
        if method == "POST":
            result = rx.hot_body(
                bincodec.TAG_CLERKING_RESULT, ClerkingResult.from_obj)
            if str(result.job) != r.group(1).lower():
                raise InvalidRequest("result job id does not match route")
            service.create_clerking_result(caller, result)
            return Reply(201)
    if r := m(rf"/v1/aggregations/({_ID})/snapshots/({_ID})/result"):
        if method == "GET":
            return option_reply(
                service.get_snapshot_result(
                    caller, AggregationId(r.group(1)),
                    SnapshotId(r.group(2))))
    if r := m(rf"/v1/aggregations/({_ID})"):
        if method == "GET":
            return option_reply(
                service.get_aggregation(caller, AggregationId(r.group(1))))
        if method == "DELETE":
            service.delete_aggregation(caller, AggregationId(r.group(1)))
            return Reply(200)

    return Reply(404, {"error": "no such route"})


# ---------------------------------------------------------------------------
# Park loops

def poll_parked_job(service, park: ParkForJob) -> Optional[Reply]:
    """One re-poll of a parked long-poll: the final reply, or None to
    keep waiting. Exceptions map exactly like dispatch-time ones."""
    try:
        job = service.get_clerking_job(park.caller, park.caller.id)
    except Exception as e:
        return error_reply(e)
    if job is not None:
        return job_reply(job, park.accepts_bin)
    if time.monotonic() >= park.give_up_at:
        return job_reply(None, park.accepts_bin)
    return None


def park_tick(service, fleet_peers) -> Optional[float]:
    """How often a parked long-poll must re-check the store, or None for
    a pure event wait. The tick exists to cover arrivals the in-process
    wakeup cannot see: a fleet peer's fan-out (notifies ITS process) and
    lease expiry (time-based, no event). A single-worker deployment with
    leasing off has neither — its parks can sleep on the subscription
    alone, so 10k parked clerks cost zero store re-scans instead of
    re-polling at the tick."""
    single_worker = fleet_peers is None or fleet_peers <= 1
    if single_worker and not getattr(
            getattr(service, "server", None), "clerking_lease_seconds", 0):
        return None
    return longpoll_tick()


def blocking_park(service, park: ParkForJob, draining,
                  fleet_peers=None) -> Reply:
    """The threaded plane's park: block THIS request thread on the job
    wakeup (re-checking on the tick for cross-worker/lease-expiry
    arrivals) until a job lands, the wait expires, or the worker starts
    draining — a draining worker wakes parked clerks with
    503 + ``Connection: close`` instead of holding them to timeout."""
    wakeup = getattr(getattr(service, "server", None), "job_wakeup", None)
    tick = park_tick(service, fleet_peers)
    if wakeup is None:
        tick = longpoll_tick()  # no wakeup to park on: tick IS the poll
    key = str(park.caller.id)
    while True:
        if draining():
            metrics.count("http.drain.longpoll_woken")
            return draining_reply()
        sub = wakeup.subscribe(key) if wakeup is not None else None
        try:
            reply = poll_parked_job(service, park)
            if reply is not None:
                return reply
            remaining = max(0.0, park.give_up_at - time.monotonic())
            timeout = remaining if tick is None else min(tick, remaining)
            if sub is not None:
                sub.wait(timeout)
            else:
                time.sleep(timeout)
        finally:
            if sub is not None:
                wakeup.unsubscribe(sub)


# ---------------------------------------------------------------------------
# Shared /statusz + drain summary (satellite: extract, don't duplicate —
# fleet-mode counter aggregation reads these fields off either plane)

def build_statusz(service, *, node_id, admission, started_at, status_counts,
                  plane: str) -> dict:
    """The ``GET /statusz`` payload: liveness + capacity + device-perf
    state in one scrape (served only when the endpoint is enabled —
    like ``/metrics`` it reveals traffic shape). ``plane`` names the
    serving transport ("threaded" / "async")."""
    from ..obs import devprof
    from ..server import health as _health
    from ..server import lifecycle as _lifecycle

    gauges = metrics.gauge_report("http.inflight")
    # unwrap a breaker proxy: the page names the BACKEND, not the wrap
    agents_store = getattr(service.server.agents_store, "_inner",
                           service.server.agents_store)
    wakeup = getattr(service.server, "job_wakeup", None)
    pickup = metrics.histogram_report("server.job.pickup").get(
        "server.job.pickup")
    return {
        "node_id": node_id,
        "plane": plane,
        "fleet": {
            "peers": metrics.gauge_report("fleet.peers").get(
                "fleet.peers", 1 if node_id else 0),
        },
        "uptime_s": round(time.time() - started_at, 3),
        # backend module name ("memory"/"sqlite"/"jsonfs"/"mongo")
        "store": type(agents_store).__module__.rsplit(".", 1)[-1],
        "inflight": gauges.get("http.inflight", 0),
        "inflight_peak": gauges.get("http.inflight.peak", 0),
        "admission_enabled": admission.enabled,
        # multi-tenant fairness verdicts (http/admission.py): which
        # tenants were admitted/shed against their own budgets —
        # present only when the per-tenant layer is armed
        "admission": (admission.tenants_report()
                      if admission.tenant_rate is not None else None),
        "requests": status_counts,
        # which wire the peers actually spoke (fleet loadgen reads
        # the negotiated outcome from here — the counters live in
        # THIS process, not the driver's)
        "codec_counters": metrics.counter_report("http.codec.") or {},
        "lease": {
            "lease_seconds": service.server.clerking_lease_seconds,
            # live (unlapsed) leases this worker holds right now — the
            # shared granted-lease sweep keeps the figure honest on
            # both planes (server/core.py sweep_granted_leases)
            "held": service.server.held_lease_count(),
            "counters": metrics.counter_report("server.job."),
            # enqueue->lease latency (ms): the long-poll headline
            "pickup_ms": ({
                "count": int(pickup["count"]),
                "p50_ms": round(pickup["p50"] * 1e3, 3),
                "p99_ms": round(pickup["p99"] * 1e3, 3),
            } if pickup else None),
        },
        # long-poll plane: how many clerk requests are parked on the
        # in-process wakeup right now (server/wakeup.py)
        "longpoll": {
            "parked": wakeup.parked() if wakeup is not None else 0,
            "max_wait_s": clamp_wait(float("inf")),
            "tick_s": longpoll_tick(),
        },
        # contended-idempotency visibility: how often this worker's
        # snapshot pipeline won, lost, or converged on a peer's freeze
        "snapshot": metrics.counter_report("server.snapshot.") or {},
        # adversarial-input visibility: out-of-field share detections
        # (clerk.share.out_of_range) live in the CLERK's process — the
        # server proper never sees plaintext shares, so these counters
        # appear here only where clerks share the scraped process
        # (in-process drills, co-located clerks); fleet mode sums them
        # across scrapes like the codec counters above
        "clerk": metrics.counter_report("clerk.share.") or {},
        # exactly-once ingestion visibility: created vs byte-identical
        # replays vs rejected equivocations (fleet loadgen sums these
        # across scrapes — the counters live in THIS process)
        "participation": metrics.counter_report(
            "server.participation.") or {},
        # round lifecycle table (server/lifecycle.py): per-state and
        # per-tenant tallies + the most recently updated LIVE rounds
        # (terminal history only pads the remainder) — the fleet's
        # shared-store view, so any worker's scrape shows every round
        "rounds": _lifecycle.rounds_report(service.server),
        # recurring-round schedules (service/scheduler.py): every
        # installed schedule's tenant, current epoch and cadence —
        # also the shared-store view
        "schedules": _schedules_report(service.server),
        # live fleet health table (server/health.py): every worker's
        # heartbeat state and age, read from the shared store — any
        # worker's scrape shows the whole fleet
        "fleet_health": _health.fleet_health_report(
            service.server.clerking_job_store),
        # store circuit breaker (server/breaker.py): present only
        # when armed (sdad --store-breaker)
        "breaker": (service.server.store_breaker.report()
                    if getattr(service.server, "store_breaker", None)
                    is not None else None),
        # fleet drills arm failpoints per worker (sdad --chaos-spec);
        # the scrape proves the faults actually fired in THIS process
        "failpoints": chaos.report() or {},
        "devprof": devprof.compile_totals(),
        "hbm": metrics.gauge_report("device.hbm."),
    }


def _schedules_report(server) -> Optional[dict]:
    """The ``/statusz`` schedules block (lazy import: the service plane
    only loads when a scrape actually asks for it)."""
    from ..service.scheduler import schedules_report

    try:
        return schedules_report(server)
    except Exception:  # a third-party store without schedule support
        return None


def drain_summary(service, *, node_id, stranded: int) -> dict:
    """The tail of a graceful drain, identical on both planes: hand every
    held clerking-job lease back to the shared store, count stranded
    in-flight requests as the leak the fleet contract gates on, and
    return the summary line ``sdad``/``sda-fleet`` parse."""
    released = service.server.release_held_leases()
    if stranded:
        # a handler still running past the grace window is an
        # abandoned request — the process exits right after and
        # kills its daemon thread mid-flight. That IS the leak the
        # fleet contract gates on.
        metrics.count("http.shutdown.leaked", stranded)
    summary = {
        "node_id": node_id,
        "released_leases": released,
        "stranded_requests": stranded,
        "leaked": stranded,
    }
    log.info("drained: %s", summary)
    return summary
