"""Server-side admission control: shed load *before* doing work.

SDA's broker faces "many weak, sporadic devices" (PAPER.md) — the overload
failure mode is a retry storm from thousands of participants that drives a
saturated server into collapse. Following the Tail-at-Scale playbook, the
cheapest correct response is early rejection with an explicit come-back
hint: a rejected request costs one header parse, an admitted one proceeds
to auth/crypto/store work.

Three independent guards, each optional (``None`` disables):

- **per-tenant budget bucket** (``tenant_rate`` tokens/sec,
  ``tenant_burst`` capacity), keyed by the ``X-SDA-Tenant`` request
  header — the RECIPIENT the request's traffic belongs to. This is the
  multi-tenant fairness layer (the continuous service plane,
  ``sda_tpu/service``): one hot tenant's device swarm sheds ``429``
  against its OWN budget before it can exhaust the shared in-flight cap
  or crowd out other tenants' agents. Checked FIRST, before the shared
  limits, by design. Like the agent key, the header is deliberately
  unverified (rate limiting must not pay the auth lookup it protects);
  requests without the header simply skip this guard.
- **per-agent token bucket** (``rate`` tokens/sec, ``burst`` capacity),
  keyed by the Basic-auth username (the agent id) or, for unauthenticated
  requests, the client address. Overflow sheds ``429`` with a
  ``Retry-After`` hint computed from the bucket's actual refill time, so
  a well-behaved client converges instead of hammering.
- **bounded in-flight limiter** (``max_inflight`` concurrently handled
  requests, process-wide). Overflow sheds ``503`` + a short ``Retry-After``
  — the server is saturated regardless of who is asking.

Decisions are counted under ``http.throttled.rate`` /
``http.throttled.tenant`` / ``http.throttled.inflight``; the current and
peak concurrency ride the ``http.inflight`` / ``http.inflight.peak``
gauges (the queue-depth signal capacity reports key on), and the
per-tenant verdicts are summarized by :meth:`AdmissionControl.tenants_report`
(``/statusz.admission``).

The handler MUST pair every admitted request with ``release()``
(try/finally in ``_Handler._route``), or the in-flight counter leaks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..utils import metrics

#: Prune idle per-agent buckets past this population (DoS hygiene: a churn
#: of one-shot agent ids must not grow the dict without bound).
_MAX_BUCKETS = 8192
_BUCKET_IDLE_S = 300.0

#: The request header naming the tenant (recipient) a request's traffic
#: belongs to — the per-tenant budget key. Clients stamp it on every
#: request of an aggregation's round (``SdaHttpClient.tenant``).
TENANT_HEADER = "X-SDA-Tenant"


class TokenBucket:
    """Classic token bucket; mutated under the owning controller's lock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        # a burst below one token could never admit anything yet would
        # keep emitting finite Retry-After hints — clamp the config
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.stamp = now

    def try_take(self, now: float) -> float:
        """Take one token. Returns 0.0 on success, else the seconds until
        a token will have accrued (the ``Retry-After`` hint)."""
        elapsed = max(0.0, now - self.stamp)
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self.stamp = now
        # epsilon: a client that honors the hint to the letter must not be
        # re-shed over float rounding in the refill product
        if self.tokens >= 1.0 - 1e-9:
            self.tokens = max(0.0, self.tokens - 1.0)
            return 0.0
        return (1.0 - self.tokens) / self.rate


class ShedDecision:
    """Why a request was rejected, and when to come back."""

    __slots__ = ("status", "retry_after", "reason")

    def __init__(self, status: int, retry_after: float, reason: str):
        self.status = status
        self.retry_after = retry_after
        self.reason = reason


class AdmissionControl:
    """Combined rate-limit + concurrency guard for ``SdaHttpServer``.

    Thread-safe; all knobs may be retuned at runtime via ``configure``
    (the loadgen driver arms overload profiles after round setup).
    """

    def __init__(
        self,
        max_inflight: Optional[int] = None,
        rate: Optional[float] = None,
        burst: float = 8.0,
        tenant_rate: Optional[float] = None,
        tenant_burst: float = 32.0,
    ):
        self._lock = threading.Lock()
        self.max_inflight = max_inflight
        self.rate = rate
        self.burst = burst
        self.tenant_rate = tenant_rate
        self.tenant_burst = tenant_burst
        self._buckets: Dict[str, TokenBucket] = {}
        self._tenant_buckets: Dict[str, TokenBucket] = {}
        # per-tenant verdict tallies [admitted, shed] for /statusz and
        # the soak report; bounded alongside the bucket dicts
        self._tenant_stats: Dict[str, list] = {}
        # one prune stamp PER bucket dict: a sweep triggered by tenant
        # churn must not suppress the agent dict's sweep (or vice versa),
        # which would force O(1) eviction of possibly-active entries
        self._last_prune: Dict[int, float] = {}
        self._inflight = 0

    def configure(
        self,
        max_inflight: Optional[int] = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        tenant_rate: Optional[float] = None,
        tenant_burst: Optional[float] = None,
    ) -> None:
        """REPLACE the whole admission config: each guard is set exactly
        as passed (``None`` disables it; ``burst=None`` restores the
        default) — no field survives a retune implicitly."""
        with self._lock:
            self.max_inflight = max_inflight
            self.rate = rate
            self.burst = 8.0 if burst is None else burst
            self.tenant_rate = tenant_rate
            self.tenant_burst = 32.0 if tenant_burst is None else tenant_burst
            self._buckets.clear()
            self._tenant_buckets.clear()
            self._tenant_stats.clear()

    @property
    def enabled(self) -> bool:
        return (self.max_inflight is not None or self.rate is not None
                or self.tenant_rate is not None)

    def _bucket(self, buckets: Dict[str, TokenBucket], key: str,
                rate: float, burst: float, now: float) -> TokenBucket:
        """Get-or-create under the held lock, with the bounded-population
        eviction discipline: the key is an UNVERIFIED header/username, so
        a churn of fresh keys must cycle the dict, never grow it —
        stale-sweep at most every few seconds, otherwise evict the
        oldest-created entry O(1)."""
        bucket = buckets.get(key)
        if bucket is None:
            if len(buckets) >= _MAX_BUCKETS:
                if now - self._last_prune.get(id(buckets), 0.0) > 5.0:
                    self._last_prune[id(buckets)] = now
                    cutoff = now - _BUCKET_IDLE_S
                    for stale in [k for k, b in buckets.items()
                                  if b.stamp < cutoff]:
                        del buckets[stale]
                if len(buckets) >= _MAX_BUCKETS:
                    del buckets[next(iter(buckets))]
            bucket = buckets[key] = TokenBucket(rate, burst, now)
        return bucket

    def _tenant_note(self, tenant_key: str, shed: bool) -> None:
        stats = self._tenant_stats.get(tenant_key)
        if stats is None:
            if len(self._tenant_stats) >= _MAX_BUCKETS:
                self._tenant_stats.pop(next(iter(self._tenant_stats)))
            stats = self._tenant_stats[tenant_key] = [0, 0]
        stats[1 if shed else 0] += 1

    def admit(self, agent_key: str,
              tenant_key: Optional[str] = None) -> Optional[ShedDecision]:
        """Admit or shed one request. ``None`` = admitted (in-flight slot
        taken; the caller owes a ``release()``); else the shed decision."""
        now = time.monotonic()
        with self._lock:
            # tenant budget FIRST: a hot tenant must shed against its own
            # budget BEFORE it can touch the shared in-flight cap — that
            # ordering IS the fairness property (one tenant's burst can
            # starve itself, never the fleet). The admitted-then-503'd
            # case burns a tenant token: the request did arrive on the
            # tenant's account.
            if self.tenant_rate is not None and tenant_key:
                if self.tenant_rate <= 0.0:
                    metrics.count("http.throttled.tenant")
                    self._tenant_note(tenant_key, shed=True)
                    return ShedDecision(429, 1.0, "per-tenant budget")
                tenant_bucket = self._bucket(
                    self._tenant_buckets, tenant_key,
                    self.tenant_rate, self.tenant_burst, now)
                wait = tenant_bucket.try_take(now)
                if wait > 0.0:
                    metrics.count("http.throttled.tenant")
                    self._tenant_note(tenant_key, shed=True)
                    return ShedDecision(429, wait, "per-tenant budget")
                self._tenant_note(tenant_key, shed=False)
            # concurrency next: an in-flight shed must not burn the
            # agent's rate token (the retry would then need two)
            if (
                self.max_inflight is not None
                and self._inflight >= self.max_inflight
            ):
                metrics.count("http.throttled.inflight")
                # no queue to estimate from: hint one "typical request" out
                return ShedDecision(503, 0.05, "server at max in-flight")
            if self.rate is not None:
                if self.rate <= 0.0:
                    # "block everything" config: shed without a bucket
                    # (a zero-rate bucket could never hand out a hint)
                    metrics.count("http.throttled.rate")
                    return ShedDecision(429, 1.0, "per-agent rate limit")
                bucket = self._bucket(self._buckets, agent_key, self.rate,
                                      self.burst, now)
                wait = bucket.try_take(now)
                if wait > 0.0:
                    metrics.count("http.throttled.rate")
                    return ShedDecision(429, wait, "per-agent rate limit")
            self._inflight += 1
            depth = self._inflight
        metrics.gauge_set("http.inflight", depth)
        metrics.gauge_max("http.inflight.peak", depth)
        return None

    def tenants_report(self, limit: int = 16) -> dict:
        """Per-tenant admission verdicts for ``/statusz`` and the soak
        report — busiest tenants first, bounded to ``limit``."""
        with self._lock:
            rows = sorted(
                self._tenant_stats.items(),
                key=lambda kv: (-(kv[1][0] + kv[1][1]), kv[0]))
            return {
                "tenant_rate": self.tenant_rate,
                "tenant_burst": self.tenant_burst,
                "tenants": {
                    tenant: {
                        "admitted": admitted,
                        "shed": shed,
                        "tokens": (round(
                            self._tenant_buckets[tenant].tokens, 3)
                            if tenant in self._tenant_buckets else None),
                    }
                    for tenant, (admitted, shed) in rows[:limit]
                },
                "tenants_omitted": max(0, len(rows) - limit),
            }

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1
            depth = self._inflight
        metrics.gauge_set("http.inflight", depth)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight
