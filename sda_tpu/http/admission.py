"""Server-side admission control: shed load *before* doing work.

SDA's broker faces "many weak, sporadic devices" (PAPER.md) — the overload
failure mode is a retry storm from thousands of participants that drives a
saturated server into collapse. Following the Tail-at-Scale playbook, the
cheapest correct response is early rejection with an explicit come-back
hint: a rejected request costs one header parse, an admitted one proceeds
to auth/crypto/store work.

Two independent guards, both optional (``None`` disables):

- **per-agent token bucket** (``rate`` tokens/sec, ``burst`` capacity),
  keyed by the Basic-auth username (the agent id) or, for unauthenticated
  requests, the client address. Overflow sheds ``429`` with a
  ``Retry-After`` hint computed from the bucket's actual refill time, so
  a well-behaved client converges instead of hammering.
- **bounded in-flight limiter** (``max_inflight`` concurrently handled
  requests, process-wide). Overflow sheds ``503`` + a short ``Retry-After``
  — the server is saturated regardless of who is asking.

Decisions are counted under ``http.throttled.rate`` /
``http.throttled.inflight``; the current and peak concurrency ride the
``http.inflight`` / ``http.inflight.peak`` gauges (the queue-depth signal
capacity reports key on).

The handler MUST pair every admitted request with ``release()``
(try/finally in ``_Handler._route``), or the in-flight counter leaks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..utils import metrics

#: Prune idle per-agent buckets past this population (DoS hygiene: a churn
#: of one-shot agent ids must not grow the dict without bound).
_MAX_BUCKETS = 8192
_BUCKET_IDLE_S = 300.0


class TokenBucket:
    """Classic token bucket; mutated under the owning controller's lock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        # a burst below one token could never admit anything yet would
        # keep emitting finite Retry-After hints — clamp the config
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.stamp = now

    def try_take(self, now: float) -> float:
        """Take one token. Returns 0.0 on success, else the seconds until
        a token will have accrued (the ``Retry-After`` hint)."""
        elapsed = max(0.0, now - self.stamp)
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self.stamp = now
        # epsilon: a client that honors the hint to the letter must not be
        # re-shed over float rounding in the refill product
        if self.tokens >= 1.0 - 1e-9:
            self.tokens = max(0.0, self.tokens - 1.0)
            return 0.0
        return (1.0 - self.tokens) / self.rate


class ShedDecision:
    """Why a request was rejected, and when to come back."""

    __slots__ = ("status", "retry_after", "reason")

    def __init__(self, status: int, retry_after: float, reason: str):
        self.status = status
        self.retry_after = retry_after
        self.reason = reason


class AdmissionControl:
    """Combined rate-limit + concurrency guard for ``SdaHttpServer``.

    Thread-safe; all knobs may be retuned at runtime via ``configure``
    (the loadgen driver arms overload profiles after round setup).
    """

    def __init__(
        self,
        max_inflight: Optional[int] = None,
        rate: Optional[float] = None,
        burst: float = 8.0,
    ):
        self._lock = threading.Lock()
        self.max_inflight = max_inflight
        self.rate = rate
        self.burst = burst
        self._buckets: Dict[str, TokenBucket] = {}
        self._last_prune = 0.0
        self._inflight = 0

    def configure(
        self,
        max_inflight: Optional[int] = None,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
    ) -> None:
        """REPLACE the whole admission config: each guard is set exactly
        as passed (``None`` disables it; ``burst=None`` restores the
        default) — no field survives a retune implicitly."""
        with self._lock:
            self.max_inflight = max_inflight
            self.rate = rate
            self.burst = 8.0 if burst is None else burst
            self._buckets.clear()

    @property
    def enabled(self) -> bool:
        return self.max_inflight is not None or self.rate is not None

    def admit(self, agent_key: str) -> Optional[ShedDecision]:
        """Admit or shed one request. ``None`` = admitted (in-flight slot
        taken; the caller owes a ``release()``); else the shed decision."""
        now = time.monotonic()
        with self._lock:
            # concurrency first: an in-flight shed must not burn the
            # agent's rate token (the retry would then need two)
            if (
                self.max_inflight is not None
                and self._inflight >= self.max_inflight
            ):
                metrics.count("http.throttled.inflight")
                # no queue to estimate from: hint one "typical request" out
                return ShedDecision(503, 0.05, "server at max in-flight")
            if self.rate is not None:
                if self.rate <= 0.0:
                    # "block everything" config: shed without a bucket
                    # (a zero-rate bucket could never hand out a hint)
                    metrics.count("http.throttled.rate")
                    return ShedDecision(429, 1.0, "per-agent rate limit")
                bucket = self._buckets.get(agent_key)
                if bucket is None:
                    if len(self._buckets) >= _MAX_BUCKETS:
                        # hard bound even under fresh-key churn (the key is
                        # an UNVERIFIED username): stale-sweep at most every
                        # few seconds, otherwise evict the oldest-created
                        # entry O(1) — an attacker minting usernames cycles
                        # this dict, never grows it
                        if now - self._last_prune > 5.0:
                            self._last_prune = now
                            cutoff = now - _BUCKET_IDLE_S
                            for key in [k for k, b in self._buckets.items()
                                        if b.stamp < cutoff]:
                                del self._buckets[key]
                        if len(self._buckets) >= _MAX_BUCKETS:
                            del self._buckets[next(iter(self._buckets))]
                    bucket = self._buckets[agent_key] = TokenBucket(
                        self.rate, self.burst, now
                    )
                wait = bucket.try_take(now)
                if wait > 0.0:
                    metrics.count("http.throttled.rate")
                    return ShedDecision(429, wait, "per-agent rate limit")
            self._inflight += 1
            depth = self._inflight
        metrics.gauge_set("http.inflight", depth)
        metrics.gauge_max("http.inflight.peak", depth)
        return None

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1
            depth = self._inflight
        metrics.gauge_set("http.inflight", depth)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight
