"""L5: REST transport — server routes and the client-side service proxy.

Two wire-identical server planes share one dispatch core (``base.py``):
the thread-per-connection ``SdaHttpServer`` and the asyncio event-loop
``SdaAsyncHttpServer`` (``sdad --async``, docs/scaling.md)."""

from .aserver import SdaAsyncHttpServer
from .client import SdaHttpClient
from .server import SdaHttpServer


def server_class(async_http: bool = False):
    """The plane selector every driver shares (``--async`` flags)."""
    return SdaAsyncHttpServer if async_http else SdaHttpServer
