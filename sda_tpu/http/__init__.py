"""L5: REST transport — server routes and the client-side service proxy."""

from .client import SdaHttpClient
from .server import SdaHttpServer
