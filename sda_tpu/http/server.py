"""REST server: the SdaService exposed over HTTP/JSON.

Route map mirrors the reference's endpoint scheme (server-http/src/lib.rs
doc table :19-60):

    GET    /v1/ping
    POST   /v1/agents/me
    GET    /v1/agents/{AgentId}
    POST   /v1/agents/me/profile
    GET    /v1/agents/{AgentId}/profile
    POST   /v1/agents/me/keys
    GET    /v1/agents/any/keys/{EncryptionKeyId}
    POST   /v1/aggregations
    GET    /v1/aggregations?title=&recipient=
    GET    /v1/aggregations/{AggregationId}
    DELETE /v1/aggregations/{AggregationId}
    GET    /v1/aggregations/{AggregationId}/committee/suggestions
    POST   /v1/aggregations/implied/committee
    GET    /v1/aggregations/{AggregationId}/committee
    POST   /v1/aggregations/participations
    GET    /v1/aggregations/{AggregationId}/status
    POST   /v1/aggregations/implied/snapshot
    GET    /v1/aggregations/any/jobs
    POST   /v1/aggregations/implied/jobs/{ClerkingJobId}/result
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result

Authentication is HTTP Basic: username = agent id, password = a
client-minted token. The token presented on the agent-creation POST is
recorded and must be reused on subsequent requests (lib.rs:192-201).
Missing resources answer 404 with an ``X-Resource-Not-Found`` header so
clients can distinguish a missing resource from a missing route
(lib.rs:338-343); errors map to 401/403/400/500 (lib.rs:105-122).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..protocol import AgentId, InvalidRequest
from ..protocol import bincodec
from ..server import SdaServerService
from ..server.routing import NODE_HEADER
from ..utils import metrics
from .. import chaos, obs
from . import base
from .admission import AdmissionControl, TENANT_HEADER
#: Re-exports: the route table and label live in ``http/base.py`` now,
#: shared with the async plane; existing importers keep working.
from .base import REQUEST_ID_RE as _REQUEST_ID_RE  # noqa: F401
from .base import ROUTE_TEMPLATES as _ROUTE_TEMPLATES  # noqa: F401
from .base import route_label  # noqa: F401

log = logging.getLogger(__name__)
#: Dedicated child logger for the per-span trace lines, so ``sdad --trace``
#: can unmute EXACTLY them without also unmuting the access log.
trace_log = logging.getLogger(__name__ + ".trace")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "sda-tpu"

    # silence default stderr spam; route through logging instead
    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    # -- helpers -----------------------------------------------------------
    @property
    def service(self) -> SdaServerService:
        return self.server.sda_service  # type: ignore[attr-defined]

    def _credentials(self) -> Optional[Tuple[AgentId, str]]:
        return base.parse_basic_auth(self.headers.get("Authorization"))

    def _content_length(self) -> int:
        """Negative (or garbage) Content-Length must 400, not turn
        ``rfile.read`` into a blocking read-to-EOF that pins this
        handler thread until the client hangs up."""
        length = base.parse_content_length(
            self.headers.get("Content-Length"))
        if length < 0:
            raise InvalidRequest("bad Content-Length")
        return length

    def _raw_body(self) -> bytes:
        length = self._content_length()
        raw = self.rfile.read(length) if length else b""
        self._body_consumed = True
        return raw

    def _json_body(self):
        raw = self._raw_body()
        if not raw:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise InvalidRequest(f"malformed JSON body: {e}")

    # -- binary wire codec (application/x-sda-bin) -------------------------
    def _bin_enabled(self) -> bool:
        return getattr(self.server, "bin_codec", True)

    def _body_is_bin(self) -> bool:
        ctype = (self.headers.get("Content-Type") or "")
        return (self._bin_enabled()
                and ctype.split(";")[0].strip().lower() == bincodec.CONTENT_TYPE)

    def _accepts_bin(self) -> bool:
        return (self._bin_enabled()
                and bincodec.CONTENT_TYPE in (self.headers.get("Accept") or ""))

    def _hot_body(self, expect_tag, from_obj):
        """Decode a hot-route POST body by its content type: negotiated
        binary frame or the JSON fallback (old peers). Codec decode
        errors raise ValueError -> 400, exactly like malformed JSON.

        Binary bodies STREAM through the incremental decoder
        (``bincodec.FeedDecoder``): chunks feed straight into the resource
        under construction, so per-request memory is bounded by the
        largest single field frame, not the whole dim-1e8 upload."""
        if self._body_is_bin():
            metrics.count("http.codec.bin.in")
            length = self._content_length()
            self._body_consumed = True  # we own the body bytes from here
            decoder = bincodec.FeedDecoder(expect_tag)
            remaining = length
            try:
                while remaining:
                    chunk = self.rfile.read(min(65536, remaining))
                    if not chunk:
                        self.close_connection = True
                        raise ValueError("truncated x-sda-bin body")
                    remaining -= len(chunk)
                    decoder.feed(chunk)
                return decoder.finish()
            except ValueError:
                # drain what's left so keep-alive framing survives the
                # 400 — bounded, like _reply's drain: a client that
                # advertised bytes and stalls forfeits the connection
                # instead of pinning this thread
                try:
                    previous = self.connection.gettimeout()
                    self.connection.settimeout(5.0)
                    try:
                        while remaining:
                            chunk = self.rfile.read(min(65536, remaining))
                            if not chunk:
                                self.close_connection = True
                                break
                            remaining -= len(chunk)
                    finally:
                        self.connection.settimeout(previous)
                except OSError:  # includes socket.timeout: framing lost
                    self.close_connection = True
                raise
        metrics.count("http.codec.json.in")
        return from_obj(self._json_body())

    def _reply(self, status: int, obj=None, resource_not_found=False,
               retry_after=None, raw=None, content_type="application/json",
               extra_headers=None):
        if raw is not None:
            body = raw
        else:
            body = b"" if obj is None else json.dumps(obj).encode("utf-8")
        # failpoint: the service call already happened — dropping HERE
        # simulates a lost response (side effect durable, client in the
        # dark), the exact hazard create-once retry semantics must absorb;
        # delay stalls the ack instead
        action = chaos.evaluate("http.server.response", kinds=("drop", "delay"))
        if action is not None:
            if action.kind == "drop":
                log.info("%s %s -> chaos-dropped response", self.command, self.path)
                self.close_connection = True
                return
            time.sleep(action.delay_s)
        # replying before the handler consumed the request body (auth
        # failures, injected 500s, malformed-route errors on POSTs) would
        # leave the body bytes in the keep-alive stream, where they get
        # parsed as the next request line — drain them first, but bounded:
        # a client that advertised a body and never sends it must not pin
        # this thread, so a stalled drain forfeits the connection instead
        length = base.parse_content_length(self.headers.get("Content-Length"))
        if length < 0:
            # garbage framing: nothing sane to drain, sever instead
            length = 0
            self.close_connection = True
        if length and not self._body_consumed:
            self._body_consumed = True
            try:
                previous = self.connection.gettimeout()
                self.connection.settimeout(5.0)
                try:
                    self.rfile.read(length)
                finally:
                    self.connection.settimeout(previous)
            except OSError:  # includes socket.timeout: framing is lost
                self.close_connection = True
        # per-request status line + counters (reference: the rouille wrapper
        # logs method/path/status per request, server-http/src/lib.rs:105-122).
        # Counted BEFORE the body write: once a client has the response, the
        # counters must already reflect it (no read-after-response race).
        dt_ms = (time.perf_counter() - self._t0) * 1e3 if self._t0 else 0.0
        if status >= 400:
            # correlate error replies with the echoed X-Request-Id so a
            # client-side failure report can be grepped straight to the
            # server-side record (and its trace)
            log.info("%s %s -> %d (%.1fms) request_id=%s",
                     self.command, self.path, status, dt_ms, self._request_id)
        else:
            log.info("%s %s -> %d (%.1fms)", self.command, self.path, status,
                     dt_ms)
        span = self._span
        if span is not None and "http.status" not in span.attributes:
            # first write wins: a failed body write re-enters _reply with a
            # 500, but the status the CLIENT saw is the one already recorded
            span.set_attribute("http.status", status)
        if not self._counted:  # a failed write re-enters _reply via the
            self._counted = True  # _route catch-all: count the request once
            counts = getattr(self.server, "status_counts", None)
            if counts is not None:
                with self.server.stats_lock:  # type: ignore[attr-defined]
                    counts[status] = counts.get(status, 0) + 1
            metrics.count("http.request")
            metrics.count(f"http.status.{status}")
            if self._shed:
                # an admission rejection is not a service latency: folding
                # sub-ms sheds into the route histogram would collapse the
                # reported tails exactly when overload makes them matter
                metrics.observe("http.latency.shed", dt_ms / 1e3)
            else:
                label = route_label(
                    self.command, getattr(self, "_route_path", None) or "/"
                )
                metrics.observe(f"http.latency.{label}", dt_ms / 1e3)
        self.send_response(status)
        if self._request_id:
            # echo the correlation id on EVERY response (reused from the
            # request when the client sent one, minted server-side else)
            self.send_header(obs.REQUEST_ID_HEADER, self._request_id)
        node_id = getattr(self.server, "node_id", None)
        if node_id:
            # fleet plane: name the worker that actually served this
            # request, so clients/loadgen can verify (advisory) routing
            # and per-node tallies without scraping anything
            self.send_header(NODE_HEADER, node_id)
        if self._bin_enabled():
            # codec advert: clients in "auto" mode upgrade the hot routes
            # to application/x-sda-bin after seeing this on ANY response
            self.send_header(bincodec.CODECS_HEADER, "bin")
        if extra_headers:
            for key, value in extra_headers.items():
                self.send_header(key, value)
        if resource_not_found:
            self.send_header("X-Resource-Not-Found", "true")
        if retry_after is not None:
            # fractional seconds: RFC 9110 says integers, but both ends of
            # this wire are ours and sub-second hints are what make the
            # token-bucket convergence fast; foreign clients that int-parse
            # still get a sane 0/1
            self.send_header("Retry-After", f"{max(0.0, retry_after):.3f}")
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    _t0 = 0.0
    _counted = False
    _body_consumed = False
    _route_path = None
    _shed = False
    _span = None
    _request_id = None

    def _agent_key(self) -> str:
        """Admission key: the CLAIMED agent id (token unverified — rate
        limiting must not pay the auth lookup it protects), else the
        client address for unauthenticated requests."""
        creds = self._credentials()
        if creds is not None:
            return str(creds[0])
        return str(self.client_address[0])

    def _tenant_key(self) -> Optional[str]:
        return base.tenant_key(self.headers.get(TENANT_HEADER))

    # -- dispatch ----------------------------------------------------------
    def _route(self, method: str):
        self._t0 = time.perf_counter()
        self._counted = False  # per-request (connections are reused)
        self._body_consumed = False
        self._shed = False
        self._span = None
        # active-REQUEST census (not connections: an idle keep-alive socket
        # parked in readline is not in-flight work) — what graceful drain
        # waits on before releasing leases and closing
        with self.server.stats_lock:  # type: ignore[attr-defined]
            self.server.active_requests += 1  # type: ignore[attr-defined]
        try:
            self._route_inner(method)
        finally:
            with self.server.stats_lock:  # type: ignore[attr-defined]
                self.server.active_requests -= 1  # type: ignore[attr-defined]

    def _route_inner(self, method: str):
        url = urlparse(self.path)
        path = url.path.rstrip("/")
        query = parse_qs(url.query)
        self._route_path = path or "/"
        self._request_id = base.request_id(
            self.headers.get(obs.REQUEST_ID_HEADER))
        # draining (a keep-alive connection can still deliver a NEW
        # request after the accept loop stopped — turn it away before
        # any auth/store work) + the admission/tracing-exempt
        # observability endpoints, shared with the async plane
        pre = base.preroute_reply(self.server, method, path)
        if pre is not None:
            return self._send_reply(pre)
        # protocol garbage pre-dispatch, matching the async plane's
        # header-parse-time rejection: a negative Content-Length would
        # otherwise turn body reads/drains into read-to-EOF stalls
        if base.parse_content_length(self.headers.get("Content-Length")) < 0:
            self.close_connection = True
            return self._reply(400, {"error": "bad Content-Length"})

        # server span: joins the caller's trace when the request carries a
        # W3C traceparent header, else roots a fresh trace. Everything the
        # handler does — admission verdicts, service calls, store ops,
        # snapshot phases — lands as descendants of this span.
        label = route_label(method, self._route_path)
        parent = obs.parse_traceparent(
            self.headers.get(obs.TRACEPARENT_HEADER))
        span_attributes = {"http.method": method, "http.route": label,
                           "request_id": self._request_id}
        node_id = getattr(self.server, "node_id", None)
        if node_id:
            # round timelines show which fleet worker served each hop
            span_attributes["node_id"] = node_id
        with obs.span(
            f"http.server {label}", parent=parent, kind="server",
            attributes=span_attributes,
        ) as server_span:
            self._span = server_span
            try:
                # admission control: shed BEFORE auth/crypto/store work. A
                # rejected request costs one header parse; Retry-After tells
                # the retrying transport exactly when the token bucket
                # refills.
                admission = getattr(self.server, "admission", None)
                if admission is not None and admission.enabled:
                    shed = admission.admit(self._agent_key(),
                                           tenant_key=self._tenant_key())
                    if shed is not None:
                        log.debug("%s %s -> %d shed (%s, retry in %.3fs)",
                                  method, path, shed.status, shed.reason,
                                  shed.retry_after)
                        self._shed = True
                        server_span.set_attribute("shed", shed.reason)
                        return self._reply(
                            shed.status,
                            {"error": f"throttled: {shed.reason}"},
                            retry_after=shed.retry_after,
                        )
                    try:
                        return self._dispatch(method, path, query)
                    finally:
                        admission.release()
                return self._dispatch(method, path, query)
            finally:
                if getattr(self.server, "trace_log", False):
                    trace_log.info(
                        "trace %s %s %s status=%s request_id=%s",
                        server_span.trace_id, method, self._route_path,
                        server_span.attributes.get("http.status"),
                        self._request_id,
                    )

    def _dispatch(self, method: str, path: str, query):
        """One request through the shared route table (``http/base.py``):
        build the transport adapter, dispatch, park long-polls on this
        request thread, then write the decided reply."""
        rx = _HandlerExchange(self, method, path, query)
        reply = base.dispatch(self.service, rx)
        if reply.park is not None:
            # long-poll: block THIS request thread (the threaded plane's
            # park) until a job lands, the wait expires, or drain wakes
            # us — the admission slot and the active-request census both
            # cover the parked time, which is what drain waits on
            if self._span is not None:
                self._span.set_attribute("longpoll.parked", True)
            reply = base.blocking_park(
                self.service, reply.park,
                draining=lambda: getattr(self.server, "draining", False),
                fleet_peers=getattr(self.server, "fleet_peers", None))
        self._send_reply(reply)

    def _send_reply(self, reply) -> None:
        if reply.drop:
            # chaos "drop": sever without response bytes
            log.info("%s %s -> chaos-dropped connection",
                     self.command, self.path)
            self.close_connection = True
            return
        if reply.span_attrs and self._span is not None:
            for key, value in reply.span_attrs.items():
                self._span.set_attribute(key, value)
        if reply.close:
            self.close_connection = True
        self._reply(
            reply.status, reply.obj, raw=reply.raw,
            content_type=reply.content_type,
            resource_not_found=reply.resource_not_found,
            retry_after=reply.retry_after, extra_headers=reply.headers,
        )

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")


class _HandlerExchange:
    """The threaded plane's transport adapter for ``base.dispatch``:
    thin delegation onto the live ``BaseHTTPRequestHandler``."""

    __slots__ = ("_h", "method", "path", "query")

    def __init__(self, handler: _Handler, method: str, path: str, query):
        self._h = handler
        self.method = method
        self.path = path
        self.query = query

    def header(self, name: str):
        return self._h.headers.get(name)

    def json_body(self):
        return self._h._json_body()

    def hot_body(self, expect_tag, from_obj):
        return self._h._hot_body(expect_tag, from_obj)

    def accepts_bin(self) -> bool:
        return self._h._accepts_bin()

    def credentials(self):
        return self._h._credentials()

    def agent_key(self) -> str:
        return self._h._agent_key()


class SdaHttpServer:
    """Threaded HTTP server wrapping an SdaServerService.

    ``max_inflight`` / ``rate_limit`` / ``rate_burst`` arm the admission
    layer (both default off — zero overhead and bit-compatible behavior
    with the pre-admission server); ``metrics_endpoint`` enables the
    plaintext Prometheus exposition at ``GET /metrics`` (off by default:
    it reveals traffic shape, opt in via ``sdad --metrics``);
    ``statusz_endpoint`` enables the ``GET /statusz`` JSON debug page
    (uptime, store backend, in-flight/peak gauges, lease stats, devprof
    compile totals — same opt-in reasoning, ``sdad --statusz``);
    ``trace_log`` logs one INFO line per finished server span (trace id,
    route, status, request id — ``sdad --trace``);
    ``bin_codec=False`` turns the binary wire codec off (no advert, no
    ``application/x-sda-bin`` parsing) — the old-JSON-server posture the
    mixed-version tests pin.

    ``node_id`` names this worker in a fleet (``sda-fleet``,
    docs/scaling.md): it rides every response as ``X-SDA-Node``, labels
    ``/metrics`` samples and ``/statusz``, and lands on every server span
    so round timelines attribute hops to workers. ``fleet_peers`` records
    the fleet size as the ``fleet.peers`` gauge.
    """

    def __init__(
        self,
        service: SdaServerService,
        bind: str = "127.0.0.1:8888",
        *,
        max_inflight: Optional[int] = None,
        rate_limit: Optional[float] = None,
        rate_burst: float = 8.0,
        tenant_rate: Optional[float] = None,
        tenant_burst: float = 32.0,
        metrics_endpoint: bool = False,
        statusz_endpoint: bool = False,
        trace_log: bool = False,
        bin_codec: bool = True,
        node_id: Optional[str] = None,
        fleet_peers: Optional[int] = None,
    ):
        host, _, port = bind.partition(":")
        self.httpd = ThreadingHTTPServer((host, int(port or 8888)), _Handler)
        self.httpd.bin_codec = bin_codec  # type: ignore[attr-defined]
        self.httpd.sda_service = service  # type: ignore[attr-defined]
        self.httpd.status_counts = {}  # type: ignore[attr-defined]
        self.httpd.stats_lock = threading.Lock()  # type: ignore[attr-defined]
        self.httpd.active_requests = 0  # type: ignore[attr-defined]
        self.httpd.draining = False  # type: ignore[attr-defined]
        self.node_id = node_id
        self.fleet_peers = fleet_peers
        self.httpd.node_id = node_id  # type: ignore[attr-defined]
        service.server.node_id = node_id
        if fleet_peers is not None:
            metrics.gauge_set("fleet.peers", fleet_peers)
        self.admission = AdmissionControl(
            max_inflight=max_inflight, rate=rate_limit, burst=rate_burst,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
        )
        self.httpd.admission = self.admission  # type: ignore[attr-defined]
        self.httpd.metrics_enabled = metrics_endpoint  # type: ignore[attr-defined]
        self.httpd.statusz_fn = (  # type: ignore[attr-defined]
            self.statusz if statusz_endpoint else None)
        self.httpd.trace_log = trace_log  # type: ignore[attr-defined]
        self._started_at = time.time()
        self._thread: Optional[threading.Thread] = None

    def statusz(self) -> dict:
        """The ``GET /statusz`` payload, built by the shared
        ``base.build_statusz`` so fleet-mode counter aggregation reads
        identical fields off either HTTP plane."""
        return base.build_statusz(
            self.httpd.sda_service,  # type: ignore[attr-defined]
            node_id=self.node_id, admission=self.admission,
            started_at=self._started_at, status_counts=self.status_counts,
            plane="threaded",
        )

    def configure_admission(
        self,
        max_inflight: Optional[int] = None,
        rate_limit: Optional[float] = None,
        rate_burst: Optional[float] = None,
        tenant_rate: Optional[float] = None,
        tenant_burst: Optional[float] = None,
    ) -> None:
        """Retune (or disable, with all-``None``) admission at runtime —
        the loadgen driver arms overload profiles only after round setup."""
        self.admission.configure(
            max_inflight=max_inflight, rate=rate_limit, burst=rate_burst,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
        )

    @property
    def sda_service(self) -> SdaServerService:
        """The wrapped service — uniform across both planes (the async
        plane exposes the same attribute), so drivers and tests can
        reach ``server.sda_service.server`` without knowing the plane."""
        return self.httpd.sda_service  # type: ignore[attr-defined]

    @property
    def status_counts(self) -> dict:
        """Requests served, keyed by HTTP status (observability floor)."""
        with self.httpd.stats_lock:  # type: ignore[attr-defined]
            return dict(self.httpd.status_counts)  # type: ignore[attr-defined]

    @property
    def active_requests(self) -> int:
        """Requests currently being handled (idle keep-alive connections
        excluded — their threads are parked in readline, not working)."""
        with self.httpd.stats_lock:  # type: ignore[attr-defined]
            return self.httpd.active_requests  # type: ignore[attr-defined]

    def drain(self, grace_s: float = 10.0) -> dict:
        """Graceful shutdown (the fleet worker's SIGTERM path): stop
        accepting, let in-flight requests finish (bounded by ``grace_s``),
        hand every clerking-job lease this worker still holds back to the
        shared store so a fleet peer's next poll reissues the work
        immediately (no visibility-timeout wait), then close. Returns the
        drain summary — ``leaked`` must be 0 for a clean exit
        (docs/scaling.md)."""
        # reject-then-stop: established keep-alive connections can still
        # deliver new requests after the accept loop stops, so flip the
        # draining flag FIRST (handlers answer 503 + Connection: close
        # from here on), then stop the accept/serve loop and wait out the
        # requests that were already in flight
        self.httpd.draining = True  # type: ignore[attr-defined]
        service = self.httpd.sda_service  # type: ignore[attr-defined]
        # wake every parked long-poll NOW: a parked clerk must get its
        # 503 + Connection: close immediately (and count as finished
        # in-flight work below), not hold the drain to its wait timeout
        wakeup = getattr(service.server, "job_wakeup", None)
        if wakeup is not None:
            wakeup.notify_all()
        self.httpd.shutdown()  # blocks until the serve loop exits
        deadline = time.monotonic() + grace_s
        while self.active_requests and time.monotonic() < deadline:
            time.sleep(0.02)
        stranded = self.active_requests
        summary = base.drain_summary(service, node_id=self.node_id,
                                     stranded=stranded)
        self.shutdown()  # joins the (already finished) serve-loop thread
        return summary

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start_background(self) -> "SdaHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self.httpd.serve_forever()

    def shutdown(self):
        self.httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a wedged handler (stuck client socket, runaway store op)
                # survives shutdown(); don't hang the caller forever, but
                # don't hide the leak either
                log.warning(
                    "HTTP server thread did not stop within 5s; "
                    "leaking daemon thread %s", self._thread.name,
                )
                metrics.count("http.shutdown.leaked")
        self.httpd.server_close()
