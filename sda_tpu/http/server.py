"""REST server: the SdaService exposed over HTTP/JSON.

Route map mirrors the reference's endpoint scheme (server-http/src/lib.rs
doc table :19-60):

    GET    /v1/ping
    POST   /v1/agents/me
    GET    /v1/agents/{AgentId}
    POST   /v1/agents/me/profile
    GET    /v1/agents/{AgentId}/profile
    POST   /v1/agents/me/keys
    GET    /v1/agents/any/keys/{EncryptionKeyId}
    POST   /v1/aggregations
    GET    /v1/aggregations?title=&recipient=
    GET    /v1/aggregations/{AggregationId}
    DELETE /v1/aggregations/{AggregationId}
    GET    /v1/aggregations/{AggregationId}/committee/suggestions
    POST   /v1/aggregations/implied/committee
    GET    /v1/aggregations/{AggregationId}/committee
    POST   /v1/aggregations/participations
    GET    /v1/aggregations/{AggregationId}/status
    POST   /v1/aggregations/implied/snapshot
    GET    /v1/aggregations/any/jobs
    POST   /v1/aggregations/implied/jobs/{ClerkingJobId}/result
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result

Authentication is HTTP Basic: username = agent id, password = a
client-minted token. The token presented on the agent-creation POST is
recorded and must be reused on subsequent requests (lib.rs:192-201).
Missing resources answer 404 with an ``X-Resource-Not-Found`` header so
clients can distinguish a missing resource from a missing route
(lib.rs:338-343); errors map to 401/403/400/500 (lib.rs:105-122).
"""

from __future__ import annotations

import base64
import json
import logging
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    Participation,
    ParticipationConflict,
    PermissionDenied,
    Profile,
    SdaError,
    Snapshot,
    SnapshotId,
    StoreUnavailable,
    signed_encryption_key_from_obj,
)
from ..protocol import bincodec
from ..server import SdaServerService, auth_token
from ..server import health as _health
from ..server import lifecycle as _lifecycle
from ..server.routing import NODE_HEADER
from ..utils import metrics
from .. import chaos, obs
from .admission import AdmissionControl, TENANT_HEADER

log = logging.getLogger(__name__)
#: Dedicated child logger for the per-span trace lines, so ``sdad --trace``
#: can unmute EXACTLY them without also unmuting the access log.
trace_log = logging.getLogger(__name__ + ".trace")

_ID = r"[0-9a-fA-F-]{36}"

#: Every route template the dispatcher matches, ids collapsed to ``{id}``.
#: Latency histograms are keyed by template (low cardinality by
#: construction); anything else becomes ``unmatched`` so a scanner probing
#: random paths cannot grow the histogram registry without bound.
_ROUTE_TEMPLATES = frozenset({
    "/v1/ping",
    "/v1/agents/me",
    "/v1/agents/{id}",
    "/v1/agents/me/profile",
    "/v1/agents/{id}/profile",
    "/v1/agents/me/keys",
    "/v1/agents/any/keys/{id}",
    "/v1/aggregations",
    "/v1/aggregations/{id}",
    "/v1/aggregations/{id}/committee/suggestions",
    "/v1/aggregations/implied/committee",
    "/v1/aggregations/{id}/committee",
    "/v1/aggregations/participations",
    "/v1/aggregations/{id}/status",
    "/v1/aggregations/{id}/round",
    "/v1/aggregations/implied/snapshot",
    "/v1/aggregations/any/jobs",
    "/v1/aggregations/implied/jobs/{id}/result",
    "/v1/aggregations/{id}/snapshots/{id}/result",
    "/metrics",
    "/statusz",
})
_ID_RE = re.compile(_ID)
#: Charset a client-supplied X-Request-Id must satisfy to be echoed back
#: (response-header injection hygiene).
_REQUEST_ID_RE = re.compile(r"[A-Za-z0-9._-]+")


def _schedules_report(server) -> Optional[dict]:
    """The ``/statusz`` schedules block (lazy import: the service plane
    only loads when a scrape actually asks for it)."""
    from ..service.scheduler import schedules_report

    try:
        return schedules_report(server)
    except Exception:  # a third-party store without schedule support
        return None


def route_label(method: str, path: str) -> str:
    """``GET /v1/agents/3f2a... -> "GET:/v1/agents/{id}"`` — the
    per-route key under ``http.latency.<route>``."""
    template = _ID_RE.sub("{id}", path)
    if template not in _ROUTE_TEMPLATES:
        return f"{method}:unmatched"
    return f"{method}:{template}"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "sda-tpu"

    # silence default stderr spam; route through logging instead
    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    # -- helpers -----------------------------------------------------------
    @property
    def service(self) -> SdaServerService:
        return self.server.sda_service  # type: ignore[attr-defined]

    def _credentials(self) -> Optional[Tuple[AgentId, str]]:
        header = self.headers.get("Authorization", "")
        if not header.startswith("Basic "):
            return None
        try:
            decoded = base64.b64decode(header[6:]).decode("utf-8")
            agent_id, _, token = decoded.partition(":")
            return AgentId(agent_id), token
        except (ValueError, UnicodeDecodeError):
            return None

    def _authenticate(self) -> Agent:
        creds = self._credentials()
        if creds is None:
            raise InvalidCredentials("missing Basic auth")
        return self.service.server.check_auth_token(auth_token(*creds))

    def _raw_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b""
        self._body_consumed = True
        return raw

    def _json_body(self):
        raw = self._raw_body()
        if not raw:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise InvalidRequest(f"malformed JSON body: {e}")

    # -- binary wire codec (application/x-sda-bin) -------------------------
    def _bin_enabled(self) -> bool:
        return getattr(self.server, "bin_codec", True)

    def _body_is_bin(self) -> bool:
        ctype = (self.headers.get("Content-Type") or "")
        return (self._bin_enabled()
                and ctype.split(";")[0].strip().lower() == bincodec.CONTENT_TYPE)

    def _accepts_bin(self) -> bool:
        return (self._bin_enabled()
                and bincodec.CONTENT_TYPE in (self.headers.get("Accept") or ""))

    def _hot_body(self, decode_bin, from_obj):
        """Decode a hot-route POST body by its content type: negotiated
        binary frame or the JSON fallback (old peers). Codec decode
        errors raise ValueError -> 400, exactly like malformed JSON."""
        if self._body_is_bin():
            metrics.count("http.codec.bin.in")
            return decode_bin(self._raw_body())
        metrics.count("http.codec.json.in")
        return from_obj(self._json_body())

    def _reply(self, status: int, obj=None, resource_not_found=False,
               retry_after=None, raw=None, content_type="application/json",
               extra_headers=None):
        if raw is not None:
            body = raw
        else:
            body = b"" if obj is None else json.dumps(obj).encode("utf-8")
        # failpoint: the service call already happened — dropping HERE
        # simulates a lost response (side effect durable, client in the
        # dark), the exact hazard create-once retry semantics must absorb;
        # delay stalls the ack instead
        action = chaos.evaluate("http.server.response", kinds=("drop", "delay"))
        if action is not None:
            if action.kind == "drop":
                log.info("%s %s -> chaos-dropped response", self.command, self.path)
                self.close_connection = True
                return
            time.sleep(action.delay_s)
        # replying before the handler consumed the request body (auth
        # failures, injected 500s, malformed-route errors on POSTs) would
        # leave the body bytes in the keep-alive stream, where they get
        # parsed as the next request line — drain them first, but bounded:
        # a client that advertised a body and never sends it must not pin
        # this thread, so a stalled drain forfeits the connection instead
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length and not self._body_consumed:
            self._body_consumed = True
            try:
                previous = self.connection.gettimeout()
                self.connection.settimeout(5.0)
                try:
                    self.rfile.read(length)
                finally:
                    self.connection.settimeout(previous)
            except OSError:  # includes socket.timeout: framing is lost
                self.close_connection = True
        # per-request status line + counters (reference: the rouille wrapper
        # logs method/path/status per request, server-http/src/lib.rs:105-122).
        # Counted BEFORE the body write: once a client has the response, the
        # counters must already reflect it (no read-after-response race).
        dt_ms = (time.perf_counter() - self._t0) * 1e3 if self._t0 else 0.0
        if status >= 400:
            # correlate error replies with the echoed X-Request-Id so a
            # client-side failure report can be grepped straight to the
            # server-side record (and its trace)
            log.info("%s %s -> %d (%.1fms) request_id=%s",
                     self.command, self.path, status, dt_ms, self._request_id)
        else:
            log.info("%s %s -> %d (%.1fms)", self.command, self.path, status,
                     dt_ms)
        span = self._span
        if span is not None and "http.status" not in span.attributes:
            # first write wins: a failed body write re-enters _reply with a
            # 500, but the status the CLIENT saw is the one already recorded
            span.set_attribute("http.status", status)
        if not self._counted:  # a failed write re-enters _reply via the
            self._counted = True  # _route catch-all: count the request once
            counts = getattr(self.server, "status_counts", None)
            if counts is not None:
                with self.server.stats_lock:  # type: ignore[attr-defined]
                    counts[status] = counts.get(status, 0) + 1
            metrics.count("http.request")
            metrics.count(f"http.status.{status}")
            if self._shed:
                # an admission rejection is not a service latency: folding
                # sub-ms sheds into the route histogram would collapse the
                # reported tails exactly when overload makes them matter
                metrics.observe("http.latency.shed", dt_ms / 1e3)
            else:
                label = route_label(
                    self.command, getattr(self, "_route_path", None) or "/"
                )
                metrics.observe(f"http.latency.{label}", dt_ms / 1e3)
        self.send_response(status)
        if self._request_id:
            # echo the correlation id on EVERY response (reused from the
            # request when the client sent one, minted server-side else)
            self.send_header(obs.REQUEST_ID_HEADER, self._request_id)
        node_id = getattr(self.server, "node_id", None)
        if node_id:
            # fleet plane: name the worker that actually served this
            # request, so clients/loadgen can verify (advisory) routing
            # and per-node tallies without scraping anything
            self.send_header(NODE_HEADER, node_id)
        if self._bin_enabled():
            # codec advert: clients in "auto" mode upgrade the hot routes
            # to application/x-sda-bin after seeing this on ANY response
            self.send_header(bincodec.CODECS_HEADER, "bin")
        if extra_headers:
            for key, value in extra_headers.items():
                self.send_header(key, value)
        if resource_not_found:
            self.send_header("X-Resource-Not-Found", "true")
        if retry_after is not None:
            # fractional seconds: RFC 9110 says integers, but both ends of
            # this wire are ours and sub-second hints are what make the
            # token-bucket convergence fast; foreign clients that int-parse
            # still get a sane 0/1
            self.send_header("Retry-After", f"{max(0.0, retry_after):.3f}")
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_option(self, obj, extra_headers=None):
        if obj is None:
            self._reply(404, {"error": "resource not found"}, resource_not_found=True)
        else:
            self._reply(200, obj.to_obj(), extra_headers=extra_headers)

    _t0 = 0.0
    _counted = False
    _body_consumed = False
    _route_path = None
    _shed = False
    _span = None
    _request_id = None

    def _agent_key(self) -> str:
        """Admission key: the CLAIMED agent id (token unverified — rate
        limiting must not pay the auth lookup it protects), else the
        client address for unauthenticated requests."""
        creds = self._credentials()
        if creds is not None:
            return str(creds[0])
        return str(self.client_address[0])

    def _tenant_key(self) -> Optional[str]:
        """Per-tenant admission key: the CLAIMED recipient id from the
        ``X-SDA-Tenant`` header (unverified, same trust model as the
        agent key), token charset + bounded length so a hostile value
        cannot grow the bucket dict with junk or smuggle bytes."""
        claimed = self.headers.get(TENANT_HEADER, "")
        if claimed and len(claimed) <= 64 \
                and _REQUEST_ID_RE.fullmatch(claimed):
            return claimed
        return None

    # -- dispatch ----------------------------------------------------------
    def _route(self, method: str):
        self._t0 = time.perf_counter()
        self._counted = False  # per-request (connections are reused)
        self._body_consumed = False
        self._shed = False
        self._span = None
        # active-REQUEST census (not connections: an idle keep-alive socket
        # parked in readline is not in-flight work) — what graceful drain
        # waits on before releasing leases and closing
        with self.server.stats_lock:  # type: ignore[attr-defined]
            self.server.active_requests += 1  # type: ignore[attr-defined]
        try:
            self._route_inner(method)
        finally:
            with self.server.stats_lock:  # type: ignore[attr-defined]
                self.server.active_requests -= 1  # type: ignore[attr-defined]

    def _route_inner(self, method: str):
        if getattr(self.server, "draining", False):
            # graceful drain: the accept loop is already stopped, but an
            # established keep-alive connection can still deliver a NEW
            # request — turn it away before any auth/store work (a lease
            # granted now would die with the process) and close the
            # connection so the client reconnects against a live peer
            self.close_connection = True
            metrics.count("http.drain.rejected")
            return self._reply(
                503, {"error": "draining"},
                extra_headers={"Connection": "close"}, retry_after=1.0,
            )
        url = urlparse(self.path)
        path = url.path.rstrip("/")
        query = parse_qs(url.query)
        self._route_path = path or "/"
        # correlation id: reuse the client's X-Request-Id, mint one else.
        # The value is echoed into a response header, so a hostile one must
        # not smuggle CRLFs or unbounded bytes: token charset, capped length
        claimed = self.headers.get(obs.REQUEST_ID_HEADER, "")
        if not (claimed and len(claimed) <= 64
                and _REQUEST_ID_RE.fullmatch(claimed)):
            claimed = obs.new_request_id()
        self._request_id = claimed

        # observability plane: exempt from admission (scrapes must land
        # during the exact overload they are meant to diagnose) and from
        # tracing (a scrape loop would churn the span ring buffer)
        if method == "GET" and path == "/metrics":
            if not getattr(self.server, "metrics_enabled", False):
                return self._reply(404, {"error": "metrics endpoint disabled "
                                                  "(sdad --metrics)"})
            node_id = getattr(self.server, "node_id", None)
            return self._reply(
                200, raw=metrics.prometheus_text(
                    labels={"node_id": node_id} if node_id else None
                ).encode("utf-8"),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        if method == "GET" and path == "/statusz":
            statusz = getattr(self.server, "statusz_fn", None)
            if statusz is None:
                return self._reply(404, {"error": "statusz endpoint disabled "
                                                  "(sdad --statusz)"})
            return self._reply(200, statusz())

        # server span: joins the caller's trace when the request carries a
        # W3C traceparent header, else roots a fresh trace. Everything the
        # handler does — admission verdicts, service calls, store ops,
        # snapshot phases — lands as descendants of this span.
        label = route_label(method, self._route_path)
        parent = obs.parse_traceparent(
            self.headers.get(obs.TRACEPARENT_HEADER))
        span_attributes = {"http.method": method, "http.route": label,
                           "request_id": self._request_id}
        node_id = getattr(self.server, "node_id", None)
        if node_id:
            # round timelines show which fleet worker served each hop
            span_attributes["node_id"] = node_id
        with obs.span(
            f"http.server {label}", parent=parent, kind="server",
            attributes=span_attributes,
        ) as server_span:
            self._span = server_span
            try:
                # admission control: shed BEFORE auth/crypto/store work. A
                # rejected request costs one header parse; Retry-After tells
                # the retrying transport exactly when the token bucket
                # refills.
                admission = getattr(self.server, "admission", None)
                if admission is not None and admission.enabled:
                    shed = admission.admit(self._agent_key(),
                                           tenant_key=self._tenant_key())
                    if shed is not None:
                        log.debug("%s %s -> %d shed (%s, retry in %.3fs)",
                                  method, path, shed.status, shed.reason,
                                  shed.retry_after)
                        self._shed = True
                        server_span.set_attribute("shed", shed.reason)
                        return self._reply(
                            shed.status,
                            {"error": f"throttled: {shed.reason}"},
                            retry_after=shed.retry_after,
                        )
                    try:
                        return self._dispatch(method, path, query)
                    finally:
                        admission.release()
                return self._dispatch(method, path, query)
            finally:
                if getattr(self.server, "trace_log", False):
                    trace_log.info(
                        "trace %s %s %s status=%s request_id=%s",
                        server_span.trace_id, method, self._route_path,
                        server_span.attributes.get("http.status"),
                        self._request_id,
                    )

    def _dispatch(self, method: str, path: str, query):
        def m(pattern):
            return re.fullmatch(pattern, path)

        # failpoint: transient transport trouble BEFORE any service work —
        # injected 500s, response delays, or hard connection drops. The
        # claimed agent id rides the ctx so a `partition` spec can sever
        # exactly one agent<->server pair (agent=<id>)
        action = chaos.evaluate(
            "http.server.request",
            ctx={"agent": self._agent_key()} if chaos.registry.active()
            else None)
        if action is not None:
            if action.kind == "error":
                return self._reply(500, {"error": str(action.exc)})
            if action.kind == "drop":
                log.info("%s %s -> chaos-dropped connection", self.command, self.path)
                self.close_connection = True
                return
            time.sleep(action.delay_s)  # "delay": proceed after the stall

        try:
            if method == "GET" and path == "/v1/ping":
                return self._reply(200, self.service.ping().to_obj())

            if method == "POST" and path == "/v1/agents/me":
                return self._create_agent()

            caller = self._authenticate()

            if r := m(rf"/v1/agents/({_ID})/profile"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_profile(caller, AgentId(r.group(1)))
                    )
            if method == "POST" and path == "/v1/agents/me/profile":
                profile = Profile.from_obj(self._json_body())
                self.service.upsert_profile(caller, profile)
                return self._reply(200)
            if r := m(rf"/v1/agents/any/keys/({_ID})"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_encryption_key(
                            caller, EncryptionKeyId(r.group(1))
                        )
                    )
            if method == "POST" and path == "/v1/agents/me/keys":
                key = signed_encryption_key_from_obj(self._json_body())
                self.service.create_encryption_key(caller, key)
                return self._reply(201)
            if r := m(rf"/v1/agents/({_ID})"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_agent(caller, AgentId(r.group(1)))
                    )

            if path == "/v1/aggregations" and method == "GET":
                title = query.get("title", [None])[0]
                recipient = query.get("recipient", [None])[0]
                ids = self.service.list_aggregations(
                    caller,
                    filter=title,
                    recipient=None if recipient is None else AgentId(recipient),
                )
                return self._reply(200, [str(i) for i in ids])
            if path == "/v1/aggregations" and method == "POST":
                agg = Aggregation.from_obj(self._json_body())
                self.service.create_aggregation(caller, agg)
                return self._reply(201)
            if r := m(rf"/v1/aggregations/({_ID})/committee/suggestions"):
                if method == "GET":
                    candidates = self.service.suggest_committee(
                        caller, AggregationId(r.group(1))
                    )
                    return self._reply(200, [c.to_obj() for c in candidates])
            if path == "/v1/aggregations/implied/committee" and method == "POST":
                committee = Committee.from_obj(self._json_body())
                self.service.create_committee(caller, committee)
                return self._reply(201)
            if r := m(rf"/v1/aggregations/({_ID})/committee"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_committee(caller, AggregationId(r.group(1)))
                    )
            if path == "/v1/aggregations/participations" and method == "POST":
                participation = self._hot_body(
                    bincodec.decode_participation, Participation.from_obj)
                self.service.create_participation(caller, participation)
                return self._reply(201)
            if r := m(rf"/v1/aggregations/({_ID})/status"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_aggregation_status(
                            caller, AggregationId(r.group(1))
                        )
                    )
            if r := m(rf"/v1/aggregations/({_ID})/round"):
                if method == "GET":
                    # round lifecycle state (server/lifecycle.py): what a
                    # blocking client polls instead of result_ready alone —
                    # terminal failed/expired states carry the diagnosis
                    return self._reply_option(
                        self.service.get_round_status(
                            caller, AggregationId(r.group(1))
                        )
                    )
            if path == "/v1/aggregations/implied/snapshot" and method == "POST":
                snap = Snapshot.from_obj(self._json_body())
                self.service.create_snapshot(caller, snap)
                return self._reply(201)
            if path == "/v1/aggregations/any/jobs" and method == "GET":
                job = self.service.get_clerking_job(caller, caller.id)
                headers = None
                if job is not None:
                    # hand the clerk the trace context the job was enqueued
                    # under: processing (even after a lease reissue) parents
                    # to the round that created the job, not the poll
                    link = obs.job_link(str(job.id))
                    if link is not None:
                        headers = {obs.TRACE_CONTEXT_HEADER:
                                   obs.format_traceparent(link)}
                if job is not None and self._accepts_bin():
                    # negotiated response codec: the job payload is the
                    # bulkiest download of a round (a whole clerk column)
                    metrics.count("http.codec.bin.out")
                    return self._reply(
                        200, raw=bincodec.encode_clerking_job(job),
                        content_type=bincodec.CONTENT_TYPE,
                        extra_headers=headers,
                    )
                return self._reply_option(job, extra_headers=headers)
            if r := m(rf"/v1/aggregations/implied/jobs/({_ID})/result"):
                if method == "POST":
                    result = self._hot_body(
                        bincodec.decode_clerking_result, ClerkingResult.from_obj)
                    if str(result.job) != r.group(1).lower():
                        raise InvalidRequest("result job id does not match route")
                    self.service.create_clerking_result(caller, result)
                    return self._reply(201)
            if r := m(rf"/v1/aggregations/({_ID})/snapshots/({_ID})/result"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_snapshot_result(
                            caller, AggregationId(r.group(1)), SnapshotId(r.group(2))
                        )
                    )
            if r := m(rf"/v1/aggregations/({_ID})"):
                if method == "GET":
                    return self._reply_option(
                        self.service.get_aggregation(caller, AggregationId(r.group(1)))
                    )
                if method == "DELETE":
                    self.service.delete_aggregation(caller, AggregationId(r.group(1)))
                    return self._reply(200)

            return self._reply(404, {"error": "no such route"})

        except InvalidCredentials as e:
            return self._reply(401, {"error": str(e)})
        except PermissionDenied as e:
            return self._reply(403, {"error": str(e)})
        except (InvalidRequest, ValueError, KeyError, TypeError) as e:
            return self._reply(400, {"error": f"{type(e).__name__}: {e}"})
        except NotFound as e:
            return self._reply(404, {"error": str(e)}, resource_not_found=True)
        except ParticipationConflict as e:
            # exactly-once ingestion rejected an equivocating upload: 409
            # is TERMINAL for the retrying transport (re-sending the same
            # conflicting bytes can never succeed), unlike the transient
            # 5xx/429 family. No stack trace — detection is the feature
            # working, and a buggy device would flood the log.
            return self._reply(409, {"error": str(e)})
        except StoreUnavailable as e:
            # breaker-open shed (server/breaker.py): the store was never
            # touched — 503 + Retry-After, same contract as admission
            # sheds, so the retrying transport backs off and resubmits.
            # No stack trace: an open breaker shedding is WORKING, and a
            # brownout would otherwise flood the log at request rate.
            metrics.count("http.store_unavailable")
            if self._span is not None:
                self._span.set_attribute("store_unavailable", True)
            return self._reply(503, {"error": str(e)},
                               retry_after=e.retry_after)
        except SdaError as e:
            log.exception("server error")
            return self._reply(500, {"error": str(e)})
        except Exception as e:  # don't kill the connection thread
            log.exception("unexpected server error")
            return self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def _create_agent(self):
        """Agent self-registration also records the presented token
        (lib.rs:192-201)."""
        creds = self._credentials()
        if creds is None:
            raise InvalidCredentials("agent creation requires Basic auth")
        agent_id, token = creds
        if not token:
            raise InvalidCredentials("empty token")
        agent = Agent.from_obj(self._json_body())
        if agent.id != agent_id:
            raise PermissionDenied("auth username must match agent id")
        # record-or-verify the token before the ACL'd create
        try:
            known = self.service.server.check_auth_token(auth_token(agent_id, token))
        except InvalidCredentials:
            if self.service.server.auth_tokens_store.get_auth_token(agent_id) is not None:
                raise  # token exists but differs: reject
            known = None
        if known is None:
            self.service.server.upsert_auth_token(auth_token(agent_id, token))
        self.service.create_agent(agent, agent)
        return self._reply(201)

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")


class SdaHttpServer:
    """Threaded HTTP server wrapping an SdaServerService.

    ``max_inflight`` / ``rate_limit`` / ``rate_burst`` arm the admission
    layer (both default off — zero overhead and bit-compatible behavior
    with the pre-admission server); ``metrics_endpoint`` enables the
    plaintext Prometheus exposition at ``GET /metrics`` (off by default:
    it reveals traffic shape, opt in via ``sdad --metrics``);
    ``statusz_endpoint`` enables the ``GET /statusz`` JSON debug page
    (uptime, store backend, in-flight/peak gauges, lease stats, devprof
    compile totals — same opt-in reasoning, ``sdad --statusz``);
    ``trace_log`` logs one INFO line per finished server span (trace id,
    route, status, request id — ``sdad --trace``);
    ``bin_codec=False`` turns the binary wire codec off (no advert, no
    ``application/x-sda-bin`` parsing) — the old-JSON-server posture the
    mixed-version tests pin.

    ``node_id`` names this worker in a fleet (``sda-fleet``,
    docs/scaling.md): it rides every response as ``X-SDA-Node``, labels
    ``/metrics`` samples and ``/statusz``, and lands on every server span
    so round timelines attribute hops to workers. ``fleet_peers`` records
    the fleet size as the ``fleet.peers`` gauge.
    """

    def __init__(
        self,
        service: SdaServerService,
        bind: str = "127.0.0.1:8888",
        *,
        max_inflight: Optional[int] = None,
        rate_limit: Optional[float] = None,
        rate_burst: float = 8.0,
        tenant_rate: Optional[float] = None,
        tenant_burst: float = 32.0,
        metrics_endpoint: bool = False,
        statusz_endpoint: bool = False,
        trace_log: bool = False,
        bin_codec: bool = True,
        node_id: Optional[str] = None,
        fleet_peers: Optional[int] = None,
    ):
        host, _, port = bind.partition(":")
        self.httpd = ThreadingHTTPServer((host, int(port or 8888)), _Handler)
        self.httpd.bin_codec = bin_codec  # type: ignore[attr-defined]
        self.httpd.sda_service = service  # type: ignore[attr-defined]
        self.httpd.status_counts = {}  # type: ignore[attr-defined]
        self.httpd.stats_lock = threading.Lock()  # type: ignore[attr-defined]
        self.httpd.active_requests = 0  # type: ignore[attr-defined]
        self.httpd.draining = False  # type: ignore[attr-defined]
        self.node_id = node_id
        self.fleet_peers = fleet_peers
        self.httpd.node_id = node_id  # type: ignore[attr-defined]
        service.server.node_id = node_id
        if fleet_peers is not None:
            metrics.gauge_set("fleet.peers", fleet_peers)
        self.admission = AdmissionControl(
            max_inflight=max_inflight, rate=rate_limit, burst=rate_burst,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
        )
        self.httpd.admission = self.admission  # type: ignore[attr-defined]
        self.httpd.metrics_enabled = metrics_endpoint  # type: ignore[attr-defined]
        self.httpd.statusz_fn = (  # type: ignore[attr-defined]
            self.statusz if statusz_endpoint else None)
        self.httpd.trace_log = trace_log  # type: ignore[attr-defined]
        self._started_at = time.time()
        self._thread: Optional[threading.Thread] = None

    def statusz(self) -> dict:
        """The ``GET /statusz`` payload: liveness + capacity + device-perf
        state in one scrape (served only when the endpoint is enabled —
        like ``/metrics`` it reveals traffic shape)."""
        from ..obs import devprof

        service = self.httpd.sda_service  # type: ignore[attr-defined]
        gauges = metrics.gauge_report("http.inflight")
        # unwrap a breaker proxy: the page names the BACKEND, not the wrap
        agents_store = getattr(service.server.agents_store, "_inner",
                               service.server.agents_store)
        return {
            "node_id": self.node_id,
            "fleet": {
                "peers": metrics.gauge_report("fleet.peers").get(
                    "fleet.peers", 1 if self.node_id else 0),
            },
            "uptime_s": round(time.time() - self._started_at, 3),
            # backend module name ("memory"/"sqlite"/"jsonfs"/"mongo")
            "store": type(agents_store).__module__.rsplit(".", 1)[-1],
            "inflight": gauges.get("http.inflight", 0),
            "inflight_peak": gauges.get("http.inflight.peak", 0),
            "admission_enabled": self.admission.enabled,
            # multi-tenant fairness verdicts (http/admission.py): which
            # tenants were admitted/shed against their own budgets —
            # present only when the per-tenant layer is armed
            "admission": (self.admission.tenants_report()
                          if self.admission.tenant_rate is not None
                          else None),
            "requests": self.status_counts,
            # which wire the peers actually spoke (fleet loadgen reads
            # the negotiated outcome from here — the counters live in
            # THIS process, not the driver's)
            "codec_counters": metrics.counter_report("http.codec.") or {},
            "lease": {
                "lease_seconds": service.server.clerking_lease_seconds,
                "counters": metrics.counter_report("server.job."),
            },
            # contended-idempotency visibility: how often this worker's
            # snapshot pipeline won, lost, or converged on a peer's freeze
            "snapshot": metrics.counter_report("server.snapshot.") or {},
            # exactly-once ingestion visibility: created vs byte-identical
            # replays vs rejected equivocations (fleet loadgen sums these
            # across scrapes — the counters live in THIS process)
            "participation": metrics.counter_report(
                "server.participation.") or {},
            # round lifecycle table (server/lifecycle.py): per-state and
            # per-tenant tallies + the most recently updated LIVE rounds
            # (terminal history only pads the remainder) — the fleet's
            # shared-store view, so any worker's scrape shows every round
            "rounds": _lifecycle.rounds_report(service.server),
            # recurring-round schedules (service/scheduler.py): every
            # installed schedule's tenant, current epoch and cadence —
            # also the shared-store view
            "schedules": _schedules_report(service.server),
            # live fleet health table (server/health.py): every worker's
            # heartbeat state and age, read from the shared store — any
            # worker's scrape shows the whole fleet
            "fleet_health": _health.fleet_health_report(
                service.server.clerking_job_store),
            # store circuit breaker (server/breaker.py): present only
            # when armed (sdad --store-breaker)
            "breaker": (service.server.store_breaker.report()
                        if getattr(service.server, "store_breaker", None)
                        is not None else None),
            # fleet drills arm failpoints per worker (sdad --chaos-spec);
            # the scrape proves the faults actually fired in THIS process
            "failpoints": chaos.report() or {},
            "devprof": devprof.compile_totals(),
            "hbm": metrics.gauge_report("device.hbm."),
        }

    def configure_admission(
        self,
        max_inflight: Optional[int] = None,
        rate_limit: Optional[float] = None,
        rate_burst: Optional[float] = None,
        tenant_rate: Optional[float] = None,
        tenant_burst: Optional[float] = None,
    ) -> None:
        """Retune (or disable, with all-``None``) admission at runtime —
        the loadgen driver arms overload profiles only after round setup."""
        self.admission.configure(
            max_inflight=max_inflight, rate=rate_limit, burst=rate_burst,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
        )

    @property
    def status_counts(self) -> dict:
        """Requests served, keyed by HTTP status (observability floor)."""
        with self.httpd.stats_lock:  # type: ignore[attr-defined]
            return dict(self.httpd.status_counts)  # type: ignore[attr-defined]

    @property
    def active_requests(self) -> int:
        """Requests currently being handled (idle keep-alive connections
        excluded — their threads are parked in readline, not working)."""
        with self.httpd.stats_lock:  # type: ignore[attr-defined]
            return self.httpd.active_requests  # type: ignore[attr-defined]

    def drain(self, grace_s: float = 10.0) -> dict:
        """Graceful shutdown (the fleet worker's SIGTERM path): stop
        accepting, let in-flight requests finish (bounded by ``grace_s``),
        hand every clerking-job lease this worker still holds back to the
        shared store so a fleet peer's next poll reissues the work
        immediately (no visibility-timeout wait), then close. Returns the
        drain summary — ``leaked`` must be 0 for a clean exit
        (docs/scaling.md)."""
        # reject-then-stop: established keep-alive connections can still
        # deliver new requests after the accept loop stops, so flip the
        # draining flag FIRST (handlers answer 503 + Connection: close
        # from here on), then stop the accept/serve loop and wait out the
        # requests that were already in flight
        self.httpd.draining = True  # type: ignore[attr-defined]
        self.httpd.shutdown()  # blocks until the serve loop exits
        deadline = time.monotonic() + grace_s
        while self.active_requests and time.monotonic() < deadline:
            time.sleep(0.02)
        stranded = self.active_requests
        service = self.httpd.sda_service  # type: ignore[attr-defined]
        released = service.server.release_held_leases()
        self.shutdown()  # joins the (already finished) serve-loop thread
        if stranded:
            # a handler still running past the grace window is an
            # abandoned request — the process exits right after and
            # kills its daemon thread mid-flight. That IS the leak the
            # fleet contract gates on.
            metrics.count("http.shutdown.leaked", stranded)
        summary = {
            "node_id": self.node_id,
            "released_leases": released,
            "stranded_requests": stranded,
            "leaked": stranded,
        }
        log.info("drained: %s", summary)
        return summary

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start_background(self) -> "SdaHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self.httpd.serve_forever()

    def shutdown(self):
        self.httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a wedged handler (stuck client socket, runaway store op)
                # survives shutdown(); don't hang the caller forever, but
                # don't hide the leak either
                log.warning(
                    "HTTP server thread did not stop within 5s; "
                    "leaking daemon thread %s", self._thread.name,
                )
                metrics.count("http.shutdown.leaked")
        self.httpd.server_close()
