"""REST client: an SdaService re-assembled over HTTP.

Reference: client-http/src/client.rs — the proxy implements the same service
interface the in-process server does, so SdaClient code is transport-blind.
The ``caller`` argument is carried by HTTP Basic auth: username = agent id,
password = a locally minted 32-char token persisted in the client store
(client-http/src/tokenstore.rs:8-23). A 404 bearing ``X-Resource-Not-Found``
maps to ``None``; a bare 404 is a routing error (client.rs:65-72).
"""

from __future__ import annotations

import logging
import os as _os
import random as _random
import re as _re
import time as _time
import secrets as _secrets
import threading as _threading
from typing import List, Optional

import requests

from .. import obs
from ..utils import metrics
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    Committee,
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    Participation,
    ParticipationConflict,
    PermissionDenied,
    Pong,
    RoundStatus,
    SdaService,
    ServerError,
    SnapshotResult,
    signed_encryption_key_from_obj,
)
from ..protocol import bincodec
from .admission import TENANT_HEADER
from ..utils.env import env_float as _env_float

TOKEN_ALIAS = "auth-token"

#: Wire codec modes: "json" pins the legacy JSON wire, "bin" forces the
#: binary codec from the first request (peer known to support it), "auto"
#: starts JSON and upgrades the hot routes once the server's
#: ``X-SDA-Codecs: bin`` advert is seen — old JSON-only servers therefore
#: keep speaking JSON transparently.
WIRE_CODECS = ("auto", "json", "bin")

log = logging.getLogger(__name__)

#: Statuses treated as transient server trouble — worth retrying.
RETRYABLE_STATUSES = frozenset({500, 502, 503, 504})

#: Admission-control shed (429 Too Many Requests): the server refused the
#: request BEFORE doing any work, so retrying is always safe regardless of
#: idempotence; the Retry-After hint says when.
THROTTLED_STATUS = 429

#: Resource ids inside request paths, collapsed to ``{id}`` in span names.
_PATH_ID_RE = _re.compile(r"[0-9a-fA-F-]{36}")


def _retry_after_seconds(response) -> Optional[float]:
    """Parse a ``Retry-After`` header: delta-seconds (our server emits
    fractional seconds) or an HTTP-date. ``None`` when absent/garbled."""
    raw = response.headers.get("Retry-After")
    if raw is None:
        return None
    raw = raw.strip()
    try:
        return max(0.0, float(raw))
    except ValueError:
        pass
    try:
        from email.utils import parsedate_to_datetime
        import datetime as _dt

        when = parsedate_to_datetime(raw)
        now = _dt.datetime.now(when.tzinfo or _dt.timezone.utc)
        return max(0.0, (when - now).total_seconds())
    except (TypeError, ValueError):
        log.debug("ignoring unparseable Retry-After=%r", raw)
        return None

#: Every mutating route this client issues. All are POSTs whose server-side
#: handlers are create-once / idempotent upserts keyed by a client-minted id
#: (participations dedupe by participation id, results by (snapshot, job),
#: snapshots by snapshot id with deterministic job ids, everything else is a
#: plain upsert), so a retried POST after a lost response cannot duplicate a
#: side effect. ``_post`` asserts membership: adding a non-idempotent route
#: without reclassifying it here must fail loudly, not silently retry.
_IDEMPOTENT_POST_ROUTES = tuple(
    _re.compile(p)
    for p in (
        r"/v1/agents/me",
        r"/v1/agents/me/profile",
        r"/v1/agents/me/keys",
        r"/v1/aggregations",
        r"/v1/aggregations/implied/committee",
        r"/v1/aggregations/implied/snapshot",
        r"/v1/aggregations/participations",
        r"/v1/aggregations/implied/jobs/[0-9a-fA-F-]{36}/result",
    )
)


def _load_or_mint_token(store, agent_id: AgentId) -> str:
    """Persisted per-identity token, minted on first use (tokenstore.rs:8-23)."""
    record = store.get(f"token-{agent_id}")
    if record is not None:
        return record["token"]
    token = _secrets.token_urlsafe(24)[:32]
    store.put(f"token-{agent_id}", {"token": token})
    return token


class SdaHttpClient(SdaService):
    """REST proxy implementing the full SdaService seam.

    Thread-safe: one proxy can serve many agents from many threads (the
    in-process tests drive concurrent clerks through one instance).
    ``requests.Session`` connection reuse is NOT safe across threads —
    interleaved request/response framing deadlocks both ends — so each
    thread gets its own session; the token cache is lock-guarded.
    """

    def __init__(
        self,
        base_url: str,
        store=None,
        token: Optional[str] = None,
        *,
        timeout: Optional[float] = None,
        max_retries: Optional[int] = None,
        backoff_base: Optional[float] = None,
        backoff_cap: Optional[float] = None,
        deadline: Optional[float] = None,
        codec: Optional[str] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.store = store
        self._fixed_token = token
        #: wire codec mode; constructor beats SDA_WIRE_CODEC beats "auto"
        self.codec = (codec if codec is not None
                      else _os.environ.get("SDA_WIRE_CODEC") or "auto")
        if self.codec not in WIRE_CODECS:
            raise ValueError(f"unknown wire codec {self.codec!r} "
                             f"(expected one of {WIRE_CODECS})")
        #: set once any response carries the server's bin-codec advert
        self._peer_bin = False
        #: cleared once a long-poll gets the old-server bare 404 — the
        #: proxy then degrades to immediate-return polling permanently
        self._peer_longpoll = True
        #: multi-tenant fairness (http/admission.py): when set to the
        #: recipient id this proxy's traffic belongs to, every request
        #: carries it as X-SDA-Tenant so the server's per-tenant budget
        #: bucket sees it — a device swarm that names its tenant sheds
        #: against that tenant's own budget, not the fleet's
        self.tenant: Optional[str] = None
        #: per-request socket timeout; constructor beats SDA_HTTP_TIMEOUT
        #: beats the historical 60 s default
        self.timeout = (
            timeout if timeout is not None else _env_float("SDA_HTTP_TIMEOUT", 60.0)
        )
        #: transient failures absorbed per operation before giving up
        self.max_retries = int(
            max_retries if max_retries is not None
            else _env_float("SDA_HTTP_RETRIES", 4)
        )
        # exponential backoff with full jitter: sleep in
        # [0, min(cap, base * 2^attempt)] — decorrelates retry storms from
        # many sporadic clients hitting one recovering server
        self.backoff_base = (
            backoff_base if backoff_base is not None
            else _env_float("SDA_HTTP_BACKOFF", 0.1)
        )
        self.backoff_cap = backoff_cap if backoff_cap is not None else 5.0
        #: per-operation wall-clock budget across all attempts (sleeps
        #: included); None derives it from timeout and retry count
        self.deadline = (
            deadline if deadline is not None
            else _env_float(
                "SDA_HTTP_DEADLINE",
                (self.timeout + self.backoff_cap) * (self.max_retries + 1),
            )
        )
        self._tokens = {}  # per-caller cache; one proxy can serve many agents
        self._tokens_lock = _threading.Lock()
        self._local = _threading.local()
        self._sessions = []  # every created session, for close()

    @property
    def session(self) -> requests.Session:
        s = getattr(self._local, "session", None)
        if s is None:
            s = self._local.session = requests.Session()
            with self._tokens_lock:
                self._sessions.append(s)
        return s

    def close(self) -> None:
        """Release pooled keep-alive sockets of every thread's session."""
        with self._tokens_lock:
            sessions, self._sessions = self._sessions, []
        for s in sessions:
            s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _auth(self, caller: Agent):
        if self._fixed_token is not None:
            return (str(caller.id), self._fixed_token)
        with self._tokens_lock:
            token = self._tokens.get(caller.id)
            if token is None:
                if self.store is None:
                    raise InvalidCredentials("no token store configured")
                token = _load_or_mint_token(self.store, caller.id)
                self._tokens[caller.id] = token
        return (str(caller.id), token)

    def _check(self, response: requests.Response):
        if response.status_code in (200, 201):
            return response
        if response.status_code == 404:
            if response.headers.get("X-Resource-Not-Found"):
                return None
            raise NotFound(f"no such route: {response.url}")
        body = response.text[:200]
        if response.status_code == 401:
            raise InvalidCredentials(body)
        if response.status_code == 403:
            raise PermissionDenied(body)
        if response.status_code == 400:
            raise InvalidRequest(body)
        if response.status_code == 409:
            # exactly-once ingestion refused the upload: TERMINAL by
            # construction (the server already holds a different bundle
            # under this key — replaying the same bytes can never turn a
            # conflict into a success), so it is deliberately not in
            # RETRYABLE_STATUSES and surfaces typed after ONE attempt
            metrics.count("http.participation.conflict")
            raise ParticipationConflict(body)
        error = ServerError(f"HTTP {response.status_code}: {body}")
        # a terminal 5xx/429 that exhausted the transport's own retries
        # may still carry the server's Retry-After (breaker-open and
        # admission sheds do): stamp it so HIGHER-level pollers —
        # await_result's round-status loop — back off on the server's
        # schedule instead of their fixed cadence
        error.retry_after = _retry_after_seconds(response)
        raise error

    def _use_bin(self) -> bool:
        """Whether the hot routes should speak binary right now."""
        return self.codec == "bin" or (self.codec == "auto" and self._peer_bin)

    def _request(self, method: str, path: str, *, params=None, json=None,
                 data=None, headers=None, auth=None, stream=False,
                 timeout_s=None):
        """One logical operation: exponential-backoff retries around the
        raw HTTP exchange, bounded by ``max_retries`` AND the
        per-operation ``deadline``. Connection errors, timeouts, 5xx
        responses, and 429 admission sheds are transient (a server
        ``Retry-After`` hint overrides the jittered backoff, still capped
        at the deadline); everything else returns immediately for
        ``_check`` to interpret.

        Tracing: the whole operation is one client span; every attempt is
        a child span tagged with its attempt number, status/error cause,
        and any ``Retry-After`` hint, and the attempt span's context rides
        the W3C ``traceparent`` header so server-side handling joins this
        trace."""
        url = self.base_url + path
        give_up_at = _time.monotonic() + self.deadline
        attempt = 0
        # span NAMES collapse resource ids (bounded cardinality, mirrors the
        # server's route_label); the raw path rides the http.target attribute
        with obs.span(
            f"http.client {method} {_PATH_ID_RE.sub('{id}', path)}",
            kind="client",
            attributes={"http.method": method, "http.target": path},
        ) as op_span:
            while True:
                cause, error, retry_after = None, None, None
                # the deadline is a wall-clock budget: each attempt's socket
                # timeout is clamped to what remains (floored so the first
                # attempt always gets a chance even under a tiny deadline)
                remaining = give_up_at - _time.monotonic()
                with obs.span(
                    "http.attempt", kind="client",
                    attributes={"attempt": attempt},
                ) as att_span:
                    send_headers = dict(headers or {})
                    send_headers[obs.TRACEPARENT_HEADER] = (
                        obs.format_traceparent(att_span.context))
                    if self.tenant:
                        send_headers[TENANT_HEADER] = str(self.tenant)
                    try:
                        response = self.session.request(
                            method, url, params=params, json=json, data=data,
                            auth=auth, headers=send_headers, stream=stream,
                            # timeout_s widens the socket timeout for ops
                            # that legitimately idle server-side (a parked
                            # long-poll); the op deadline still caps it
                            timeout=min(timeout_s or self.timeout,
                                        max(0.05, remaining)),
                        )
                    except requests.Timeout as e:
                        cause, error = "timeout", e
                    except requests.ConnectionError as e:
                        cause, error = "connection", e
                    else:
                        if not self._peer_bin and "bin" in response.headers.get(
                                bincodec.CODECS_HEADER, ""):
                            # codec advert: every later hot-route request
                            # from this proxy may upgrade to binary
                            self._peer_bin = True
                        att_span.set_attribute(
                            "http.status", response.status_code)
                        request_id = response.headers.get(
                            obs.REQUEST_ID_HEADER)
                        if request_id:
                            att_span.set_attribute("request_id", request_id)
                        if response.status_code == THROTTLED_STATUS:
                            # admission shed: nothing executed server-side
                            cause = "status_429"
                            retry_after = _retry_after_seconds(response)
                        elif response.status_code in RETRYABLE_STATUSES:
                            cause = "status_5xx"
                            retry_after = _retry_after_seconds(response)
                        else:
                            if attempt:
                                metrics.count("http.retry.recovered")
                                op_span.set_attribute("retries", attempt)
                            if stream:
                                # one bulk read instead of requests' 10 KB
                                # chunk loop — matters at multi-MB clerk-job
                                # payloads; ``.content`` then serves callers
                                # from this buffer
                                response._content = response.raw.read(
                                    decode_content=True)
                                response._content_consumed = True
                            return response
                    if error is not None:
                        att_span.set_attribute("error", cause)
                    elif stream:
                        # unread streamed body of a retryable response:
                        # drop the connection rather than poison keep-alive
                        response.close()
                    if retry_after is not None:
                        att_span.set_attribute("retry_after_s", retry_after)
                attempt += 1
                if attempt > self.max_retries or _time.monotonic() >= give_up_at:
                    metrics.count("http.retry.exhausted")
                    op_span.set_attribute("retries", attempt)
                    op_span.set_attribute("exhausted", True)
                    if error is not None:
                        raise error
                    return response  # terminal 5xx: let _check raise ServerError
                metrics.count("http.retry.attempt")
                metrics.count(f"http.retry.{cause}")
                jitter = _random.uniform(
                    0.0,
                    min(self.backoff_cap, self.backoff_base * (2 ** (attempt - 1))),
                )
                if retry_after is not None:
                    # the server told us when to come back: honor the hint,
                    # PLUS the growing jitter — early retries follow the hint
                    # closely (fast token-bucket convergence), persistent
                    # shedding still decays into exponential backoff instead
                    # of a cohort hammering at a constant hinted rate
                    metrics.count("http.retry.after_hint")
                    sleep = retry_after + jitter
                else:
                    sleep = jitter
                sleep = min(sleep, max(0.0, give_up_at - _time.monotonic()))
                log.debug(
                    "%s %s transient failure (%s), retry %d/%d in %.3fs",
                    method, path, cause, attempt, self.max_retries, sleep,
                )
                if sleep:
                    _time.sleep(sleep)

    def _get(self, caller: Agent, path: str, params=None):
        return self._check(
            self._request("GET", path, params=params, auth=self._auth(caller))
        )

    def _post(self, caller: Agent, path: str, obj, resource=None):
        # POSTs are only retry-safe because every mutating route is a
        # create-once/idempotent upsert server-side — enforce the claim
        # (explicit raise, not `assert`: must survive python -O)
        if not any(r.fullmatch(path) for r in _IDEMPOTENT_POST_ROUTES):
            raise AssertionError(
                f"POST {path} is not classified retry-safe; add it to "
                "_IDEMPOTENT_POST_ROUTES only if its handler is idempotent"
            )
        if resource is not None and self._use_bin():
            # negotiated hot-route body: one binary frame instead of
            # base64-inside-JSON; the raw bytes re-send identically on
            # retries, so retry semantics are unchanged
            return self._check(self._request(
                "POST", path, data=bincodec.encode(resource),
                headers={"Content-Type": bincodec.CONTENT_TYPE},
                auth=self._auth(caller),
            ))
        # ``obj`` may be a thunk so hot callers skip building the (large)
        # JSON tree when the binary path was taken
        return self._check(
            self._request("POST", path, json=obj() if callable(obj) else obj,
                          auth=self._auth(caller))
        )

    def _delete(self, caller: Agent, path: str) -> None:
        self._check(self._request("DELETE", path, auth=self._auth(caller)))

    @staticmethod
    def _option(response, codec):
        return None if response is None else codec(response.json())

    # -- service implementation --------------------------------------------
    def ping(self) -> Pong:
        response = self._request("GET", "/v1/ping")
        self._check(response)
        return Pong.from_obj(response.json())

    def create_agent(self, caller, agent):
        self._post(caller, "/v1/agents/me", agent.to_obj())

    def get_agent(self, caller, agent):
        return self._option(
            self._get(caller, f"/v1/agents/{agent}"), Agent.from_obj
        )

    def upsert_profile(self, caller, profile):
        self._post(caller, "/v1/agents/me/profile", profile.to_obj())

    def get_profile(self, caller, owner):
        from ..protocol import Profile

        return self._option(
            self._get(caller, f"/v1/agents/{owner}/profile"), Profile.from_obj
        )

    def create_encryption_key(self, caller, key):
        self._post(caller, "/v1/agents/me/keys", key.to_obj())

    def get_encryption_key(self, caller, key):
        return self._option(
            self._get(caller, f"/v1/agents/any/keys/{key}"),
            signed_encryption_key_from_obj,
        )

    def list_aggregations(self, caller, filter=None, recipient=None) -> List[AggregationId]:
        params = {}
        if filter is not None:
            params["title"] = filter
        if recipient is not None:
            params["recipient"] = str(recipient)
        response = self._get(caller, "/v1/aggregations", params=params)
        return [AggregationId(i) for i in response.json()]

    def get_aggregation(self, caller, aggregation):
        return self._option(
            self._get(caller, f"/v1/aggregations/{aggregation}"), Aggregation.from_obj
        )

    def get_committee(self, caller, aggregation):
        return self._option(
            self._get(caller, f"/v1/aggregations/{aggregation}/committee"),
            Committee.from_obj,
        )

    def create_aggregation(self, caller, aggregation):
        self._post(caller, "/v1/aggregations", aggregation.to_obj())

    def delete_aggregation(self, caller, aggregation):
        self._delete(caller, f"/v1/aggregations/{aggregation}")

    def suggest_committee(self, caller, aggregation):
        response = self._get(
            caller, f"/v1/aggregations/{aggregation}/committee/suggestions"
        )
        if response is None:
            raise NotFound("no aggregation found")
        return [ClerkCandidate.from_obj(c) for c in response.json()]

    def create_committee(self, caller, committee):
        self._post(caller, "/v1/aggregations/implied/committee", committee.to_obj())

    def get_aggregation_status(self, caller, aggregation):
        return self._option(
            self._get(caller, f"/v1/aggregations/{aggregation}/status"),
            AggregationStatus.from_obj,
        )

    def get_round_status(self, caller, aggregation):
        try:
            response = self._get(
                caller, f"/v1/aggregations/{aggregation}/round")
        except NotFound:
            # bare 404 (no X-Resource-Not-Found): an old server without
            # the round-lifecycle route — report "not tracked", exactly
            # like the in-process default, so await_result degrades to
            # plain result_ready polling against pre-supervisor peers
            return None
        return self._option(response, RoundStatus.from_obj)

    def create_snapshot(self, caller, snapshot):
        self._post(caller, "/v1/aggregations/implied/snapshot", snapshot.to_obj())

    def get_snapshot_result(self, caller, aggregation, snapshot):
        return self._option(
            self._get(
                caller, f"/v1/aggregations/{aggregation}/snapshots/{snapshot}/result"
            ),
            SnapshotResult.from_obj,
        )

    def create_participation(self, caller, participation):
        # tree-relay participations (forwarded leaf-mask ciphertexts in
        # band) ride the JSON wire: the v1 binary frame has no slot for
        # them and bincodec.encode_participation refuses to drop them
        resource = (None if participation.forwarded_masks is not None
                    else participation)
        if self._post(caller, "/v1/aggregations/participations",
                      participation.to_obj, resource=resource) is None:
            # X-Resource-Not-Found 404: the aggregation is gone. The
            # in-process seam raises here, and resume() relies on the
            # distinction to reap orphaned journal entries instead of
            # miscounting them as resumed — mirror it.
            raise NotFound(
                f"unknown aggregation {participation.aggregation}")

    def _job_headers(self):
        if self.codec == "json":
            return None
        # offer the binary codec for the bulkiest download of a round;
        # an old server ignores the Accept header and answers JSON
        return {"Accept": f"{bincodec.CONTENT_TYPE}, application/json"}

    def _decode_job(self, response):
        """Shared decode of a clerking-job response (immediate poll and
        long-poll): negotiated codec + the X-Trace-Context job link."""
        if response is None:
            return None
        ctype = (response.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == bincodec.CONTENT_TYPE:
            job = bincodec.decode_clerking_job(response.content)
        else:
            job = ClerkingJob.from_obj(response.json())
        # the server hands back the trace context the job was enqueued
        # under (X-Trace-Context); mirror it locally so processing — even
        # of a lease-REISSUED job — parents to the original round trace
        ctx = obs.parse_traceparent(
            response.headers.get(obs.TRACE_CONTEXT_HEADER))
        if ctx is not None:
            obs.link_job(str(job.id), ctx)
        return job

    def get_clerking_job(self, caller, clerk):
        return self._decode_job(self._check(self._request(
            "GET", "/v1/aggregations/any/jobs", headers=self._job_headers(),
            auth=self._auth(caller), stream=True,
        )))

    def longpoll_supported(self) -> bool:
        """Whether this peer still takes parked long-polls — False once
        a bare 404 revealed an old server, at which point callers like
        ``run_clerk`` must supply their own polling cadence (the
        immediate-return fallback no longer sleeps server-side)."""
        return bool(getattr(self, "_peer_longpoll", True))

    def await_clerking_job(self, caller, clerk, wait_s: float = 0.0):
        """Long-poll job delivery (``GET /v1/clerking-jobs?wait=S``,
        docs/http.md): the server parks the request until a job exists
        for this clerk, the wait expires (empty answer -> None), or the
        worker drains (503 -> the retrying transport re-issues against a
        live peer). Old servers without the route answer a bare 404: we
        remember that (``http.longpoll.unsupported``) and fall back to
        the immediate-return poll transparently, so mixed-version fleets
        keep working. The socket timeout is widened past ``wait_s`` so a
        healthy parked request is never reaped client-side."""
        if not self.longpoll_supported():
            return self.get_clerking_job(caller, clerk)
        wait_s = max(0.0, float(wait_s))
        try:
            response = self._check(self._request(
                "GET", "/v1/clerking-jobs", params={"wait": f"{wait_s:.3f}"},
                headers=self._job_headers(), auth=self._auth(caller),
                stream=True, timeout_s=wait_s + max(5.0, self.timeout),
            ))
        except NotFound:
            # bare 404 (no X-Resource-Not-Found): an old server without
            # the long-poll route — degrade to the classic poll for the
            # rest of this proxy's life
            self._peer_longpoll = False
            metrics.count("http.longpoll.unsupported")
            return self.get_clerking_job(caller, clerk)
        return self._decode_job(response)

    def create_clerking_result(self, caller, result):
        self._post(
            caller, f"/v1/aggregations/implied/jobs/{result.job}/result",
            result.to_obj, resource=result,
        )
