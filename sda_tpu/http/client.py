"""REST client: an SdaService re-assembled over HTTP.

Reference: client-http/src/client.rs — the proxy implements the same service
interface the in-process server does, so SdaClient code is transport-blind.
The ``caller`` argument is carried by HTTP Basic auth: username = agent id,
password = a locally minted 32-char token persisted in the client store
(client-http/src/tokenstore.rs:8-23). A 404 bearing ``X-Resource-Not-Found``
maps to ``None``; a bare 404 is a routing error (client.rs:65-72).
"""

from __future__ import annotations

import secrets as _secrets
import threading as _threading
from typing import List, Optional

import requests

from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    Committee,
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    Participation,
    PermissionDenied,
    Pong,
    SdaService,
    ServerError,
    SnapshotResult,
    signed_encryption_key_from_obj,
)

TOKEN_ALIAS = "auth-token"


def _load_or_mint_token(store, agent_id: AgentId) -> str:
    """Persisted per-identity token, minted on first use (tokenstore.rs:8-23)."""
    record = store.get(f"token-{agent_id}")
    if record is not None:
        return record["token"]
    token = _secrets.token_urlsafe(24)[:32]
    store.put(f"token-{agent_id}", {"token": token})
    return token


class SdaHttpClient(SdaService):
    """REST proxy implementing the full SdaService seam.

    Thread-safe: one proxy can serve many agents from many threads (the
    in-process tests drive concurrent clerks through one instance).
    ``requests.Session`` connection reuse is NOT safe across threads —
    interleaved request/response framing deadlocks both ends — so each
    thread gets its own session; the token cache is lock-guarded.
    """

    def __init__(self, base_url: str, store=None, token: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.store = store
        self._fixed_token = token
        self._tokens = {}  # per-caller cache; one proxy can serve many agents
        self._tokens_lock = _threading.Lock()
        self._local = _threading.local()
        self._sessions = []  # every created session, for close()

    @property
    def session(self) -> requests.Session:
        s = getattr(self._local, "session", None)
        if s is None:
            s = self._local.session = requests.Session()
            with self._tokens_lock:
                self._sessions.append(s)
        return s

    def close(self) -> None:
        """Release pooled keep-alive sockets of every thread's session."""
        with self._tokens_lock:
            sessions, self._sessions = self._sessions, []
        for s in sessions:
            s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _auth(self, caller: Agent):
        if self._fixed_token is not None:
            return (str(caller.id), self._fixed_token)
        with self._tokens_lock:
            token = self._tokens.get(caller.id)
            if token is None:
                if self.store is None:
                    raise InvalidCredentials("no token store configured")
                token = _load_or_mint_token(self.store, caller.id)
                self._tokens[caller.id] = token
        return (str(caller.id), token)

    def _check(self, response: requests.Response):
        if response.status_code in (200, 201):
            return response
        if response.status_code == 404:
            if response.headers.get("X-Resource-Not-Found"):
                return None
            raise NotFound(f"no such route: {response.url}")
        body = response.text[:200]
        if response.status_code == 401:
            raise InvalidCredentials(body)
        if response.status_code == 403:
            raise PermissionDenied(body)
        if response.status_code == 400:
            raise InvalidRequest(body)
        raise ServerError(f"HTTP {response.status_code}: {body}")

    def _get(self, caller: Agent, path: str, params=None):
        return self._check(
            self.session.get(
                self.base_url + path, params=params, auth=self._auth(caller), timeout=60
            )
        )

    def _post(self, caller: Agent, path: str, obj) -> None:
        self._check(
            self.session.post(
                self.base_url + path, json=obj, auth=self._auth(caller), timeout=60
            )
        )

    def _delete(self, caller: Agent, path: str) -> None:
        self._check(
            self.session.delete(self.base_url + path, auth=self._auth(caller), timeout=60)
        )

    @staticmethod
    def _option(response, codec):
        return None if response is None else codec(response.json())

    # -- service implementation --------------------------------------------
    def ping(self) -> Pong:
        response = self.session.get(self.base_url + "/v1/ping", timeout=60)
        self._check(response)
        return Pong.from_obj(response.json())

    def create_agent(self, caller, agent):
        self._post(caller, "/v1/agents/me", agent.to_obj())

    def get_agent(self, caller, agent):
        return self._option(
            self._get(caller, f"/v1/agents/{agent}"), Agent.from_obj
        )

    def upsert_profile(self, caller, profile):
        self._post(caller, "/v1/agents/me/profile", profile.to_obj())

    def get_profile(self, caller, owner):
        from ..protocol import Profile

        return self._option(
            self._get(caller, f"/v1/agents/{owner}/profile"), Profile.from_obj
        )

    def create_encryption_key(self, caller, key):
        self._post(caller, "/v1/agents/me/keys", key.to_obj())

    def get_encryption_key(self, caller, key):
        return self._option(
            self._get(caller, f"/v1/agents/any/keys/{key}"),
            signed_encryption_key_from_obj,
        )

    def list_aggregations(self, caller, filter=None, recipient=None) -> List[AggregationId]:
        params = {}
        if filter is not None:
            params["title"] = filter
        if recipient is not None:
            params["recipient"] = str(recipient)
        response = self._get(caller, "/v1/aggregations", params=params)
        return [AggregationId(i) for i in response.json()]

    def get_aggregation(self, caller, aggregation):
        return self._option(
            self._get(caller, f"/v1/aggregations/{aggregation}"), Aggregation.from_obj
        )

    def get_committee(self, caller, aggregation):
        return self._option(
            self._get(caller, f"/v1/aggregations/{aggregation}/committee"),
            Committee.from_obj,
        )

    def create_aggregation(self, caller, aggregation):
        self._post(caller, "/v1/aggregations", aggregation.to_obj())

    def delete_aggregation(self, caller, aggregation):
        self._delete(caller, f"/v1/aggregations/{aggregation}")

    def suggest_committee(self, caller, aggregation):
        response = self._get(
            caller, f"/v1/aggregations/{aggregation}/committee/suggestions"
        )
        if response is None:
            raise NotFound("no aggregation found")
        return [ClerkCandidate.from_obj(c) for c in response.json()]

    def create_committee(self, caller, committee):
        self._post(caller, "/v1/aggregations/implied/committee", committee.to_obj())

    def get_aggregation_status(self, caller, aggregation):
        return self._option(
            self._get(caller, f"/v1/aggregations/{aggregation}/status"),
            AggregationStatus.from_obj,
        )

    def create_snapshot(self, caller, snapshot):
        self._post(caller, "/v1/aggregations/implied/snapshot", snapshot.to_obj())

    def get_snapshot_result(self, caller, aggregation, snapshot):
        return self._option(
            self._get(
                caller, f"/v1/aggregations/{aggregation}/snapshots/{snapshot}/result"
            ),
            SnapshotResult.from_obj,
        )

    def create_participation(self, caller, participation):
        self._post(caller, "/v1/aggregations/participations", participation.to_obj())

    def get_clerking_job(self, caller, clerk):
        return self._option(
            self._get(caller, "/v1/aggregations/any/jobs"), ClerkingJob.from_obj
        )

    def create_clerking_result(self, caller, result):
        self._post(
            caller, f"/v1/aggregations/implied/jobs/{result.job}/result", result.to_obj()
        )
