"""SQLite store backend — the production-database store.

The reference ships a production MongoDB backend next to its JSON-file one
(server-store-mongodb/src/lib.rs:86-151); this is the same tier for sda-tpu,
built on the stdlib ``sqlite3`` so it needs no external service. Design
follows the Mongo store's shape, not the file store's:

- one document table per resource, JSON text keyed by id, upserts via
  ``INSERT .. ON CONFLICT`` (the Mongo store's ``modisert`` helper,
  lib.rs:118-151);
- snapshotting marks frozen participations in a join table — the analog of
  ``$addToSet``-ing the snapshot id onto participation docs
  (server-store-mongodb/src/aggregations.rs:132-142);
- the clerk-job queue is a done-flag column, result creation flips it in the
  same transaction (clerking_jobs.rs:32-75 done-flag queue);
- snapshot reads fetch frozen participations with one SQL join
  (``iter_snapped_participations``); the per-clerk transpose itself uses the
  shared default from ``stores.py`` (the Mongo store instead pushes it into a
  $match→$unwind→$group pipeline, aggregations.rs:164-195).

All four stores share one database handle (single writer, WAL) so a whole
server lives in one ``.db`` file — durable-by-construction like every other
backend (SURVEY.md §5.4).
"""

from __future__ import annotations

import contextlib
import json
import os
import sqlite3
import threading
import time
from typing import List

from .. import chaos
from ..utils import metrics
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    Encryption,
    EncryptionKeyId,
    NotFound,
    Participation,
    ParticipationConflict,
    Profile,
    Snapshot,
    SnapshotId,
    signed_encryption_key_from_obj,
)
from .stores import (
    AgentsStore,
    AggregationsStore,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
    auth_token,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS auth_tokens (
    id TEXT PRIMARY KEY, body TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS agents (
    id TEXT PRIMARY KEY, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS profiles (
    owner TEXT PRIMARY KEY, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS enc_keys (
    id TEXT PRIMARY KEY, signer TEXT NOT NULL, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS aggregations (
    id TEXT PRIMARY KEY, title TEXT NOT NULL, recipient TEXT NOT NULL,
    doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS committees (
    aggregation TEXT PRIMARY KEY, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS participations (
    id TEXT NOT NULL, aggregation TEXT NOT NULL,
    participant TEXT NOT NULL DEFAULT '',
    digest TEXT NOT NULL DEFAULT '',
    doc TEXT NOT NULL,
    PRIMARY KEY (aggregation, id));
CREATE INDEX IF NOT EXISTS ix_parts_agent
    ON participations (aggregation, participant);
CREATE TABLE IF NOT EXISTS snapshots (
    id TEXT NOT NULL, aggregation TEXT NOT NULL, doc TEXT NOT NULL,
    PRIMARY KEY (aggregation, id));
CREATE TABLE IF NOT EXISTS snapshot_parts (
    snapshot TEXT NOT NULL, participation TEXT NOT NULL,
    PRIMARY KEY (snapshot, participation));
CREATE TABLE IF NOT EXISTS snapshot_freezes (
    snapshot TEXT PRIMARY KEY);
CREATE TABLE IF NOT EXISTS snapshot_masks (
    snapshot TEXT PRIMARY KEY, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS snapshot_mask_chunks (
    snapshot TEXT NOT NULL, chunk_ix INTEGER NOT NULL, doc TEXT NOT NULL,
    PRIMARY KEY (snapshot, chunk_ix));
CREATE TABLE IF NOT EXISTS clerking_jobs (
    id TEXT NOT NULL, clerk TEXT NOT NULL, snapshot TEXT NOT NULL,
    done INTEGER NOT NULL DEFAULT 0, leased_until REAL NOT NULL DEFAULT 0,
    leased_by TEXT NOT NULL DEFAULT '',
    doc TEXT NOT NULL,
    PRIMARY KEY (clerk, id));
CREATE INDEX IF NOT EXISTS ix_jobs_queue ON clerking_jobs (clerk, done, id);
CREATE TABLE IF NOT EXISTS clerking_results (
    job TEXT NOT NULL, snapshot TEXT NOT NULL, doc TEXT NOT NULL,
    PRIMARY KEY (snapshot, job));
CREATE TABLE IF NOT EXISTS rounds (
    aggregation TEXT PRIMARY KEY, state TEXT NOT NULL, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS schedules (
    schedule TEXT PRIMARY KEY, epoch INTEGER NOT NULL, doc TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS worker_heartbeats (
    node TEXT PRIMARY KEY, state TEXT NOT NULL, doc TEXT NOT NULL);
"""


class SqliteDb:
    """Shared per-process handle; ``":memory:"`` works for tests.

    One database file can be shared by SEVERAL OS processes (the fleet
    plane, ``sda_tpu/server/fleet.py``): WAL lets readers proceed under a
    writer, ``busy_timeout`` makes competing writers queue instead of
    throwing ``database is locked``, and every multi-statement write runs
    inside an explicit ``BEGIN IMMEDIATE`` transaction so it takes the
    write lock up front — no deferred-transaction upgrade deadlocks
    between two processes mid-write. Within one process the ``lock``
    RLock serializes threads over the single connection.
    """

    def __init__(self, path, busy_timeout_s: float = None):
        self.path = str(path)
        self.lock = threading.RLock()
        if busy_timeout_s is None:
            busy_timeout_s = float(os.environ.get("SDA_SQLITE_BUSY_MS", 10000)) / 1e3
        # isolation_level=None = autocommit: single statements commit
        # themselves; transactions are explicit BEGIN IMMEDIATE via
        # immediate() (python's implicit deferred transactions would
        # upgrade read->write locks mid-transaction, the classic
        # two-process SQLITE_BUSY deadlock)
        self.conn = sqlite3.connect(
            self.path, check_same_thread=False, isolation_level=None
        )
        with self.lock:
            self.conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_s * 1e3)}")
            if self.path != ":memory:":
                # the rollback->WAL transition needs an exclusive lock and
                # does NOT always consult the busy handler (it returns
                # SQLITE_BUSY straight away mid-transition) — N fleet
                # workers opening one fresh database file race exactly
                # that, so retry by hand under the same time budget
                deadline = time.monotonic() + busy_timeout_s
                while True:
                    try:
                        self.conn.execute("PRAGMA journal_mode=WAL")
                        break
                    except sqlite3.OperationalError as e:
                        if "locked" not in str(e) \
                                or time.monotonic() >= deadline:
                            raise
                        time.sleep(0.05)
                # WAL's standard durability pairing: fsync on checkpoint,
                # not on every commit — the cross-process write path is
                # hot (every participation is one commit)
                self.conn.execute("PRAGMA synchronous=NORMAL")
            self.conn.executescript(_SCHEMA)
            # migrate pre-lease databases: CREATE IF NOT EXISTS won't add
            # the column to an existing clerking_jobs table
            cols = {
                r[1] for r in self.conn.execute("PRAGMA table_info(clerking_jobs)")
            }
            if "leased_until" not in cols:
                self.conn.execute(
                    "ALTER TABLE clerking_jobs "
                    "ADD COLUMN leased_until REAL NOT NULL DEFAULT 0"
                )
            if "leased_by" not in cols:
                # pre-gray-failure databases: the lease-owner column the
                # heartbeat recall / hedging plane keys on
                self.conn.execute(
                    "ALTER TABLE clerking_jobs "
                    "ADD COLUMN leased_by TEXT NOT NULL DEFAULT ''"
                )
            # migrate pre-exactly-once databases: the participant/digest
            # columns the single-winner participation insert keys on.
            # Legacy rows keep '' (never matches a real agent id or
            # digest); the read path recomputes their digest from doc.
            part_cols = {
                r[1] for r in self.conn.execute(
                    "PRAGMA table_info(participations)")
            }
            for column in ("participant", "digest"):
                if column not in part_cols:
                    self.conn.execute(
                        f"ALTER TABLE participations "
                        f"ADD COLUMN {column} TEXT NOT NULL DEFAULT ''"
                    )
            self.conn.execute(
                "CREATE INDEX IF NOT EXISTS ix_parts_agent "
                "ON participations (aggregation, participant)")

    @contextlib.contextmanager
    def immediate(self):
        """One multi-statement write as a single ``BEGIN IMMEDIATE``
        transaction: the write lock is taken at BEGIN (queueing behind
        other processes under busy_timeout), statements run, COMMIT
        publishes all of them atomically."""
        with self.lock:
            self.conn.execute("BEGIN IMMEDIATE")
            try:
                yield self.conn
            except BaseException:
                self.conn.execute("ROLLBACK")
                raise
            else:
                self.conn.execute("COMMIT")

    def ping(self) -> None:
        with self.lock:
            self.conn.execute("SELECT 1").fetchone()


class _SqliteStore(BaseStore):
    def __init__(self, db: SqliteDb):
        self.db = db

    def ping(self) -> None:
        self.db.ping()

    def _one(self, sql: str, args=()):
        with self.db.lock:
            row = self.db.conn.execute(sql, args).fetchone()
        return row

    def _all(self, sql: str, args=()):
        with self.db.lock:
            return self.db.conn.execute(sql, args).fetchall()

    def _exec(self, sql: str, args=()):
        # autocommit connection: a single statement is its own transaction
        with self.db.lock:
            return self.db.conn.execute(sql, args)


class SqliteAuthTokensStore(_SqliteStore, AuthTokensStore):
    def upsert_auth_token(self, token):
        self._exec(
            "INSERT INTO auth_tokens (id, body) VALUES (?, ?) "
            "ON CONFLICT (id) DO UPDATE SET body = excluded.body",
            (str(token.id), token.body),
        )

    def get_auth_token(self, id):
        row = self._one("SELECT body FROM auth_tokens WHERE id = ?", (str(id),))
        return None if row is None else auth_token(id, row[0])

    def delete_auth_token(self, id):
        self._exec("DELETE FROM auth_tokens WHERE id = ?", (str(id),))


class SqliteAgentsStore(_SqliteStore, AgentsStore):
    def create_agent(self, agent):
        self._exec(
            "INSERT INTO agents (id, doc) VALUES (?, ?) "
            "ON CONFLICT (id) DO UPDATE SET doc = excluded.doc",
            (str(agent.id), json.dumps(agent.to_obj())),
        )

    def get_agent(self, id):
        row = self._one("SELECT doc FROM agents WHERE id = ?", (str(id),))
        return None if row is None else Agent.from_obj(json.loads(row[0]))

    def upsert_profile(self, profile):
        self._exec(
            "INSERT INTO profiles (owner, doc) VALUES (?, ?) "
            "ON CONFLICT (owner) DO UPDATE SET doc = excluded.doc",
            (str(profile.owner), json.dumps(profile.to_obj())),
        )

    def get_profile(self, owner):
        row = self._one("SELECT doc FROM profiles WHERE owner = ?", (str(owner),))
        return None if row is None else Profile.from_obj(json.loads(row[0]))

    def create_encryption_key(self, key):
        self._exec(
            "INSERT INTO enc_keys (id, signer, doc) VALUES (?, ?, ?) "
            "ON CONFLICT (id) DO UPDATE SET signer = excluded.signer, "
            "doc = excluded.doc",
            (str(key.body.id), str(key.signer), json.dumps(key.to_obj())),
        )

    def get_encryption_key(self, key):
        row = self._one("SELECT doc FROM enc_keys WHERE id = ?", (str(key),))
        return None if row is None else signed_encryption_key_from_obj(json.loads(row[0]))

    def suggest_committee(self):
        rows = self._all("SELECT signer, id FROM enc_keys ORDER BY signer, id")
        candidates: List[ClerkCandidate] = []
        for signer, key_id in rows:
            if candidates and str(candidates[-1].id) == signer:
                candidates[-1].keys.append(EncryptionKeyId(key_id))
            else:
                candidates.append(
                    ClerkCandidate(id=AgentId(signer), keys=[EncryptionKeyId(key_id)])
                )
        return candidates


class SqliteAggregationsStore(_SqliteStore, AggregationsStore):
    def list_aggregations(self, filter=None, recipient=None):
        sql = "SELECT id FROM aggregations"
        clauses, args = [], []
        if filter is not None:
            clauses.append("instr(title, ?) > 0")
            args.append(filter)
        if recipient is not None:
            clauses.append("recipient = ?")
            args.append(str(recipient))
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += " ORDER BY id"
        return [AggregationId(r[0]) for r in self._all(sql, tuple(args))]

    def create_aggregation(self, aggregation):
        self._exec(
            "INSERT INTO aggregations (id, title, recipient, doc) VALUES (?, ?, ?, ?) "
            "ON CONFLICT (id) DO UPDATE SET title = excluded.title, "
            "recipient = excluded.recipient, doc = excluded.doc",
            (
                str(aggregation.id),
                aggregation.title,
                str(aggregation.recipient),
                json.dumps(aggregation.to_obj()),
            ),
        )

    def get_aggregation(self, aggregation):
        row = self._one("SELECT doc FROM aggregations WHERE id = ?", (str(aggregation),))
        return None if row is None else Aggregation.from_obj(json.loads(row[0]))

    def delete_aggregation(self, aggregation):
        agg = str(aggregation)
        with self.db.immediate():
            for table in ("snapshot_parts", "snapshot_masks",
                          "snapshot_mask_chunks", "snapshot_freezes"):
                self.db.conn.execute(
                    f"DELETE FROM {table} WHERE snapshot IN "
                    "(SELECT id FROM snapshots WHERE aggregation = ?)",
                    (agg,),
                )
            self.db.conn.execute(
                "DELETE FROM participations WHERE aggregation = ?", (agg,)
            )
            self.db.conn.execute("DELETE FROM snapshots WHERE aggregation = ?", (agg,))
            self.db.conn.execute("DELETE FROM committees WHERE aggregation = ?", (agg,))
            self.db.conn.execute("DELETE FROM rounds WHERE aggregation = ?", (agg,))
            self.db.conn.execute("DELETE FROM aggregations WHERE id = ?", (agg,))

    def get_committee(self, aggregation):
        row = self._one(
            "SELECT doc FROM committees WHERE aggregation = ?", (str(aggregation),)
        )
        return None if row is None else Committee.from_obj(json.loads(row[0]))

    def create_committee(self, committee):
        self._exec(
            "INSERT INTO committees (aggregation, doc) VALUES (?, ?) "
            "ON CONFLICT (aggregation) DO UPDATE SET doc = excluded.doc",
            (str(committee.aggregation), json.dumps(committee.to_obj())),
        )

    @staticmethod
    def _row_digest(digest, doc):
        """A row's canonical digest, recomputed from the stored doc for
        legacy rows written before the digest column existed."""
        if digest:
            return digest
        return Participation.from_obj(json.loads(doc)).canonical_digest()

    def create_participation(self, participation):
        chaos.fail("store.create_participation")
        digest = participation.canonical_digest()
        # the checks and the insert share one BEGIN IMMEDIATE transaction:
        # the write lock is the cross-process arbiter, so two racing
        # uploaders of one key admit exactly one winner (exactly-once
        # ingestion contract, stores.py)
        with self.db.immediate():
            exists = self.db.conn.execute(
                "SELECT 1 FROM aggregations WHERE id = ?",
                (str(participation.aggregation),),
            ).fetchone()
            if exists is None:
                raise NotFound("aggregation not found")
            row = self.db.conn.execute(
                "SELECT digest, doc FROM participations "
                "WHERE aggregation = ? AND id = ?",
                (str(participation.aggregation), str(participation.id)),
            ).fetchone()
            if row is not None:
                # same participation id: byte-identical replay succeeds
                # idempotently; different content never silently replaces
                if self._row_digest(row[0], row[1]) == digest:
                    return False
                raise ParticipationConflict(
                    f"participation {participation.id} already exists "
                    "with different content",
                    participant=participation.participant,
                    aggregation=participation.aggregation)
            owned = self.db.conn.execute(
                "SELECT id FROM participations "
                "WHERE aggregation = ? AND participant = ?",
                (str(participation.aggregation),
                 str(participation.participant)),
            ).fetchone()
            if owned is not None:
                # same agent under a NEW id: a recompute-with-fresh-
                # randomness (or equivocation) that would double-count
                raise ParticipationConflict(
                    f"agent {participation.participant} already "
                    f"participated in {participation.aggregation} "
                    f"(participation {owned[0]})",
                    participant=participation.participant,
                    aggregation=participation.aggregation)
            self.db.conn.execute(
                "INSERT INTO participations "
                "(id, aggregation, participant, digest, doc) "
                "VALUES (?, ?, ?, ?, ?)",
                (
                    str(participation.id),
                    str(participation.aggregation),
                    str(participation.participant),
                    digest,
                    json.dumps(participation.to_obj()),
                ),
            )
            return True

    def create_snapshot(self, snapshot):
        chaos.fail("store.create_snapshot")
        # conditional insert (single-winner across competing server
        # processes): OR IGNORE makes the existing row win and rowcount
        # says whether THIS statement inserted — the contended-idempotency
        # commit point (stores.py contract)
        cursor = self._exec(
            "INSERT OR IGNORE INTO snapshots (id, aggregation, doc) "
            "VALUES (?, ?, ?)",
            (
                str(snapshot.id),
                str(snapshot.aggregation),
                json.dumps(snapshot.to_obj()),
            ),
        )
        return cursor.rowcount > 0

    def list_snapshots(self, aggregation):
        rows = self._all(
            "SELECT id FROM snapshots WHERE aggregation = ? ORDER BY id",
            (str(aggregation),),
        )
        return [SnapshotId(r[0]) for r in rows]

    def get_snapshot(self, aggregation, snapshot):
        row = self._one(
            "SELECT doc FROM snapshots WHERE aggregation = ? AND id = ?",
            (str(aggregation), str(snapshot)),
        )
        return None if row is None else Snapshot.from_obj(json.loads(row[0]))

    def count_participations(self, aggregation):
        row = self._one(
            "SELECT COUNT(*) FROM participations WHERE aggregation = ?",
            (str(aggregation),),
        )
        return row[0]

    def snapshot_participations(self, aggregation, snapshot):
        # the $addToSet moment, made single-winner for the fleet plane:
        # the freeze-marker insert inside BEGIN IMMEDIATE is the race
        # arbiter (OR IGNORE + rowcount), and the frozen id set commits in
        # the SAME transaction — a loser observing rowcount 0 is
        # guaranteed the winner's set is already durable, because the
        # winner's transaction committed before ours could see its marker
        with self.db.immediate():
            cursor = self.db.conn.execute(
                "INSERT OR IGNORE INTO snapshot_freezes (snapshot) VALUES (?)",
                (str(snapshot),),
            )
            if cursor.rowcount == 0:
                return False  # a concurrent/earlier freeze already won
            self.db.conn.execute(
                "INSERT OR IGNORE INTO snapshot_parts (snapshot, participation) "
                "SELECT ?, id FROM participations WHERE aggregation = ?",
                (str(snapshot), str(aggregation)),
            )
        return True

    def has_snapshot_freeze(self, aggregation, snapshot):
        row = self._one(
            "SELECT 1 FROM snapshot_freezes WHERE snapshot = ?", (str(snapshot),)
        )
        return row is not None

    def count_participations_snapshot(self, aggregation, snapshot):
        row = self._one(
            "SELECT COUNT(*) FROM snapshot_parts WHERE snapshot = ?", (str(snapshot),)
        )
        return row[0]

    def iter_snapped_participations(self, aggregation, snapshot):
        rows = self._all(
            "SELECT p.doc FROM snapshot_parts s "
            "JOIN participations p ON p.id = s.participation AND p.aggregation = ? "
            "WHERE s.snapshot = ? ORDER BY p.id",
            (str(aggregation), str(snapshot)),
        )
        return [Participation.from_obj(json.loads(r[0])) for r in rows]

    def iter_snapshot_clerk_jobs_data(self, aggregation, snapshot, clerks_number):
        # the snapshot transpose without the detour through full
        # Participation objects: one join read (one lock hold), decode
        # ONLY the clerk_encryptions field of each document — at committee
        # width C that skips 3 uuid parses + a recipient-mask decode per row
        rows = self._all(
            "SELECT p.doc FROM snapshot_parts s "
            "JOIN participations p ON p.id = s.participation AND p.aggregation = ? "
            "WHERE s.snapshot = ? ORDER BY p.id",
            (str(aggregation), str(snapshot)),
        )
        columns: List[List[Encryption]] = [[] for _ in range(clerks_number)]
        for (doc,) in rows:
            for ix, (_, enc) in enumerate(json.loads(doc)["clerk_encryptions"]):
                columns[ix].append(Encryption.from_obj(enc))
        return columns

    #: rows per keyset page of the streamed mask-column reads below —
    #: each page completes its statement before the caller's interleaved
    #: chunk writes, so reader memory is O(page) at tree-scale counts
    _MASK_PAGE = 256

    def _iter_snapped_docs(self, aggregation, snapshot):
        """Keyset-paginated walk of the frozen set's documents, in
        participation-id order: only one page of JSON is ever resident,
        and no cursor stays open across the mask-chunk writes the
        snapshot pipeline interleaves with this read."""
        last = ""
        while True:
            rows = self._all(
                "SELECT p.id, p.doc FROM snapshot_parts s "
                "JOIN participations p ON p.id = s.participation AND p.aggregation = ? "
                "WHERE s.snapshot = ? AND p.id > ? ORDER BY p.id LIMIT ?",
                (str(aggregation), str(snapshot), last, self._MASK_PAGE),
            )
            if not rows:
                return
            for _pid, doc in rows:
                yield json.loads(doc)
            last = rows[-1][0]

    def iter_snapped_recipient_encryptions(self, aggregation, snapshot):
        # mask-column read: decode only the recipient_encryption field,
        # streamed page by page
        for doc in self._iter_snapped_docs(aggregation, snapshot):
            enc = doc.get("recipient_encryption")
            yield None if enc is None else Encryption.from_obj(enc)

    def iter_snapped_forwarded_masks(self, aggregation, snapshot):
        # forwarded-mask column read (tree parents): same streamed walk,
        # decode only the forwarded_masks field
        for doc in self._iter_snapped_docs(aggregation, snapshot):
            for enc in doc.get("forwarded_masks") or ():
                yield Encryption.from_obj(enc)

    # -- round lifecycle ----------------------------------------------------
    def put_round_state(self, doc):
        self._exec(
            "INSERT INTO rounds (aggregation, state, doc) VALUES (?, ?, ?) "
            "ON CONFLICT (aggregation) DO UPDATE SET "
            "state = excluded.state, doc = excluded.doc",
            (doc["aggregation"], doc["state"], json.dumps(doc)),
        )

    def get_round_state(self, aggregation):
        row = self._one(
            "SELECT doc FROM rounds WHERE aggregation = ?", (str(aggregation),)
        )
        return None if row is None else json.loads(row[0])

    def list_round_states(self):
        rows = self._all("SELECT doc FROM rounds ORDER BY aggregation")
        return [json.loads(r[0]) for r in rows]

    def transition_round_state(self, aggregation, from_states, doc):
        # single-winner CAS across OS processes: ONE conditional UPDATE —
        # autocommit makes it its own transaction, rowcount says whether
        # THIS worker's sweep performed the transition (fleet contract,
        # same shape as the snapshot-freeze conditional insert)
        from_states = tuple(str(s) for s in from_states)
        cursor = self._exec(
            "UPDATE rounds SET state = ?, doc = ? WHERE aggregation = ? "
            f"AND state IN ({','.join('?' * len(from_states))})",
            (doc["state"], json.dumps(doc), str(aggregation), *from_states),
        )
        return cursor.rowcount > 0

    # -- recurring-round schedules -------------------------------------------
    def create_schedule_state(self, doc):
        # conditional insert (single-winner across OS processes): OR
        # IGNORE + rowcount, same arbitration shape as create_snapshot —
        # a booting scheduler can never reset an advanced schedule
        cursor = self._exec(
            "INSERT OR IGNORE INTO schedules (schedule, epoch, doc) "
            "VALUES (?, ?, ?)",
            (doc["schedule"], int(doc["epoch"]), json.dumps(doc)),
        )
        return cursor.rowcount > 0

    def get_schedule_state(self, schedule):
        row = self._one(
            "SELECT doc FROM schedules WHERE schedule = ?", (str(schedule),)
        )
        return None if row is None else json.loads(row[0])

    def list_schedule_states(self):
        rows = self._all("SELECT doc FROM schedules ORDER BY schedule")
        return [json.loads(r[0]) for r in rows]

    def transition_schedule_state(self, schedule, from_epoch, doc):
        # single-winner epoch CAS across OS processes: ONE conditional
        # UPDATE keyed on the FROM epoch; rowcount says whether THIS
        # scheduler's advance won (same shape as transition_round_state)
        cursor = self._exec(
            "UPDATE schedules SET epoch = ?, doc = ? "
            "WHERE schedule = ? AND epoch = ?",
            (int(doc["epoch"]), json.dumps(doc), str(schedule),
             int(from_epoch)),
        )
        return cursor.rowcount > 0

    def create_snapshot_mask(self, snapshot, mask):
        self.put_snapshot_mask_chunk(snapshot, 0, mask)
        self.trim_snapshot_mask_chunks(snapshot, 1)

    def put_snapshot_mask_chunk(self, snapshot, index, encryptions):
        # pure chunk upsert keyed by (snapshot, chunk_ix): a replaying or
        # contended pipeline rewrites byte-identical chunks (stores.py
        # contract), so a reader holding the committed snapshot record
        # always sees a complete mask — the atomicity the old single-row
        # write had. Chunk 0 also supersedes any legacy single-row mask.
        snap = str(snapshot)
        doc = json.dumps([e.to_obj() for e in encryptions])
        with self.db.immediate():
            if index == 0:
                self.db.conn.execute(
                    "DELETE FROM snapshot_masks WHERE snapshot = ?", (snap,))
            self.db.conn.execute(
                "INSERT INTO snapshot_mask_chunks (snapshot, chunk_ix, doc) "
                "VALUES (?, ?, ?) ON CONFLICT (snapshot, chunk_ix) "
                "DO UPDATE SET doc = excluded.doc",
                (snap, int(index), doc),
            )

    def trim_snapshot_mask_chunks(self, snapshot, count):
        self._exec(
            "DELETE FROM snapshot_mask_chunks WHERE snapshot = ? "
            "AND chunk_ix >= ?", (str(snapshot), int(count)),
        )

    def get_snapshot_mask(self, snapshot):
        rows = self._all(
            "SELECT doc FROM snapshot_mask_chunks WHERE snapshot = ? "
            "ORDER BY chunk_ix", (str(snapshot),)
        )
        if not rows:
            # pre-chunking database: fall back to the legacy single row
            row = self._one(
                "SELECT doc FROM snapshot_masks WHERE snapshot = ?",
                (str(snapshot),)
            )
            if row is None:
                return None
            return [Encryption.from_obj(e) for e in json.loads(row[0])]
        return [
            Encryption.from_obj(e)
            for (doc,) in rows
            for e in json.loads(doc)
        ]


class SqliteClerkingJobsStore(_SqliteStore, ClerkingJobsStore):
    def enqueue_clerking_job(self, job):
        chaos.fail("store.enqueue_clerking_job")
        # upsert keyed by (clerk, id); the conflict clause deliberately
        # leaves done/leased_until alone — and refuses to touch a DONE
        # job's payload at all — so a snapshot retry can't resurrect,
        # un-lease, or rewrite completed work
        self._exec(
            "INSERT INTO clerking_jobs (id, clerk, snapshot, done, doc) "
            "VALUES (?, ?, ?, 0, ?) "
            "ON CONFLICT (clerk, id) DO UPDATE SET doc = excluded.doc "
            "WHERE clerking_jobs.done = 0",
            (
                str(job.id),
                str(job.clerk),
                str(job.snapshot),
                json.dumps(job.to_obj()),
            ),
        )

    def enqueue_clerking_jobs(self, jobs):
        # the snapshot fan-out: C jobs (each a whole clerk column) in ONE
        # transaction instead of C commits. Same upsert clause as the
        # per-item path, so done jobs are never resurrected; failpoints
        # fire per job so chaos drills keep their trigger budget
        jobs = list(jobs)
        if not jobs:
            return
        for _ in jobs:
            chaos.fail("store.enqueue_clerking_job")
        with self.db.immediate():
            self.db.conn.executemany(
                "INSERT INTO clerking_jobs (id, clerk, snapshot, done, doc) "
                "VALUES (?, ?, ?, 0, ?) "
                "ON CONFLICT (clerk, id) DO UPDATE SET doc = excluded.doc "
                "WHERE clerking_jobs.done = 0",
                [
                    (
                        str(job.id),
                        str(job.clerk),
                        str(job.snapshot),
                        json.dumps(job.to_obj()),
                    )
                    for job in jobs
                ],
            )

    def poll_clerking_job(self, clerk):
        chaos.fail("store.poll_clerking_job")
        row = self._one(
            "SELECT doc FROM clerking_jobs WHERE clerk = ? AND done = 0 "
            "ORDER BY id LIMIT 1",
            (str(clerk),),
        )
        return None if row is None else ClerkingJob.from_obj(json.loads(row[0]))

    def lease_clerking_job(self, clerk, lease_seconds, now=None, owner=None):
        chaos.fail("store.poll_clerking_job")
        now = time.time() if now is None else now
        # select + stamp in ONE immediate transaction: two processes
        # polling the same clerk identity cannot both stamp one job
        with self.db.immediate():
            row = self.db.conn.execute(
                "SELECT id, doc, leased_until FROM clerking_jobs "
                "WHERE clerk = ? AND done = 0 AND leased_until <= ? "
                "ORDER BY id LIMIT 1",
                (str(clerk), now),
            ).fetchone()
            if row is None:
                return None
            job_id, doc, previous = row
            if previous > 0:
                metrics.count("server.job.reissued")
            expires = now + lease_seconds
            self.db.conn.execute(
                "UPDATE clerking_jobs SET leased_until = ?, leased_by = ? "
                "WHERE clerk = ? AND id = ?",
                (expires, owner or "", str(clerk), job_id),
            )
            return ClerkingJob.from_obj(json.loads(doc)), expires

    def release_clerking_job_lease(self, clerk, job, expires=None):
        # graceful drain: hand a still-undone job straight back to the
        # fleet (leased_until 0 == immediately pollable by any process).
        # Compare-and-release: with `expires` the UPDATE only matches the
        # exact lease this caller was granted — a lapsed lease re-granted
        # to a peer has a new leased_until and stays the peer's
        sql = ("UPDATE clerking_jobs SET leased_until = 0, leased_by = '' "
               "WHERE clerk = ? AND id = ? AND done = 0 AND leased_until > 0")
        args = [str(clerk), str(job)]
        if expires is not None:
            sql += " AND leased_until = ?"
            args.append(expires)
        cursor = self._exec(sql, tuple(args))
        return cursor.rowcount > 0

    def recall_clerking_job_leases(self, node_id):
        # the dead-node recovery step: ONE conditional UPDATE drops every
        # active lease the dead worker granted — any process's next poll
        # reissues them immediately (autocommit: its own transaction)
        cursor = self._exec(
            "UPDATE clerking_jobs SET leased_until = 0, leased_by = '' "
            "WHERE leased_by = ? AND done = 0 AND leased_until > 0",
            (str(node_id),),
        )
        return cursor.rowcount

    def hedge_clerking_job(self, clerk, suspect_nodes, lease_seconds,
                           now=None, owner=None):
        # hedged execution: re-grant a SUSPECT holder's ACTIVE lease to
        # this caller inside one immediate transaction (two hedgers race,
        # one wins); the original holder may still finish — result commit
        # stays single-winner on the done flag
        suspects = [str(n) for n in suspect_nodes]
        if not suspects:
            return None
        now = time.time() if now is None else now
        with self.db.immediate():
            row = self.db.conn.execute(
                "SELECT id, doc FROM clerking_jobs "
                "WHERE clerk = ? AND done = 0 AND leased_until > ? "
                f"AND leased_by IN ({','.join('?' * len(suspects))}) "
                "ORDER BY id LIMIT 1",
                (str(clerk), now, *suspects),
            ).fetchone()
            if row is None:
                return None
            job_id, doc = row
            expires = now + lease_seconds
            self.db.conn.execute(
                "UPDATE clerking_jobs SET leased_until = ?, leased_by = ? "
                "WHERE clerk = ? AND id = ?",
                (expires, owner or "", str(clerk), job_id),
            )
            return ClerkingJob.from_obj(json.loads(doc)), expires

    # -- fleet heartbeats ---------------------------------------------------
    def put_worker_heartbeat(self, doc):
        self._exec(
            "INSERT INTO worker_heartbeats (node, state, doc) "
            "VALUES (?, ?, ?) "
            "ON CONFLICT (node) DO UPDATE SET "
            "state = excluded.state, doc = excluded.doc",
            (doc["node"], doc["state"], json.dumps(doc)),
        )

    def get_worker_heartbeat(self, node):
        row = self._one(
            "SELECT doc FROM worker_heartbeats WHERE node = ?", (str(node),)
        )
        return None if row is None else json.loads(row[0])

    def list_worker_heartbeats(self):
        rows = self._all("SELECT doc FROM worker_heartbeats ORDER BY node")
        return [json.loads(r[0]) for r in rows]

    def transition_worker_state(self, node, from_states, doc):
        # single-winner CAS across OS processes: one conditional UPDATE,
        # rowcount says whether THIS sweeper's declaration won (same
        # shape as transition_round_state)
        from_states = tuple(str(s) for s in from_states)
        cursor = self._exec(
            "UPDATE worker_heartbeats SET state = ?, doc = ? "
            f"WHERE node = ? AND state IN ({','.join('?' * len(from_states))})",
            (doc["state"], json.dumps(doc), str(node), *from_states),
        )
        return cursor.rowcount > 0

    def list_snapshot_jobs(self, snapshot):
        # the sweeper's dead-clerk census: one indexed-column read, no
        # payload decode (the doc column never leaves the database)
        rows = self._all(
            "SELECT id, clerk, done, leased_until FROM clerking_jobs "
            "WHERE snapshot = ? ORDER BY id",
            (str(snapshot),),
        )
        return [
            (ClerkingJobId(r[0]), AgentId(r[1]), bool(r[2]), float(r[3]))
            for r in rows
        ]

    def get_clerking_job(self, clerk, job):
        row = self._one(
            "SELECT doc FROM clerking_jobs WHERE clerk = ? AND id = ?",
            (str(clerk), str(job)),
        )
        return None if row is None else ClerkingJob.from_obj(json.loads(row[0]))

    def create_clerking_result(self, result):
        chaos.fail("store.create_clerking_result")
        # result write + done-flag flip, atomically (the Mongo store's
        # done-flag queue semantics, clerking_jobs.rs:32-75)
        with self.db.immediate():
            row = self.db.conn.execute(
                "SELECT snapshot, done FROM clerking_jobs WHERE clerk = ? AND id = ?",
                (str(result.clerk), str(result.job)),
            ).fetchone()
            if row is None:
                raise NotFound("job not found for clerk")
            snapshot, done = row
            if done:
                return  # duplicate result upload: idempotent
            self.db.conn.execute(
                "INSERT INTO clerking_results (job, snapshot, doc) VALUES (?, ?, ?) "
                "ON CONFLICT (snapshot, job) DO UPDATE SET doc = excluded.doc",
                (str(result.job), snapshot, json.dumps(result.to_obj())),
            )
            self.db.conn.execute(
                "UPDATE clerking_jobs SET done = 1 WHERE clerk = ? AND id = ?",
                (str(result.clerk), str(result.job)),
            )

    def purge_snapshot_jobs(self, snapshot):
        # the retention/delete cascade's job-store half: jobs (queued and
        # done, leases riding the rows) and results of the snapshot leave
        # in one transaction
        with self.db.immediate():
            jobs = self.db.conn.execute(
                "DELETE FROM clerking_jobs WHERE snapshot = ?",
                (str(snapshot),),
            ).rowcount
            results = self.db.conn.execute(
                "DELETE FROM clerking_results WHERE snapshot = ?",
                (str(snapshot),),
            ).rowcount
        return max(0, jobs) + max(0, results)

    def list_results(self, snapshot):
        rows = self._all(
            "SELECT job FROM clerking_results WHERE snapshot = ? ORDER BY job",
            (str(snapshot),),
        )
        return [ClerkingJobId(r[0]) for r in rows]

    def get_result(self, snapshot, job):
        row = self._one(
            "SELECT doc FROM clerking_results WHERE snapshot = ? AND job = ?",
            (str(snapshot), str(job)),
        )
        return None if row is None else ClerkingResult.from_obj(json.loads(row[0]))
