"""Consistent-hash routing of aggregations onto fleet workers.

The SDA server is a stateless broker over durable stores, so *any* worker
can serve *any* request — routing is purely an affinity optimization: by
concentrating one aggregation's traffic (its snapshot POSTs, its clerks'
job polls, its recipient's status/result reads) on a preferred worker, the
client-side immutable-doc caches stay hot and clerking-job leases are
taken and refreshed by the node that already holds the committee documents
in memory. A request that lands elsewhere is still served correctly; the
store-level contended-idempotency contract (docs/scaling.md) guarantees
that even racing control-plane writes from two nodes converge bit-exactly.

The ring is the classic Karger construction: each node is hashed onto the
circle at ``replicas`` virtual points and a key routes to the first node
clockwise. Adding/removing one node therefore only moves ~1/N of the
keyspace — a drained worker's aggregations redistribute without reshuffling
everyone else's affinity (and therefore their caches).

Deterministic by construction (SHA-256, no process state): every client,
worker, and the fleet launcher computes the same mapping from the same
peer list, so routing needs no coordination service.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Sequence

#: Response header naming the worker that actually served the request.
NODE_HEADER = "X-SDA-Node"

DEFAULT_REPLICAS = 64


def _point(data: str) -> int:
    return int.from_bytes(hashlib.sha256(data.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over a fixed set of node ids."""

    def __init__(self, nodes: Sequence[str], replicas: int = DEFAULT_REPLICAS):
        nodes = list(dict.fromkeys(str(n) for n in nodes))  # dedupe, keep order
        if not nodes:
            raise ValueError("HashRing needs at least one node")
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self.nodes = nodes
        self.replicas = replicas
        points = []
        for node in nodes:
            for replica in range(replicas):
                points.append((_point(f"{node}#{replica}"), node))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [n for _, n in points]

    def node_for(self, key: str) -> str:
        """The preferred worker for ``key`` (e.g. an aggregation id)."""
        ix = bisect.bisect_right(self._points, _point(str(key)))
        if ix == len(self._points):
            ix = 0  # wrap: first point clockwise past the top of the circle
        return self._owners[ix]

    def preferred(self, key: str, count: int = 1) -> List[str]:
        """The first ``count`` DISTINCT nodes clockwise from ``key`` —
        position 0 is the primary, the rest are the natural failover
        order (same walk a replica placement would use)."""
        count = min(count, len(self.nodes))
        ix = bisect.bisect_right(self._points, _point(str(key)))
        out: List[str] = []
        for step in range(len(self._points)):
            node = self._owners[(ix + step) % len(self._points)]
            if node not in out:
                out.append(node)
                if len(out) == count:
                    break
        return out

    def spread(self, keys: Sequence[str]) -> Dict[str, int]:
        """Keys-per-node tally — the launcher prints this so an operator
        can eyeball balance before pointing real traffic at the fleet."""
        tally = {node: 0 for node in self.nodes}
        for key in keys:
            tally[self.node_for(key)] += 1
        return tally
