"""In-process job-wakeup registry — the push half of long-poll clerking.

The polling storm the async HTTP plane exists to kill has two parts:
idle connections (solved by the event loop) and the *store* being
re-scanned by every clerk on a fixed cadence. This registry removes the
second: a long-poll request parks on a per-clerk subscription, and the
events that can make a job appear — snapshot fan-out
(``server/snapshot.py``), a drain handing leases back
(``SdaServer.release_held_leases``), a failure detector recalling a dead
worker's leases (``server/health.py``) — notify exactly the clerks that
might now have work. Job-pickup latency collapses from the polling
interval to the notify-to-poll hop.

Fleet caveat: the registry is per-process. A peer worker's fan-out
notifies *its* subscribers, not ours, so a parked long-poll also
re-checks the shared store on a short tick (``SDA_LONGPOLL_TICK``) —
cross-worker wakeups degrade to that tick, same-worker wakeups are
immediate. Lease *expiry* (a time-based reissue with no event) is
covered by the same tick.

Two waiter flavors share one subscription type: the threaded HTTP plane
blocks its request thread on ``Subscription.wait``; the asyncio plane
registers a callback that ``loop.call_soon_threadsafe``-sets an
``asyncio.Event``, so a parked long-poll holds no thread at all.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from ..utils.env import env_float

__all__ = ["JobWakeup", "Subscription", "LONGPOLL_MAX_S", "LONGPOLL_TICK_S",
           "clamp_wait", "longpoll_tick"]


# ---------------------------------------------------------------------------
# Long-poll contract knobs. They live HERE, next to the wakeup registry,
# because "how long may a wait park" is a server-layer policy shared by
# every long-poll flavor — the HTTP route, the in-process
# ``await_clerking_job`` seam — not an HTTP detail (``http/base.py``
# re-exports them for the transports).

#: Hard ceiling on ``wait=`` (docs/http.md): long enough to kill the
#: polling storm, short enough that proxies/timeouts never reap a healthy
#: parked request. Clients re-issue on empty.
LONGPOLL_MAX_S = 55.0
#: Parked re-check cadence: the cross-worker degradation path (a fleet
#: peer's fan-out notifies ITS process, not ours) and the lease-expiry
#: reissue path (time-based, no event) are both bounded by this.
LONGPOLL_TICK_S = 0.5


def clamp_wait(wait_s: float) -> float:
    """Clamp a requested long-poll wait to [0, SDA_LONGPOLL_MAX]."""
    ceiling = env_float("SDA_LONGPOLL_MAX", LONGPOLL_MAX_S)
    return max(0.0, min(float(wait_s), ceiling))


def longpoll_tick() -> float:
    return max(0.01, env_float("SDA_LONGPOLL_TICK", LONGPOLL_TICK_S))


class Subscription:
    """One parked waiter for one clerk key. ``wait`` serves sync waiters;
    ``callback`` (invoked at most once, from the notifier's thread) serves
    event-loop waiters. Always ``unsubscribe`` in a ``finally``."""

    __slots__ = ("key", "_event", "_callback", "_fired")

    def __init__(self, key: str, callback: Optional[Callable[[], None]]):
        self.key = key
        self._event = threading.Event()
        self._callback = callback
        self._fired = False

    def fire(self) -> None:
        self._event.set()
        cb, self._callback = self._callback, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass  # a dying event loop must not break the notifier

    def wait(self, timeout: Optional[float]) -> bool:
        """Block until notified (or ``timeout``); True when notified."""
        return self._event.wait(timeout)

    def clear(self) -> None:
        """Re-arm a sync subscription for another wait round."""
        self._event.clear()


class JobWakeup:
    """Condition-variable fan-out keyed by clerk id (as ``str``).

    ``notify(keys)`` wakes every subscription under those keys;
    ``notify()`` / ``notify_all()`` wakes everyone — the drain path uses
    that so parked long-polls answer 503 immediately instead of holding
    their timeout. Notifying a key nobody is parked on is free.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._waiters: dict = {}  # key -> list[Subscription]

    def subscribe(self, key, callback: Optional[Callable[[], None]] = None
                  ) -> Subscription:
        sub = Subscription(str(key), callback)
        with self._lock:
            self._waiters.setdefault(sub.key, []).append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            subs = self._waiters.get(sub.key)
            if subs is not None:
                try:
                    subs.remove(sub)
                except ValueError:
                    pass
                if not subs:
                    self._waiters.pop(sub.key, None)

    def parked(self) -> int:
        """How many subscriptions are currently parked (statusz)."""
        with self._lock:
            return sum(len(subs) for subs in self._waiters.values())

    def notify(self, keys: Optional[Iterable] = None) -> int:
        """Wake the waiters parked under ``keys`` (every waiter when
        ``keys`` is None); returns how many subscriptions fired."""
        with self._lock:
            if keys is None:
                fired = [s for subs in self._waiters.values() for s in subs]
            else:
                fired = []
                for key in {str(k) for k in keys}:
                    fired.extend(self._waiters.get(key, ()))
        for sub in fired:
            sub.fire()
        return len(fired)

    def notify_all(self) -> int:
        return self.notify(None)
