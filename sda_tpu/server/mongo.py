"""MongoDB store backend — document-database tier (reference parity).

Direct analog of the reference's production storage
(server-store-mongodb/src/lib.rs): one collection per resource holding the
JSON document keyed by ``_id``, upserts via ``replace_one(upsert=True)``
(the Mongo store's ``modisert``, lib.rs:118-151), snapshot freezing as an
``$addToSet`` of the snapshot id onto participation documents
(aggregations.rs:132-142), and a done-flag clerk-job queue with an atomic
``find_one_and_update`` flip (clerking_jobs.rs:32-75).

``pymongo`` is not part of this image, so the module is import-gated:
``available()`` is False without the driver and ``new_mongo_server``
raises a clear error. The semantics mirror the SQLite backend
(sqlite.py), which runs the same store test suites in-image; when a Mongo
deployment is present, point ``sdad --mongo URI`` at it.
"""

from __future__ import annotations

import time
from typing import List, Optional

from .. import chaos
from ..utils import metrics

try:  # driver not baked into this image; gate, don't fail at import
    import pymongo

    _PYMONGO = True
except ImportError:  # pragma: no cover - exercised only without the driver
    pymongo = None
    _PYMONGO = False

from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    Encryption,
    EncryptionKeyId,
    NotFound,
    Participation,
    ParticipationConflict,
    Profile,
    Snapshot,
    SnapshotId,
    signed_encryption_key_from_obj,
)
from .stores import (
    AgentsStore,
    AggregationsStore,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
    auth_token,
)


def available() -> bool:
    return _PYMONGO


class _MongoStore(BaseStore):
    def __init__(self, db):
        self.db = db

    def ping(self) -> None:
        self.db.command("ping")


class MongoAuthTokensStore(_MongoStore, AuthTokensStore):
    def upsert_auth_token(self, token):
        self.db.auth_tokens.replace_one(
            {"_id": str(token.id)}, {"_id": str(token.id), "body": token.body},
            upsert=True,
        )

    def get_auth_token(self, id):
        doc = self.db.auth_tokens.find_one({"_id": str(id)})
        return None if doc is None else auth_token(id, doc["body"])

    def delete_auth_token(self, id):
        self.db.auth_tokens.delete_one({"_id": str(id)})


class MongoAgentsStore(_MongoStore, AgentsStore):
    def create_agent(self, agent):
        self.db.agents.replace_one(
            {"_id": str(agent.id)}, {"_id": str(agent.id), "doc": agent.to_obj()},
            upsert=True,
        )

    def get_agent(self, id):
        doc = self.db.agents.find_one({"_id": str(id)})
        return None if doc is None else Agent.from_obj(doc["doc"])

    def upsert_profile(self, profile):
        self.db.profiles.replace_one(
            {"_id": str(profile.owner)},
            {"_id": str(profile.owner), "doc": profile.to_obj()},
            upsert=True,
        )

    def get_profile(self, owner):
        doc = self.db.profiles.find_one({"_id": str(owner)})
        return None if doc is None else Profile.from_obj(doc["doc"])

    def create_encryption_key(self, key):
        self.db.enc_keys.replace_one(
            {"_id": str(key.body.id)},
            {"_id": str(key.body.id), "signer": str(key.signer), "doc": key.to_obj()},
            upsert=True,
        )

    def get_encryption_key(self, key):
        doc = self.db.enc_keys.find_one({"_id": str(key)})
        return None if doc is None else signed_encryption_key_from_obj(doc["doc"])

    def suggest_committee(self):
        # group keys by signer, sorted — the reference does this with a
        # client-side itertools group (jfs_stores/agents.rs:66-83)
        candidates: List[ClerkCandidate] = []
        for doc in self.db.enc_keys.find().sort([("signer", 1), ("_id", 1)]):
            signer, key_id = doc["signer"], doc["_id"]
            if candidates and str(candidates[-1].id) == signer:
                candidates[-1].keys.append(EncryptionKeyId(key_id))
            else:
                candidates.append(
                    ClerkCandidate(id=AgentId(signer), keys=[EncryptionKeyId(key_id)])
                )
        return candidates


class MongoAggregationsStore(_MongoStore, AggregationsStore):
    def list_aggregations(self, filter=None, recipient=None):
        query = {}
        if filter is not None:
            import re

            # escape so this is plain substring matching, same as the
            # memory/jsonfs/sqlite backends (the reference's raw-$regex
            # behavior diverges per backend and errors on metacharacters)
            query["title"] = {"$regex": re.escape(filter)}
        if recipient is not None:
            query["recipient"] = str(recipient)
        return [
            AggregationId(d["_id"])
            for d in self.db.aggregations.find(query).sort("_id", 1)
        ]

    def create_aggregation(self, aggregation):
        self.db.aggregations.replace_one(
            {"_id": str(aggregation.id)},
            {
                "_id": str(aggregation.id),
                "title": aggregation.title,
                "recipient": str(aggregation.recipient),
                "doc": aggregation.to_obj(),
            },
            upsert=True,
        )

    def get_aggregation(self, aggregation):
        doc = self.db.aggregations.find_one({"_id": str(aggregation)})
        return None if doc is None else Aggregation.from_obj(doc["doc"])

    def delete_aggregation(self, aggregation):
        agg = str(aggregation)
        snap_ids = [d["_id"] for d in self.db.snapshots.find({"aggregation": agg})]
        if snap_ids:
            self.db.snapshot_masks.delete_many({"_id": {"$in": snap_ids}})
            self.db.snapshot_mask_chunks.delete_many(
                {"snapshot": {"$in": snap_ids}})
            self.db.snapshot_freezes.delete_many({"_id": {"$in": snap_ids}})
        self.db.participations.delete_many({"aggregation": agg})
        self.db.participation_owners.delete_many(
            {"_id": {"$regex": f"^{agg}:"}})
        self.db.snapshots.delete_many({"aggregation": agg})
        self.db.committees.delete_one({"_id": agg})
        self.db.rounds.delete_one({"_id": agg})
        self.db.aggregations.delete_one({"_id": agg})

    def get_committee(self, aggregation):
        doc = self.db.committees.find_one({"_id": str(aggregation)})
        return None if doc is None else Committee.from_obj(doc["doc"])

    def create_committee(self, committee):
        self.db.committees.replace_one(
            {"_id": str(committee.aggregation)},
            {"_id": str(committee.aggregation), "doc": committee.to_obj()},
            upsert=True,
        )

    @staticmethod
    def _participation_doc(participation, digest):
        return {
            "_id": str(participation.id),
            "aggregation": str(participation.aggregation),
            "participant": str(participation.participant),
            "digest": digest,
            "snapshots": [],
            "doc": participation.to_obj(),
        }

    @staticmethod
    def _doc_digest(doc):
        """A stored participation doc's canonical digest, recomputed for
        legacy docs written before the digest field existed."""
        if doc.get("digest"):
            return doc["digest"]
        return Participation.from_obj(doc["doc"]).canonical_digest()

    def create_participation(self, participation):
        chaos.fail("store.create_participation")
        if self.get_aggregation(participation.aggregation) is None:
            raise NotFound("aggregation not found")
        digest = participation.canonical_digest()
        pid = str(participation.id)
        existing = self.db.participations.find_one({"_id": pid})
        if existing is not None:
            # same participation id: byte-identical replay succeeds
            # idempotently; different content never silently replaces
            if self._doc_digest(existing) == digest:
                self._claim_owner(participation, digest)  # heal the marker
                return False
            raise ParticipationConflict(
                f"participation {pid} already exists with different "
                "content",
                participant=participation.participant,
                aggregation=participation.aggregation)
        result = self._claim_owner(participation, digest)
        if result.upserted_id is not None:
            # won the (aggregation, participant) key: publish the payload
            # with Mongo's atomic create-if-absent (a replayed loser of a
            # crash window republishes the same bytes harmlessly)
            self.db.participations.update_one(
                {"_id": pid},
                {"$setOnInsert": self._participation_doc(participation,
                                                         digest)},
                upsert=True,
            )
            return True
        marker = self.db.participation_owners.find_one(
            {"_id": self._owner_key(participation)}) or {}
        if marker.get("digest") == digest:
            # replay of our own bytes; re-publish the payload in case the
            # original writer crashed between marker and payload
            self.db.participations.update_one(
                {"_id": pid},
                {"$setOnInsert": self._participation_doc(participation,
                                                         digest)},
                upsert=True,
            )
            return False
        raise ParticipationConflict(
            f"agent {participation.participant} already participated in "
            f"{participation.aggregation} (participation "
            f"{marker.get('id')})",
            participant=participation.participant,
            aggregation=participation.aggregation)

    @staticmethod
    def _owner_key(participation):
        return f"{participation.aggregation}:{participation.participant}"

    def _claim_owner(self, participation, digest):
        """$setOnInsert upsert on the per-(aggregation, participant)
        marker — Mongo's atomic create-if-absent is the single-winner
        arbiter (marker first, payload second: same crash-window
        reasoning as the jsonfs backend)."""
        return self.db.participation_owners.update_one(
            {"_id": self._owner_key(participation)},
            {"$setOnInsert": {"_id": self._owner_key(participation),
                              "id": str(participation.id),
                              "digest": digest}},
            upsert=True,
        )

    def create_snapshot(self, snapshot):
        chaos.fail("store.create_snapshot")
        # conditional insert via $setOnInsert upsert — Mongo's atomic
        # create-if-absent; upserted_id says whether THIS call won the
        # race (contended-idempotency contract, stores.py)
        result = self.db.snapshots.update_one(
            {"_id": str(snapshot.id)},
            {"$setOnInsert": {
                "_id": str(snapshot.id),
                "aggregation": str(snapshot.aggregation),
                "doc": snapshot.to_obj(),
            }},
            upsert=True,
        )
        return result.upserted_id is not None

    def list_snapshots(self, aggregation):
        return [
            SnapshotId(d["_id"])
            for d in self.db.snapshots.find(
                {"aggregation": str(aggregation)}).sort("_id", 1)
        ]

    def get_snapshot(self, aggregation, snapshot):
        doc = self.db.snapshots.find_one(
            {"_id": str(snapshot), "aggregation": str(aggregation)}
        )
        return None if doc is None else Snapshot.from_obj(doc["doc"])

    def count_participations(self, aggregation):
        return self.db.participations.count_documents(
            {"aggregation": str(aggregation)}
        )

    def snapshot_participations(self, aggregation, snapshot):
        # single-winner freeze in ONE atomic document write: the marker
        # doc itself carries the frozen participation-id list, installed
        # with a $setOnInsert upsert — Mongo's create-if-absent — so two
        # racing server processes cannot install different sets and the
        # loser (upserted_id None) can read the winner's complete list
        # the moment this returns. This replaces the reference's
        # two-write $addToSet + marker freeze (aggregations.rs:132-142),
        # which was crash-replay-safe but not contended-safe: two
        # processes interleaving $addToSet sweeps could freeze different
        # supersets. Legacy $addToSet-frozen data (marker without "ids")
        # still reads through the snapshots-array fallback below.
        part_ids = sorted(
            d["_id"]
            for d in self.db.participations.find(
                {"aggregation": str(aggregation)})
        )
        result = self.db.snapshot_freezes.update_one(
            {"_id": str(snapshot)},
            {"$setOnInsert": {"_id": str(snapshot), "ids": part_ids}},
            upsert=True,
        )
        return result.upserted_id is not None

    def has_snapshot_freeze(self, aggregation, snapshot):
        return self.db.snapshot_freezes.find_one({"_id": str(snapshot)}) is not None

    def _frozen_ids(self, snapshot) -> Optional[List[str]]:
        """The marker doc's frozen id list, or None for pre-fleet data
        frozen via $addToSet (read those through the snapshots array)."""
        marker = self.db.snapshot_freezes.find_one({"_id": str(snapshot)})
        if marker is None or "ids" not in marker:
            return None
        return marker["ids"]

    def count_participations_snapshot(self, aggregation, snapshot):
        ids = self._frozen_ids(snapshot)
        if ids is not None:
            return len(ids)
        return self.db.participations.count_documents(
            {"aggregation": str(aggregation), "snapshots": str(snapshot)}
        )

    def iter_snapped_participations(self, aggregation, snapshot):
        ids = self._frozen_ids(snapshot)
        if ids is not None:
            cursor = self.db.participations.find(
                {"aggregation": str(aggregation), "_id": {"$in": ids}}
            )
        else:  # legacy $addToSet freeze
            cursor = self.db.participations.find(
                {"aggregation": str(aggregation), "snapshots": str(snapshot)}
            )
        return [
            Participation.from_obj(d["doc"]) for d in cursor.sort("_id", 1)
        ]

    def _iter_snapped_docs(self, aggregation, snapshot):
        """Streamed walk of the frozen set's documents (cursor-batched by
        the driver — O(batch) resident, never the whole population)."""
        ids = self._frozen_ids(snapshot)
        if ids is not None:
            cursor = self.db.participations.find(
                {"aggregation": str(aggregation), "_id": {"$in": ids}}
            )
        else:  # legacy $addToSet freeze
            cursor = self.db.participations.find(
                {"aggregation": str(aggregation), "snapshots": str(snapshot)}
            )
        for d in cursor.sort("_id", 1):
            yield d["doc"]

    def iter_snapped_recipient_encryptions(self, aggregation, snapshot):
        # mask-column read: decode only the recipient_encryption field
        for doc in self._iter_snapped_docs(aggregation, snapshot):
            enc = doc.get("recipient_encryption")
            yield None if enc is None else Encryption.from_obj(enc)

    def iter_snapped_forwarded_masks(self, aggregation, snapshot):
        # forwarded-mask column read (tree parents): same streamed walk
        for doc in self._iter_snapped_docs(aggregation, snapshot):
            for enc in doc.get("forwarded_masks") or ():
                yield Encryption.from_obj(enc)

    # -- round lifecycle ----------------------------------------------------
    def put_round_state(self, doc):
        self.db.rounds.replace_one(
            {"_id": doc["aggregation"]},
            {"_id": doc["aggregation"], "state": doc["state"], "doc": doc},
            upsert=True,
        )

    def get_round_state(self, aggregation):
        found = self.db.rounds.find_one({"_id": str(aggregation)})
        return None if found is None else found["doc"]

    def list_round_states(self):
        return [d["doc"] for d in self.db.rounds.find({}).sort("_id", 1)]

    def transition_round_state(self, aggregation, from_states, doc):
        # single-winner CAS: one atomic find_one_and_update filtered on
        # the FROM state — N sweeping workers race, exactly one matches
        found = self.db.rounds.find_one_and_update(
            {"_id": str(aggregation), "state": {"$in": list(from_states)}},
            {"$set": {"state": doc["state"], "doc": doc}},
        )
        return found is not None

    # -- recurring-round schedules -------------------------------------------
    def create_schedule_state(self, doc):
        # conditional insert via $setOnInsert upsert — Mongo's atomic
        # create-if-absent; installation is single-winner, so a booting
        # scheduler can never reset an advanced schedule
        result = self.db.schedules.update_one(
            {"_id": doc["schedule"]},
            {"$setOnInsert": {"_id": doc["schedule"],
                              "epoch": int(doc["epoch"]), "doc": doc}},
            upsert=True,
        )
        return result.upserted_id is not None

    def get_schedule_state(self, schedule):
        found = self.db.schedules.find_one({"_id": str(schedule)})
        return None if found is None else found["doc"]

    def list_schedule_states(self):
        return [d["doc"] for d in self.db.schedules.find({}).sort("_id", 1)]

    def transition_schedule_state(self, schedule, from_epoch, doc):
        # single-winner epoch CAS: one atomic find_one_and_update keyed
        # on the FROM epoch (same shape as transition_round_state)
        found = self.db.schedules.find_one_and_update(
            {"_id": str(schedule), "epoch": int(from_epoch)},
            {"$set": {"epoch": int(doc["epoch"]), "doc": doc}},
        )
        return found is not None

    def create_snapshot_mask(self, snapshot, mask):
        self.put_snapshot_mask_chunk(snapshot, 0, mask)
        self.trim_snapshot_mask_chunks(snapshot, 1)

    def put_snapshot_mask_chunk(self, snapshot, index, encryptions):
        # one document per chunk, _id "<snapshot>:<ix>", pure upsert: a
        # replaying or contended pipeline rewrites byte-identical chunks
        # (stores.py contract), so readers always see a complete mask.
        # Chunk 0 supersedes any legacy single-document mask.
        snap = str(snapshot)
        if index == 0:
            self.db.snapshot_masks.delete_many({"_id": snap})
        self.db.snapshot_mask_chunks.replace_one(
            {"_id": f"{snap}:{int(index):08d}"},
            {"_id": f"{snap}:{int(index):08d}", "snapshot": snap,
             "chunk_ix": int(index), "doc": [e.to_obj() for e in encryptions]},
            upsert=True,
        )

    def trim_snapshot_mask_chunks(self, snapshot, count):
        self.db.snapshot_mask_chunks.delete_many(
            {"snapshot": str(snapshot), "chunk_ix": {"$gte": int(count)}})

    def get_snapshot_mask(self, snapshot):
        chunks = list(self.db.snapshot_mask_chunks.find(
            {"snapshot": str(snapshot)}))
        if chunks:
            chunks.sort(key=lambda d: d.get("chunk_ix", 0))
            return [Encryption.from_obj(e) for c in chunks for e in c["doc"]]
        # pre-chunking database: fall back to the legacy single document
        doc = self.db.snapshot_masks.find_one({"_id": str(snapshot)})
        if doc is None:
            return None
        return [Encryption.from_obj(e) for e in doc["doc"]]


class MongoClerkingJobsStore(_MongoStore, ClerkingJobsStore):
    @staticmethod
    def _job_doc(job):
        return {
            "_id": str(job.id),
            "clerk": str(job.clerk),
            "snapshot": str(job.snapshot),
            "done": False,
            "doc": job.to_obj(),
        }

    def _enqueue_doc(self, payload):
        # refresh only a still-QUEUED job; a snapshot replay must never
        # resurrect a done job or wipe its embedded result
        res = self.db.clerking_jobs.replace_one(
            {"_id": payload["_id"], "done": False}, payload
        )
        if res.matched_count == 0:
            self.db.clerking_jobs.update_one(
                {"_id": payload["_id"]}, {"$setOnInsert": payload}, upsert=True
            )

    def enqueue_clerking_job(self, job):
        chaos.fail("store.enqueue_clerking_job")
        self._enqueue_doc(self._job_doc(job))

    def enqueue_clerking_jobs(self, jobs):
        # the snapshot fan-out in three round trips under the real driver
        # (refresh-queued bulk, existence probe, insert-missing bulk)
        # instead of 2C; same never-resurrect-done semantics per job
        jobs = list(jobs)
        if not jobs:
            return
        for _ in jobs:
            chaos.fail("store.enqueue_clerking_job")
        payloads = [self._job_doc(job) for job in jobs]
        if not _PYMONGO:
            for payload in payloads:
                self._enqueue_doc(payload)
            return
        self.db.clerking_jobs.bulk_write(
            [pymongo.ReplaceOne({"_id": p["_id"], "done": False}, p)
             for p in payloads],
            ordered=False,
        )
        existing = {
            d["_id"]
            for d in self.db.clerking_jobs.find(
                {"_id": {"$in": [p["_id"] for p in payloads]}},
                {"_id": 1})  # ids only: don't re-download the clerk columns
        }
        missing = [p for p in payloads if p["_id"] not in existing]
        if missing:
            self.db.clerking_jobs.bulk_write(
                [pymongo.UpdateOne({"_id": p["_id"]}, {"$setOnInsert": p},
                                   upsert=True)
                 for p in missing],
                ordered=False,
            )

    def poll_clerking_job(self, clerk):
        chaos.fail("store.poll_clerking_job")
        doc = self.db.clerking_jobs.find_one(
            {"clerk": str(clerk), "done": False}, sort=[("_id", 1)]
        )
        return None if doc is None else ClerkingJob.from_obj(doc["doc"])

    def lease_clerking_job(self, clerk, lease_seconds, now=None, owner=None):
        chaos.fail("store.poll_clerking_job")
        now = time.time() if now is None else now
        expires = now + lease_seconds
        doc = self.db.clerking_jobs.find_one_and_update(
            {
                "clerk": str(clerk),
                "done": False,
                "$or": [
                    {"leased_until": {"$exists": False}},
                    {"leased_until": {"$lte": now}},
                ],
            },
            {"$set": {"leased_until": expires, "leased_by": owner}},
            sort=[("_id", 1)],
        )
        if doc is None:
            return None
        if doc.get("leased_until") is not None:
            metrics.count("server.job.reissued")
        return ClerkingJob.from_obj(doc["doc"]), expires

    def release_clerking_job_lease(self, clerk, job, expires=None):
        # graceful drain: zero the visibility timeout on a still-undone
        # job so any process's next lease poll picks it up immediately.
        # Compare-and-release: with `expires` only the exact granted
        # lease matches — a reissued lease (new leased_until) is the
        # peer's to keep
        result = self.db.clerking_jobs.update_one(
            {"_id": str(job), "clerk": str(clerk), "done": False,
             "leased_until": {"$gt": 0} if expires is None else expires},
            {"$set": {"leased_until": 0, "leased_by": None}},
        )
        return result.matched_count > 0

    def recall_clerking_job_leases(self, node_id):
        # the dead-node recovery step: one bulk conditional update drops
        # every active lease the dead worker granted
        result = self.db.clerking_jobs.update_many(
            {"leased_by": str(node_id), "done": False,
             "leased_until": {"$gt": 0}},
            {"$set": {"leased_until": 0, "leased_by": None}},
        )
        return int(getattr(result, "modified_count", None)
                   or getattr(result, "matched_count", 0) or 0)

    def hedge_clerking_job(self, clerk, suspect_nodes, lease_seconds,
                           now=None, owner=None):
        # hedged execution: one atomic find_one_and_update re-grants a
        # SUSPECT holder's active lease to this caller (two hedgers race,
        # the filter matches exactly once); result commit stays
        # single-winner on the done flag
        suspects = [str(n) for n in suspect_nodes]
        if not suspects:
            return None
        now = time.time() if now is None else now
        expires = now + lease_seconds
        doc = self.db.clerking_jobs.find_one_and_update(
            {"clerk": str(clerk), "done": False,
             "leased_until": {"$gt": now},
             "leased_by": {"$in": suspects}},
            {"$set": {"leased_until": expires, "leased_by": owner}},
            sort=[("_id", 1)],
        )
        if doc is None:
            return None
        return ClerkingJob.from_obj(doc["doc"]), expires

    # -- fleet heartbeats ---------------------------------------------------
    def put_worker_heartbeat(self, doc):
        self.db.worker_heartbeats.replace_one(
            {"_id": doc["node"]},
            {"_id": doc["node"], "state": doc["state"], "doc": doc},
            upsert=True,
        )

    def get_worker_heartbeat(self, node):
        found = self.db.worker_heartbeats.find_one({"_id": str(node)})
        return None if found is None else found["doc"]

    def list_worker_heartbeats(self):
        return [d["doc"]
                for d in self.db.worker_heartbeats.find({}).sort("_id", 1)]

    def transition_worker_state(self, node, from_states, doc):
        # single-winner CAS: one atomic find_one_and_update filtered on
        # the FROM state (same shape as transition_round_state)
        found = self.db.worker_heartbeats.find_one_and_update(
            {"_id": str(node), "state": {"$in": list(from_states)}},
            {"$set": {"state": doc["state"], "doc": doc}},
        )
        return found is not None

    def list_snapshot_jobs(self, snapshot):
        # the sweeper's dead-clerk census: only the queue metadata fields
        # are decoded (the embedded payload/result docs stay untouched)
        out = []
        for d in self.db.clerking_jobs.find(
                {"snapshot": str(snapshot)}).sort("_id", 1):
            out.append((
                ClerkingJobId(d["_id"]),
                AgentId(d["clerk"]),
                bool(d.get("done")),
                float(d.get("leased_until") or 0.0),
            ))
        return out

    def get_clerking_job(self, clerk, job):
        doc = self.db.clerking_jobs.find_one({"_id": str(job), "clerk": str(clerk)})
        return None if doc is None else ClerkingJob.from_obj(doc["doc"])

    def create_clerking_result(self, result):
        chaos.fail("store.create_clerking_result")
        # ONE atomic single-document update sets the result and flips done —
        # a crash can never consume the job without storing the result (the
        # reference's clerking_jobs.rs create_clerking_result does the same
        # single $set; the round-1 two-write version lost the result if it
        # died between the flip and the separate results-collection insert)
        doc = self.db.clerking_jobs.find_one_and_update(
            {"_id": str(result.job), "clerk": str(result.clerk), "done": False},
            {"$set": {"done": True, "result": result.to_obj()}},
        )
        if doc is None:
            already = self.db.clerking_jobs.find_one(
                {"_id": str(result.job), "clerk": str(result.clerk)}
            )
            if already is not None and already.get("done"):
                return  # duplicate result upload: idempotent
            raise NotFound("job not found for clerk")

    def purge_snapshot_jobs(self, snapshot):
        # the retention/delete cascade's job-store half: job docs carry
        # their result embedded (post-atomic-fix schema), so one
        # delete_many covers jobs + leases + results; the legacy results
        # collection is swept for pre-fix data
        jobs = self.db.clerking_jobs.delete_many(
            {"snapshot": str(snapshot)})
        legacy = self.db.clerking_results.delete_many(
            {"snapshot": str(snapshot)})
        return (int(getattr(jobs, "deleted_count", 0) or 0)
                + int(getattr(legacy, "deleted_count", 0) or 0))

    def list_results(self, snapshot):
        ids = {
            d["_id"]
            for d in self.db.clerking_jobs.find(
                {"snapshot": str(snapshot), "done": True,
                 "result": {"$exists": True}})
        }
        # legacy schema (pre-atomic fix): result in its own collection
        ids.update(
            d["_id"]
            for d in self.db.clerking_results.find({"snapshot": str(snapshot)})
        )
        return [ClerkingJobId(i) for i in sorted(ids)]

    def get_result(self, snapshot, job):
        doc = self.db.clerking_jobs.find_one(
            {"_id": str(job), "snapshot": str(snapshot),
             "result": {"$exists": True}}
        )
        if doc is not None:
            return ClerkingResult.from_obj(doc["result"])
        legacy = self.db.clerking_results.find_one(
            {"_id": str(job), "snapshot": str(snapshot)}
        )
        return None if legacy is None else ClerkingResult.from_obj(legacy["doc"])
