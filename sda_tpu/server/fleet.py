"""Fleet launcher: N stateless ``sdad`` worker processes, one shared store.

The SDA server is an untrusted broker + job scheduler over durable stores
(PAPER.md: ``server/src/snapshot.rs`` merely transposes participations
into per-clerk jobs), so nothing in the protocol requires a single
process. This module turns that property into an operational shape: spawn
N real OS processes, each a full ``sdad`` (``sda_tpu/cli/serverd.py``),
all pointed at ONE shared backend — a WAL-mode sqlite file, a jsonfs
directory, or a MongoDB URI. Correctness under contention does not live
here: it lives in the store layer's contended-idempotency contract
(``stores.py``: single-winner ``create_snapshot`` /
``snapshot_participations``, lease-arbitrated job pickup), which this
launcher merely exercises. Any worker can serve any request; the
consistent-hash ring (``routing.py``) only concentrates affinity.

Lifecycle contract with the worker CLI:

- startup: the worker prints ``sdad listening on http://host:port`` as its
  first stdout line; the launcher parses it for the bound address (port 0
  binds are ephemeral, so the line is the only source of truth).
- shutdown: the launcher sends SIGTERM; the worker drains (stop accepting,
  finish in-flight, release held clerking-job leases back to the shared
  store) and prints ``sdad drained {json}`` as its last stdout line. The
  summary's ``leaked`` must be 0 — a leaked handler thread means a request
  was abandoned mid-flight.

This is also the engine under ``sda-fleet`` (the operator CLI) and the
loadgen driver's ``--fleet N`` mode (docs/scaling.md).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .routing import DEFAULT_REPLICAS, HashRing

log = logging.getLogger(__name__)

LISTEN_PREFIX = "sdad listening on "
DRAIN_PREFIX = "sdad drained "

#: Stdout/stderr lines retained per worker for post-mortems.
_LOG_LINES = 200


def merge_statusz_block(docs, block: str) -> Dict[str, int]:
    """Sum one counter block (``"participation"``, ``"codec_counters"``,
    ...) across worker ``/statusz`` documents. Counters are per-process,
    so the fleet-wide tally is the sum of the workers' — the shared merge
    under every drill's exactly-once and codec verdicts."""
    merged: Dict[str, int] = {}
    for doc in docs:
        for name, count in ((doc or {}).get(block) or {}).items():
            merged[name] = merged.get(name, 0) + count
    return merged


@dataclass
class FleetWorker:
    """One spawned ``sdad`` process and what the launcher learned about it."""

    node_id: str
    command: List[str]
    process: Optional[subprocess.Popen] = None
    address: Optional[str] = None
    drain_summary: Optional[dict] = None
    returncode: Optional[int] = None
    log: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=_LOG_LINES))
    _ready: threading.Event = field(default_factory=threading.Event)
    _pump: Optional[threading.Thread] = None

    def to_obj(self) -> dict:
        return {
            "node_id": self.node_id,
            "address": self.address,
            "pid": self.process.pid if self.process else None,
        }


class Fleet:
    """Spawn, address, and drain N ``sdad`` workers over one backend.

    ``backend_args`` selects the SHARED store exactly as on the ``sdad``
    command line (``["--sqlite", path]`` / ``["--jfs", dir]`` /
    ``["--mongo", uri]``); ``extra_args`` is appended verbatim to every
    worker (lease, admission, chaos, observability flags). ``base_port``
    0 gives every worker an ephemeral port (the default — the listen line
    reports it); a nonzero base gives worker *i* ``base_port + i``.

    Context-manager friendly: ``with Fleet(...) as fleet:`` starts the
    workers and drains them on exit.
    """

    def __init__(
        self,
        n: int,
        backend_args: Sequence[str],
        *,
        extra_args: Sequence[str] = (),
        node_prefix: str = "w",
        host: str = "127.0.0.1",
        base_port: int = 0,
        replicas: int = DEFAULT_REPLICAS,
        env: Optional[dict] = None,
    ):
        if n < 1:
            raise ValueError("a fleet needs at least one worker")
        if "--memory" in backend_args:
            raise ValueError(
                "--memory cannot back a fleet: each process would get its "
                "own isolated store; use --sqlite/--jfs/--mongo")
        self.replicas = replicas
        self.env = env
        self.workers: List[FleetWorker] = []
        for i in range(n):
            node_id = f"{node_prefix}{i}"
            port = 0 if base_port == 0 else base_port + i
            command = [
                sys.executable, "-m", "sda_tpu.cli.serverd",
                *backend_args,
                "--node-id", node_id,
                "--fleet-peers", str(n),
                *extra_args,
                "httpd", "--bind", f"{host}:{port}",
            ]
            self.workers.append(FleetWorker(node_id=node_id, command=command))

    # -- lifecycle ---------------------------------------------------------
    def _pump_output(self, worker: FleetWorker) -> None:
        """Reader thread: parse the two protocol lines (listen, drain),
        retain the rest for post-mortems, never let the pipe fill."""
        assert worker.process is not None and worker.process.stdout is not None
        for line in worker.process.stdout:
            line = line.rstrip("\n")
            worker.log.append(line)
            if worker.address is None and line.startswith(LISTEN_PREFIX):
                worker.address = line[len(LISTEN_PREFIX):].strip()
                worker._ready.set()
            elif line.startswith(DRAIN_PREFIX):
                try:
                    worker.drain_summary = json.loads(line[len(DRAIN_PREFIX):])
                except ValueError:
                    log.warning("%s: unparseable drain line: %s",
                                worker.node_id, line)
        worker._ready.set()  # EOF: unblock start() so it can report death

    def start(self, timeout_s: float = 60.0) -> "Fleet":
        """Spawn every worker and wait until all report their address."""
        env = dict(os.environ if self.env is None else self.env)
        # workers must import sda_tpu exactly as this process does, even
        # when the package is run from a source tree instead of installed
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        # the fleet plane measures the transport/store tier; keep worker
        # startup light and deterministic on any host
        env.setdefault("JAX_PLATFORMS", "cpu")
        for worker in self.workers:
            # stderr folded into stdout: worker tracebacks land in the
            # retained log instead of interleaving on the launcher's tty
            worker.process = subprocess.Popen(
                worker.command, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env,
            )
            worker._pump = threading.Thread(
                target=self._pump_output, args=(worker,), daemon=True)
            worker._pump.start()
        deadline = time.monotonic() + timeout_s
        for worker in self.workers:
            worker._ready.wait(max(0.0, deadline - time.monotonic()))
            if worker.address is None:
                tail = "\n".join(list(worker.log)[-20:])
                self.stop(timeout_s=5.0)
                raise RuntimeError(
                    f"fleet worker {worker.node_id} did not report an "
                    f"address within {timeout_s}s; last output:\n{tail}")
        log.info("fleet up: %s",
                 {w.node_id: w.address for w in self.workers})
        return self

    def kill(self, node_id: str) -> FleetWorker:
        """SIGKILL one worker — no drain, no lease handback, no drained
        line: the ungraceful death the gray-failure plane exists for.
        The worker's heartbeat goes stale, a peer's failure detector
        declares it dead and recalls its held leases
        (``server/health.py``); this method only delivers the blow."""
        worker = next((w for w in self.workers if w.node_id == node_id),
                      None)
        if worker is None:
            raise ValueError(f"no fleet worker named {node_id!r}")
        if worker.process is not None and worker.process.poll() is None:
            worker.process.kill()
            worker.process.wait()
        if worker._pump is not None:
            worker._pump.join(timeout=5.0)
        worker.returncode = (worker.process.returncode
                             if worker.process is not None else None)
        log.warning("fleet worker %s SIGKILLed (no drain)", node_id)
        return worker

    def stop(self, timeout_s: float = 30.0) -> List[dict]:
        """SIGTERM every worker (graceful drain), reap, return the drain
        summaries. Stragglers past the timeout are SIGKILLed and reported
        with ``{"killed": True}`` — a killed worker never drained, so its
        leases ride out the visibility timeout instead."""
        for worker in self.workers:
            if worker.process is not None and worker.process.poll() is None:
                try:
                    worker.process.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + timeout_s
        summaries = []
        for worker in self.workers:
            if worker.process is None:
                continue
            try:
                worker.process.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                log.warning("%s: did not drain in time; killing",
                            worker.node_id)
                worker.process.kill()
                worker.process.wait()
            if worker._pump is not None:
                worker._pump.join(timeout=5.0)
            worker.returncode = worker.process.returncode
            summaries.append(worker.drain_summary
                             or {"node_id": worker.node_id, "killed": True})
        return summaries

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- topology ----------------------------------------------------------
    @property
    def node_ids(self) -> List[str]:
        return [w.node_id for w in self.workers]

    @property
    def addresses(self) -> Dict[str, str]:
        """``{node_id: http://host:port}`` for every started worker."""
        return {w.node_id: w.address for w in self.workers
                if w.address is not None}

    def ring(self) -> HashRing:
        """The fleet's consistent-hash ring — every client/worker/launcher
        computes the same mapping from the same node list, so routing
        needs no coordination service (routing.py)."""
        return HashRing(self.node_ids, replicas=self.replicas)

    def scrape_statusz(self, timeout_s: float = 10.0) -> Dict[str, dict]:
        """Best-effort ``/statusz`` scrape of every addressable worker —
        ``{node_id: doc}``, unreachable workers silently omitted. Worker
        counters (exactly-once ingestion tallies, codec traffic, armed
        failpoints) live in THEIR processes and die on drain, so drills
        must scrape before ``stop()``; this is the one implementation the
        load/soak/FL drills share."""
        import requests

        docs: Dict[str, dict] = {}
        for node, address in self.addresses.items():
            try:
                docs[node] = requests.get(address + "/statusz",
                                          timeout=timeout_s).json()
            except Exception:
                continue
        return docs

    def to_obj(self) -> dict:
        return {"workers": [w.to_obj() for w in self.workers]}
