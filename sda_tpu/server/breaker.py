"""Store circuit breaker + retry budget: brownout survival for the
serving plane.

A browning-out backend (elevated latency + elevated error rate — the
``brownout``/``flap`` chaos kinds, and the dominant production failure
mode per "The Tail at Scale", Dean & Barroso, CACM 2013) is worse than a
dead one: every request still pays the full latency *and* fails, threads
pile up behind the slow dependency, and the retrying clients multiply the
load exactly when the store can least afford it. The classic remedy is a
circuit breaker (Nygard, *Release It!*) plus a bounded retry budget:

- **closed** (healthy): operations pass through; a failed operation may
  be retried once immediately IF the shared retry-budget token bucket has
  a token (bounds the fleet-wide retry amplification to ``budget_rate``
  extra store calls/sec no matter how hard the backend is failing);
  ``threshold`` failures within the rolling ``failure_window_s`` trip the
  breaker. Windowed, not consecutive, on purpose: a browning-out store
  FAILS GRAY — some ops keep succeeding between the failures — and a
  consecutive counter would never fire exactly when the breaker matters
  most.
- **open**: every operation is shed instantly with
  :class:`~sda_tpu.protocol.StoreUnavailable` carrying ``retry_after`` =
  the time until the next probe — the HTTP seam maps it to
  ``503 + Retry-After``, so clients back off instead of queueing, and
  reads keep flowing from the client-side immutable-document cache.
- **half-open** (after ``recovery_s``): exactly ONE probe operation is
  let through; success closes the breaker, failure re-opens it for
  another ``recovery_s``.

Wiring is opt-in (``sdad --store-breaker``): :func:`wrap_server_stores`
replaces a server's four store handles with :class:`BreakerStore`
proxies sharing ONE breaker (one backend, one health verdict). Semantic
errors — NotFound, InvalidRequest, auth failures — pass through
uncounted: they are answers, not infrastructure failures.

Observability: ``server.store.breaker.state`` gauge (0 closed, 1
half-open, 2 open), ``server.store.breaker.{open,close,reopen,shed,
failure,retry,probe}`` counters, and a span event per transition so
round timelines show exactly when the breaker tripped. ``report()``
feeds the chaos drill's ``time_to_recover_s`` MTTR record (ci.sh
brownout step, gated advisory by ``sda-bench --check``).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import obs
from ..utils import metrics
from ..protocol import (
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    ParticipationConflict,
    PermissionDenied,
    StoreUnavailable,
)

#: Exception types that are protocol ANSWERS, not store failures — they
#: pass through the breaker uncounted and unretried (a rejected
#: equivocation is detection WORKING; a flood of equivocators must not
#: trip the breaker).
SEMANTIC_ERRORS = (NotFound, InvalidRequest, ParticipationConflict,
                   PermissionDenied, InvalidCredentials, StoreUnavailable)

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Shared breaker state for one storage backend (thread-safe)."""

    def __init__(self, *, threshold: int = 5, recovery_s: float = 1.0,
                 failure_window_s: float = 10.0,
                 budget_rate: float = 2.0, budget_cap: float = 4.0):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = int(threshold)
        self.recovery_s = float(recovery_s)
        self.failure_window_s = float(failure_window_s)
        self.budget_rate = float(budget_rate)
        self.budget_cap = float(budget_cap)
        self._lock = threading.Lock()
        self.state = CLOSED
        self._failures: list = []   # failure instants inside the window
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        self._probe_started_at = 0.0
        # retry budget: token bucket shared by every wrapped store op
        self._tokens = float(budget_cap)
        self._tokens_at = time.monotonic()
        # MTTR bookkeeping for the drill record
        self.first_opened_at: Optional[float] = None
        self.last_closed_at: Optional[float] = None
        self.times_opened = 0
        metrics.gauge_set("server.store.breaker.state", 0)

    # -- state transitions (caller holds the lock) --------------------------
    def _to(self, state: str, counter: str) -> None:
        self.state = state
        metrics.gauge_set("server.store.breaker.state", _STATE_GAUGE[state])
        metrics.count(f"server.store.breaker.{counter}")
        obs.add_event(f"store.breaker.{counter}", state=state)

    def _open(self, now: float, counter: str) -> None:
        self._opened_at = now
        self._probe_inflight = False
        if self.first_opened_at is None:
            self.first_opened_at = now
        self.times_opened += 1
        self._to(OPEN, counter)

    # -- the wrap-side API --------------------------------------------------
    def admit(self, op: str) -> bool:
        """Gate one store operation. Returns True when the call is the
        half-open PROBE (its outcome decides the breaker), raises
        ``StoreUnavailable`` when shed, False for a plain closed-state
        pass-through."""
        now = time.monotonic()
        with self._lock:
            if self.state == CLOSED:
                return False
            if self.state == OPEN:
                remaining = self._opened_at + self.recovery_s - now
                if remaining > 0:
                    metrics.count("server.store.breaker.shed")
                    raise StoreUnavailable(
                        f"store breaker open ({op} shed); retrying in "
                        f"{remaining:.3f}s", retry_after=max(0.01, remaining))
                self._to(HALF_OPEN, "half_open")
            # half-open: exactly one probe at a time; everyone else sheds
            # with a hint sized to the probe's likely round trip. A probe
            # stuck longer than a recovery period (hung flock, NFS stall —
            # elevated latency IS the failure mode in play) forfeits its
            # slot, or the breaker would wedge shedding forever
            probe_patience = max(self.recovery_s, 5.0)
            if self._probe_inflight \
                    and now - self._probe_started_at < probe_patience:
                metrics.count("server.store.breaker.shed")
                raise StoreUnavailable(
                    f"store breaker half-open ({op} shed while probing)",
                    retry_after=max(0.01, self.recovery_s / 4))
            self._probe_inflight = True
            self._probe_started_at = now
            metrics.count("server.store.breaker.probe")
            return True

    def record_success(self, probe: bool) -> None:
        with self._lock:
            if probe and self.state == HALF_OPEN:
                self._probe_inflight = False
                self._failures.clear()
                self.last_closed_at = time.monotonic()
                self._to(CLOSED, "close")

    def record_failure(self, probe: bool) -> None:
        now = time.monotonic()
        with self._lock:
            metrics.count("server.store.breaker.failure")
            if probe and self.state == HALF_OPEN:
                self._open(now, "reopen")  # the probe failed: back off again
                return
            if self.state != CLOSED:
                return
            # rolling window, NOT a consecutive counter: a gray store
            # keeps succeeding between failures, and those successes must
            # not launder the failure rate
            cutoff = now - self.failure_window_s
            self._failures = [t for t in self._failures if t > cutoff]
            self._failures.append(now)
            if len(self._failures) >= self.threshold:
                self._failures.clear()
                self._open(now, "open")

    def try_spend_retry(self) -> bool:
        """One token from the shared retry budget, or False — the bound on
        fleet-wide retry amplification while the backend struggles."""
        now = time.monotonic()
        with self._lock:
            self._tokens = min(
                self.budget_cap,
                self._tokens + (now - self._tokens_at) * self.budget_rate)
            self._tokens_at = now
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            return True

    def report(self) -> dict:
        """Drill/statusz snapshot; ``time_to_recover_s`` is the wall time
        from the FIRST trip to the LAST recovery — the MTTR headline the
        brownout drill records."""
        with self._lock:
            recover = None
            if self.first_opened_at is not None \
                    and self.last_closed_at is not None \
                    and self.last_closed_at > self.first_opened_at:
                recover = round(self.last_closed_at - self.first_opened_at, 4)
            return {
                "state": self.state,
                "threshold": self.threshold,
                "recovery_s": self.recovery_s,
                "times_opened": self.times_opened,
                "time_to_recover_s": recover,
            }


class BreakerStore:
    """Proxy one store handle through a shared :class:`CircuitBreaker`.

    Every public method call is gated by ``admit`` (shed fast while
    open), counted into the breaker on infrastructure failure, and — in
    the closed state — retried once when the shared retry budget allows
    (safe: every store operation in this codebase is an idempotent upsert
    / conditional insert by the retry contract in docs/robustness.md).
    """

    def __init__(self, inner, breaker: CircuitBreaker):
        # object.__setattr__: __getattr__ below must never recurse
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_breaker", breaker)
        object.__setattr__(self, "_wrapped", {})

    def __getattr__(self, name):
        if name.startswith("_"):
            return getattr(self._inner, name)
        cached = self._wrapped.get(name)
        if cached is not None:
            return cached
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr
        breaker = self._breaker

        def guarded(*args, **kwargs):
            probe = breaker.admit(name)  # raises StoreUnavailable when open
            try:
                result = attr(*args, **kwargs)
            except SEMANTIC_ERRORS:
                # a protocol answer, not a store failure: the probe (if
                # any) reached the backend and got a coherent reply
                breaker.record_success(probe)
                raise
            except Exception:
                if not probe and breaker.try_spend_retry():
                    metrics.count("server.store.breaker.retry")
                    try:
                        result = attr(*args, **kwargs)
                    except SEMANTIC_ERRORS:
                        breaker.record_success(probe)
                        raise
                    except Exception:
                        breaker.record_failure(probe)
                        raise
                    breaker.record_success(probe)
                    return result
                breaker.record_failure(probe)
                raise
            except BaseException:
                # KeyboardInterrupt/SystemExit tearing through a probe
                # must still release the probe slot — count it failed
                # (conservative: the breaker reopens) rather than wedge
                breaker.record_failure(probe)
                raise
            breaker.record_success(probe)
            return result

        self._wrapped[name] = guarded
        return guarded


def wrap_server_stores(server, breaker: Optional[CircuitBreaker] = None
                       ) -> CircuitBreaker:
    """Route all four of ``server``'s store handles through one shared
    breaker (they are one backend — one sqlite file, one jsonfs root, one
    Mongo database — so they share one health verdict). Returns the
    breaker for drills/statusz to read."""
    breaker = breaker or CircuitBreaker()
    server.agents_store = BreakerStore(server.agents_store, breaker)
    server.auth_tokens_store = BreakerStore(server.auth_tokens_store, breaker)
    server.aggregation_store = BreakerStore(server.aggregation_store, breaker)
    server.clerking_job_store = BreakerStore(server.clerking_job_store,
                                             breaker)
    server.store_breaker = breaker
    return breaker
