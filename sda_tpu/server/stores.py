"""Storage interfaces for the server core.

Mirrors reference: server/src/stores.rs — four store traits behind which the
server is a thin delegation layer, so backends (memory, JSON-files, real
databases) are swappable. The snapshot *transpose* — turning N participations
x C clerks into C per-clerk job payloads — has a default implementation here
(stores.rs:86-101), which concrete stores may override with something
smarter (the reference's Mongo store uses an aggregation pipeline;
server-store-mongodb/src/aggregations.rs:164-195).
"""

from __future__ import annotations

import abc
import time
from typing import Iterable, List, Optional, Tuple

from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    Encryption,
    EncryptionKeyId,
    Labelled,
    Participation,
    Profile,
    Signed,
    Snapshot,
    SnapshotId,
)

#: Auth token: an agent id labelled with its secret token string
#: (stores.rs:7 ``AuthToken = Labelled<AgentId, String>``).
AuthToken = Labelled


def auth_token(id: AgentId, body: str) -> AuthToken:
    return Labelled(id, body)


class BaseStore(abc.ABC):
    @abc.abstractmethod
    def ping(self) -> None:
        """Raise if the backend is unhealthy."""


class AuthTokensStore(BaseStore):
    @abc.abstractmethod
    def upsert_auth_token(self, token: AuthToken) -> None: ...

    @abc.abstractmethod
    def get_auth_token(self, id: AgentId) -> Optional[AuthToken]: ...

    @abc.abstractmethod
    def delete_auth_token(self, id: AgentId) -> None: ...


class AgentsStore(BaseStore):
    @abc.abstractmethod
    def create_agent(self, agent: Agent) -> None: ...

    @abc.abstractmethod
    def get_agent(self, id: AgentId) -> Optional[Agent]: ...

    @abc.abstractmethod
    def upsert_profile(self, profile: Profile) -> None: ...

    @abc.abstractmethod
    def get_profile(self, owner: AgentId) -> Optional[Profile]: ...

    @abc.abstractmethod
    def create_encryption_key(self, key: Signed) -> None: ...

    @abc.abstractmethod
    def get_encryption_key(self, key: EncryptionKeyId) -> Optional[Signed]: ...

    @abc.abstractmethod
    def suggest_committee(self) -> List[ClerkCandidate]:
        """All agents owning encryption keys, sorted by agent id, with their
        keys — the (temporary, like the reference's) committee heuristic
        (jfs_stores/agents.rs:66-83)."""


class AggregationsStore(BaseStore):
    @abc.abstractmethod
    def list_aggregations(
        self, filter: Optional[str] = None, recipient: Optional[AgentId] = None
    ) -> List[AggregationId]: ...

    @abc.abstractmethod
    def create_aggregation(self, aggregation: Aggregation) -> None: ...

    @abc.abstractmethod
    def get_aggregation(self, aggregation: AggregationId) -> Optional[Aggregation]: ...

    @abc.abstractmethod
    def delete_aggregation(self, aggregation: AggregationId) -> None: ...

    @abc.abstractmethod
    def get_committee(self, aggregation: AggregationId) -> Optional[Committee]: ...

    @abc.abstractmethod
    def create_committee(self, committee: Committee) -> None: ...

    @abc.abstractmethod
    def create_participation(self, participation: Participation) -> bool:
        """Exactly-once ingestion: a single-winner conditional insert
        keyed by ``(aggregation, participant)`` with the participation's
        canonical content digest stored alongside (the same
        contended-idempotency discipline as ``create_snapshot``, arbitrated
        at the store so it holds across competing server processes).

        - fresh key: insert, return True (this call created it);
        - byte-identical replay (same key, same digest — a crash/retry or
          journal resume re-uploading the SAME sealed bytes): change
          nothing, return False (idempotent success);
        - same key, different digest (a device that recomputed with fresh
          randomness under a new participation id, or an equivocator
          submitting a second input), or an existing participation id
          being re-uploaded with different content: raise
          ``ParticipationConflict`` — never silently replace.

        Post-freeze arrivals are NOT this method's concern: they insert
        normally and the frozen id set keeps them out of the running
        round (``snapshot_participations``)."""

    @abc.abstractmethod
    def create_snapshot(self, snapshot: Snapshot) -> bool:
        """Conditional insert: record the snapshot iff no record with its
        id exists yet, and return whether THIS call created it. The
        record is the snapshot pipeline's commit point, so the insert
        must be single-winner even across competing server processes
        (contended-idempotency contract, docs/scaling.md): the loser's
        pipeline has already upserted the exact same deterministic job
        set, so losing is convergence, not failure. Never overwrites."""

    @abc.abstractmethod
    def list_snapshots(self, aggregation: AggregationId) -> List[SnapshotId]: ...

    @abc.abstractmethod
    def get_snapshot(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> Optional[Snapshot]: ...

    @abc.abstractmethod
    def count_participations(self, aggregation: AggregationId) -> int: ...

    @abc.abstractmethod
    def snapshot_participations(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> bool:
        """Freeze the current participation set under the snapshot id — the
        consistency point that keeps late arrivals out of a running round.

        Single-winner across competing server processes: the freeze
        marker and the frozen id set commit ATOMICALLY, exactly once.
        Returns True when this call performed the freeze, False when a
        concurrent (or earlier crashed) attempt already did — in which
        case the caller must proceed with the WINNER'S frozen set, which
        is guaranteed readable the moment this returns False. Two
        processes must never install different frozen sets for one
        snapshot id: that would mix share generations across clerk
        columns (docs/scaling.md, contended-idempotency contract)."""

    def has_snapshot_freeze(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> bool:
        """Whether ``snapshot_participations`` already ran for this
        snapshot. The snapshot pipeline's first-write-wins replay guard:
        a frozen-but-EMPTY set must read as frozen, or a crash-replay
        with a late participation would re-freeze a superset. Backends
        should override with a durable marker; this fallback (count > 0)
        cannot tell frozen-empty from unfrozen."""
        return self.count_participations_snapshot(aggregation, snapshot) > 0

    @abc.abstractmethod
    def iter_snapped_participations(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> Iterable[Participation]: ...

    def count_participations_snapshot(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> int:
        return sum(1 for _ in self.iter_snapped_participations(aggregation, snapshot))

    def iter_snapshot_clerk_jobs_data(
        self, aggregation: AggregationId, snapshot: SnapshotId, clerks_number: int
    ) -> List[List[Encryption]]:
        """THE server-side transpose (stores.rs:86-101): participation rows ->
        per-clerk encryption columns, positionally by committee index."""
        columns: List[List[Encryption]] = [[] for _ in range(clerks_number)]
        for participation in self.iter_snapped_participations(aggregation, snapshot):
            for ix, (_, encryption) in enumerate(participation.clerk_encryptions):
                columns[ix].append(encryption)
        return columns

    def iter_snapped_recipient_encryptions(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> List[Optional[Encryption]]:
        """The recipient-mask column of the frozen set, in participation
        order (``None`` where a participation carried no mask). Backends
        that store documents can extract just this field instead of
        re-materializing every full participation a second time."""
        return [
            p.recipient_encryption
            for p in self.iter_snapped_participations(aggregation, snapshot)
        ]

    def iter_snapped_forwarded_masks(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> Iterable[Encryption]:
        """Flattened ``forwarded_masks`` ciphertexts of the frozen set, in
        participation order — the leaf-mask ciphertexts tree relays carry
        upward in-band (``Participation.forwarded_masks``). Empty for
        flat rounds; the snapshot pipeline only walks this for tree
        parents, so the full-document fallback below costs nothing
        elsewhere."""
        for p in self.iter_snapped_participations(aggregation, snapshot):
            for encryption in (p.forwarded_masks or ()):
                yield encryption

    @abc.abstractmethod
    def create_snapshot_mask(
        self, snapshot: SnapshotId, mask: List[Encryption]
    ) -> None: ...

    def put_snapshot_mask_chunk(
        self, snapshot: SnapshotId, index: int, encryptions: List[Encryption]
    ) -> None:
        """Chunked snapshot-mask write — the O(batch) half of the
        streamed mask collection (``server/snapshot.py``): the pipeline
        writes the recipient-mask column as bounded chunks keyed by
        ``(snapshot, chunk index)`` instead of materializing the whole
        list in memory first. Contract:

        - chunks are pure upserts keyed by index — NEVER a wipe. The
          chunk stream is deterministic from the frozen set (single-
          winner across the fleet) and the batch size, so a crash-replay
          or a contended peer rewrites byte-identical chunks: any
          interleaving converges bit-exactly, and a reader that already
          holds the committed snapshot record always sees a COMPLETE
          mask (the atomicity the old single-row write had). This
          REQUIRES every fleet worker to chunk at the same batch size
          (``SDA_SNAPSHOT_MASK_BATCH``) — like every per-worker protocol
          knob (lease seconds, deadlines, premix), it must be uniform
          across the fleet: writers chunking one snapshot at different
          boundaries cannot converge under concurrency, trim or no trim;
        - ``trim_snapshot_mask_chunks`` finishes the stream, dropping
          chunks past the end (a leftover from an attempt chunked with a
          different batch size);
        - ``get_snapshot_mask`` returns the concatenation in index order.

        The four in-repo backends override with durable chunk rows; this
        read-modify-write fallback keeps third-party stores working (NOT
        fleet-safe, like the round-state fallbacks above)."""
        if index == 0:
            self.create_snapshot_mask(snapshot, list(encryptions))
            return
        existing = self.get_snapshot_mask(snapshot) or []
        self.create_snapshot_mask(snapshot, existing + list(encryptions))

    def trim_snapshot_mask_chunks(
        self, snapshot: SnapshotId, count: int
    ) -> None:
        """Drop mask chunks with index >= ``count`` — the end-of-stream
        marker of the chunked mask write above. A no-op everywhere
        except after an attempt that chunked the same snapshot with a
        LARGER batch size (fewer chunks) than a crashed predecessor.
        Backends with durable chunk rows override; the fallback's
        whole-list writes never leave excess chunks."""
        return None

    @abc.abstractmethod
    def get_snapshot_mask(self, snapshot: SnapshotId) -> Optional[List[Encryption]]: ...

    # -- recurring-round schedules (service/scheduler.py) -------------------
    # One document per ScheduleSpec, keyed by ``doc["schedule"]`` and
    # carrying the schedule's current epoch number. The scheduler plane
    # uses two conditional writes — create-if-absent installation and an
    # epoch-keyed CAS advance — so a fleet of schedulers mints each epoch
    # exactly once (the same single-winner discipline as
    # ``transition_round_state``). The four in-repo backends override
    # with durable, contended-safe implementations; the base fallbacks
    # keep third-party stores working (in-memory, NOT crash- or
    # fleet-safe).

    def _fallback_schedules(self) -> dict:
        schedules = getattr(self, "_base_schedules", None)
        if schedules is None:
            schedules = self._base_schedules = {}
        return schedules

    def create_schedule_state(self, doc: dict) -> bool:
        """Conditional insert: record the schedule document iff none with
        its ``doc["schedule"]`` name exists yet; returns whether THIS
        call installed it. Installation must be single-winner so a fleet
        of schedulers booting against one shared store cannot reset a
        schedule that already advanced past epoch 0."""
        schedules = self._fallback_schedules()
        if doc["schedule"] in schedules:
            return False
        schedules[doc["schedule"]] = dict(doc)
        return True

    def get_schedule_state(self, schedule: str) -> Optional[dict]:
        doc = self._fallback_schedules().get(str(schedule))
        return None if doc is None else dict(doc)

    def list_schedule_states(self) -> List[dict]:
        return [dict(d) for d in self._fallback_schedules().values()]

    def transition_schedule_state(
        self, schedule: str, from_epoch: int, doc: dict
    ) -> bool:
        """Single-winner epoch advance: install ``doc`` iff the stored
        document's current ``epoch`` equals ``from_epoch``. N racing
        scheduler workers CAS epoch e -> e+1; exactly one wins and mints
        the epoch's aggregation, the losers observe the winner's advance
        and converge (service/scheduler.py)."""
        schedules = self._fallback_schedules()
        current = schedules.get(str(schedule))
        if current is None or int(current.get("epoch", -1)) != int(from_epoch):
            return False
        schedules[str(schedule)] = dict(doc)
        return True

    # -- round lifecycle (server/lifecycle.py) ------------------------------
    # The four in-repo backends override all of these with durable,
    # contended-safe implementations; the base fallbacks below keep
    # third-party stores working (in-memory, NOT crash- or fleet-safe).

    def _fallback_rounds(self) -> dict:
        rounds = getattr(self, "_base_rounds", None)
        if rounds is None:
            rounds = self._base_rounds = {}
        return rounds

    def put_round_state(self, doc: dict) -> None:
        """Unconditionally upsert a round lifecycle document (keyed by its
        ``doc["aggregation"]`` id string)."""
        self._fallback_rounds()[doc["aggregation"]] = dict(doc)

    def get_round_state(self, aggregation: AggregationId) -> Optional[dict]:
        doc = self._fallback_rounds().get(str(aggregation))
        return None if doc is None else dict(doc)

    def list_round_states(self) -> List[dict]:
        return [dict(d) for d in self._fallback_rounds().values()]

    def transition_round_state(
        self, aggregation: AggregationId, from_states, doc: dict
    ) -> bool:
        """Conditional publish: install ``doc`` iff the stored record's
        current ``state`` is one of ``from_states`` — the single-winner
        CAS that lets N fleet workers race a lifecycle transition and
        guarantees exactly one performs it (the same conditional-write
        contract as ``create_snapshot``; docs/robustness.md)."""
        rounds = self._fallback_rounds()
        current = rounds.get(str(aggregation))
        if current is None or current.get("state") not in from_states:
            return False
        rounds[str(aggregation)] = dict(doc)
        return True


class ClerkingJobsStore(BaseStore):
    @abc.abstractmethod
    def enqueue_clerking_job(self, job: ClerkingJob) -> None:
        """Queue a job for its clerk. Must be an upsert keyed by
        ``(clerk, id)`` and must NOT resurrect a completed job — snapshot
        creation relies on this to be retry-idempotent."""

    def enqueue_clerking_jobs(self, jobs: Iterable[ClerkingJob]) -> None:
        """Bulk enqueue — the snapshot pipeline queues one job per
        committee member in a single store transaction where the backend
        supports it. The fallback loops; overrides must preserve the
        per-job upsert + never-resurrect-done semantics."""
        for job in jobs:
            self.enqueue_clerking_job(job)

    @abc.abstractmethod
    def poll_clerking_job(self, clerk: AgentId) -> Optional[ClerkingJob]:
        """Peek the clerk's next undone job (reference semantics: the job
        stays visible until its result lands)."""

    def lease_clerking_job(
        self, clerk: AgentId, lease_seconds: float,
        now: Optional[float] = None, owner: Optional[str] = None,
    ) -> Optional[Tuple[ClerkingJob, float]]:
        """Pull the clerk's next undone job that is not under an active
        lease and stamp a new lease on it; returns ``(job, expires_at)``.

        A lease is a visibility timeout (the SQS model): while held, other
        pollers of the same clerk identity get the NEXT job instead of
        duplicating this one; once it expires without a result the job is
        *reissued* — returned again to whichever live poller asks first
        (``server.job.reissued``). Backends without native lease support
        inherit this fallback, which degrades to the plain visible-poll.

        ``owner`` names the fleet worker granting the lease (the server's
        ``node_id``): backends record it so the gray-failure plane can
        proactively recall EVERY lease a dead worker held
        (``recall_clerking_job_leases``) and hedge a suspect worker's
        jobs (``hedge_clerking_job``) without waiting out per-job expiry.
        """
        job = self.poll_clerking_job(clerk)
        if job is None:
            return None
        now = time.time() if now is None else now
        return job, now + lease_seconds

    def release_clerking_job_lease(
        self, clerk: AgentId, job: ClerkingJobId,
        expires: Optional[float] = None,
    ) -> bool:
        """Drop an active lease early so the NEXT poller (on any worker
        process) gets the job immediately instead of waiting out the
        visibility timeout — the graceful-drain path: a terminating
        worker hands its in-flight clerking work back to the fleet.

        ``expires`` is the expiry instant the caller was granted: when
        given, ONLY the lease expiring at exactly that instant is
        released (compare-and-release) — a lease that lapsed and was
        re-granted to a peer belongs to that peer now and must be left
        alone, or the drain would expose the peer's in-flight job to a
        third worker. Returns whether a lease was actually released.
        No-op (False) on done jobs and on backends without lease
        support."""
        return False

    def recall_clerking_job_leases(self, node_id: str) -> int:
        """Drop EVERY active lease granted by fleet worker ``node_id`` —
        the failure detector's recovery step once that worker is declared
        dead (``server/health.py``): any peer's next poll reissues the
        work immediately instead of waiting out per-job lease expiry.
        Done jobs are untouched (their results already landed). Returns
        how many leases were recalled; 0 on backends without lease-owner
        support (per-job expiry remains the fallback)."""
        return 0

    def hedge_clerking_job(
        self, clerk: AgentId, suspect_nodes, lease_seconds: float,
        now: Optional[float] = None, owner: Optional[str] = None,
    ) -> Optional[Tuple[ClerkingJob, float]]:
        """Straggler hedging (the Tail-at-Scale hedged-request move, at
        clerking-job granularity): grant THIS caller a lease on the
        clerk's next undone job even though it is actively leased — but
        ONLY when the current holder is one of ``suspect_nodes`` (a
        worker whose heartbeat went stale without being declared dead).
        The hedged copy races the original; commit stays single-winner
        via the store-arbitrated conditional result insert, so duplicate
        partial sums are impossible and the verdict stays bit-exact.
        Returns ``(job, expires_at)`` or None; None on backends without
        lease-owner support."""
        return None

    # -- fleet heartbeats (server/health.py) --------------------------------
    # The four in-repo backends override these with durable, contended-safe
    # implementations; the base fallbacks keep third-party stores working
    # (in-memory, NOT crash- or fleet-safe).

    def _fallback_heartbeats(self) -> dict:
        beats = getattr(self, "_base_heartbeats", None)
        if beats is None:
            beats = self._base_heartbeats = {}
        return beats

    def put_worker_heartbeat(self, doc: dict) -> None:
        """Unconditionally upsert a worker heartbeat row (keyed by
        ``doc["node"]``) — each worker writes only its own."""
        self._fallback_heartbeats()[doc["node"]] = dict(doc)

    def get_worker_heartbeat(self, node: str) -> Optional[dict]:
        doc = self._fallback_heartbeats().get(str(node))
        return None if doc is None else dict(doc)

    def list_worker_heartbeats(self) -> List[dict]:
        return [dict(d) for d in self._fallback_heartbeats().values()]

    def transition_worker_state(self, node: str, from_states,
                                doc: dict) -> bool:
        """Conditional publish: install ``doc`` iff the stored heartbeat's
        current ``state`` is one of ``from_states`` — the single-winner
        CAS that lets N fleet sweepers race a suspect/dead declaration
        and guarantees exactly one performs it (and recalls the dead
        node's leases exactly once); same contract as
        ``transition_round_state``."""
        beats = self._fallback_heartbeats()
        current = beats.get(str(node))
        if current is None or current.get("state") not in from_states:
            return False
        beats[str(node)] = dict(doc)
        return True

    @abc.abstractmethod
    def get_clerking_job(
        self, clerk: AgentId, job: ClerkingJobId
    ) -> Optional[ClerkingJob]: ...

    @abc.abstractmethod
    def create_clerking_result(self, result: ClerkingResult) -> None: ...

    def list_snapshot_jobs(
        self, snapshot: SnapshotId
    ) -> List[Tuple[ClerkingJobId, AgentId, bool, float]]:
        """Every clerking job of the snapshot as ``(job id, clerk, done,
        leased_until)`` — the round sweeper's dead-clerk census
        (``server/lifecycle.py``): past the clerking deadline, an undone
        job with no active lease (``leased_until <= now``) marks its
        clerk dead. ``leased_until`` is 0 for never-leased jobs and on
        backends without lease support. The base fallback returns ``[]``
        (no census possible → the sweeper stays silent)."""
        return []

    def purge_snapshot_jobs(self, snapshot: SnapshotId) -> int:
        """Remove EVERY clerking job, lease, and result of ``snapshot`` —
        the job-store half of the aggregation delete/retention cascade
        (``SdaServer.purge_aggregation``): a long-running service expires
        revealed rounds and their artifacts must actually leave all four
        backends, or fleet memory and store size grow forever
        (service/retention.py). Idempotent: purging an already-purged
        snapshot removes nothing. Returns how many documents (jobs +
        results) were removed; 0 on backends without purge support (the
        base fallback — artifacts then leak, as pre-retention stores
        always did)."""
        return 0

    @abc.abstractmethod
    def list_results(self, snapshot: SnapshotId) -> List[ClerkingJobId]: ...

    @abc.abstractmethod
    def get_result(
        self, snapshot: SnapshotId, job: ClerkingJobId
    ) -> Optional[ClerkingResult]: ...
