"""The snapshot scheduler — the server-side "compute".

Reference: server/src/snapshot.rs:4-47. Creating a snapshot (1) freezes the
current participation set, (2) transposes participations x clerks into one
ClerkingJob per committee member, (3) records the snapshot, and (4) collects
the recipient-mask encryptions if the aggregation masks. All heavy lifting
is data movement; the field math happens at the clerks — EXCEPT under
Paillier premixing (below), where the broker also multiplies ciphertexts.

Premixing: when the committee encryption scheme is PackedPaillier and the
server opts in (``SdaServer.premix_paillier``), each clerk's column of
participation ciphertext batches is homomorphically combined *on the
server* before enqueueing — the untrusted broker compresses every clerk's
download from N batches to ceil(N / additive_capacity) without learning
anything (ciphertext products reveal nothing new), and the clerk-side flow
is unchanged: it decrypts integer share sums and its modular combine
reduces them. This is the payoff the reference's commented-out
PackedPaillier declaration (protocol/src/crypto.rs:164-174) was pointing
at; Sodium aggregations are untouched since sealed boxes don't compose.
"""

from __future__ import annotations

import logging
import uuid

from ..protocol import (
    ClerkingJob,
    ClerkingJobId,
    NotFound,
    PackedPaillierEncryption,
    Snapshot,
)
from .. import obs
from ..utils import metrics, timed_phase
from . import lifecycle

log = logging.getLogger(__name__)

#: Namespace for deterministic clerking-job ids (uuid5 over snapshot:clerk).
_JOB_NAMESPACE = uuid.UUID("6ad33932-6a4c-4745-a2b4-11e89e7206ad")


def clerking_job_id(snapshot_id, clerk_id) -> ClerkingJobId:
    """Deterministic job id for (snapshot, clerk) — re-running the snapshot
    pipeline (a retried POST after a lost response, a crash-resume replay)
    upserts the SAME jobs instead of enqueueing duplicates, which is what
    makes snapshot creation safe for the retrying transport."""
    return ClerkingJobId(uuid.uuid5(_JOB_NAMESPACE, f"{snapshot_id}:{clerk_id}"))


def _premix_columns(server, aggregation, committee, columns):
    """Per-clerk homomorphic combine of participation ciphertext columns."""
    from ..crypto.encryption import paillier_combine

    scheme = aggregation.committee_encryption_scheme
    cap = scheme.additive_capacity
    mixed = []
    for (clerk_id, key_id), column in zip(committee.clerks_and_keys, columns):
        signed_key = server.get_encryption_key(key_id)
        if signed_key is None:
            raise NotFound("lost clerk encryption key")
        ek = signed_key.body.body
        try:
            combined = [
                paillier_combine(ek, scheme, column[i : i + cap])
                for i in range(0, len(column), cap)
            ]
        except ValueError as e:
            # participant uploads are untrusted: a forged/malformed batch
            # must not wedge snapshot creation for everyone — enqueue the
            # column unmixed and let the clerk hit the bad batch itself,
            # exactly as it would without premixing
            log.warning(
                "premix skipped for clerk %s (malformed participation "
                "ciphertext: %s); enqueueing column unmixed", clerk_id, e
            )
            metrics.count("server.premix.skipped_malformed")
            mixed.append(column)
            continue
        metrics.count("server.premix.inputs", len(column))
        metrics.count("server.premix.outputs", len(combined))
        mixed.append(combined)
    return mixed


#: Upper bound on mask-ciphertext chunks materialized in pipeline memory
#: at once (override via SDA_SNAPSHOT_MASK_BATCH). Tree-scale leaf counts
#: make the mask column the largest per-round allocation on the broker;
#: chunking keeps snapshot memory O(batch) regardless of population size.
#: FLEET-UNIFORM, like every per-worker protocol knob: concurrent
#: pipelines chunking ONE snapshot at different boundaries cannot
#: converge (stores.py mask-chunk contract) — never vary this across
#: workers of one fleet mid-flight; the trim step only reconciles
#: SEQUENTIAL config changes (a replay after restart).
DEFAULT_MASK_BATCH = 1024


def _mask_batch_size() -> int:
    import os

    raw = os.environ.get("SDA_SNAPSHOT_MASK_BATCH", "")
    try:
        return max(1, int(raw)) if raw.strip() else DEFAULT_MASK_BATCH
    except ValueError:
        return DEFAULT_MASK_BATCH


def _collect_masks_streamed(server, aggregation, snap) -> None:
    """Stream the recipient-mask column into bounded store chunks.

    The column read stays a per-participation iterator and each full
    batch is flushed with ``put_snapshot_mask_chunk`` — pipeline memory
    is O(batch), not O(participants). Chunk writes are pure upserts: a
    crash-replay (or a contended fleet peer re-running the pipeline over
    the SAME frozen set) rewrites an identical chunk sequence, so any
    interleaving converges bit-exactly AND a reader holding the
    committed snapshot record always sees a complete mask (stores.py
    contended-idempotency contract); the final trim drops excess chunks
    left by an attempt that used a different batch size.

    Tree parents additionally append the frozen set's FORWARDED mask
    ciphertexts (``Participation.forwarded_masks`` — each relay's leaf
    masks, sealed to the root recipient), so the root's reveal sees one
    flat mask list: relay masks first (participation order), then the
    forwarded leaf masks.
    """
    batch = _mask_batch_size()
    store = server.aggregation_store
    chunk, index, total = [], 0, 0

    def flush():
        nonlocal chunk, index
        store.put_snapshot_mask_chunk(snap.id, index, chunk)
        metrics.observe("server.snapshot.mask_chunk", len(chunk))
        index += 1
        chunk = []

    for encryption in store.iter_snapped_recipient_encryptions(
        snap.aggregation, snap.id
    ):
        if encryption is None:
            raise NotFound("participation should have had a recipient encryption")
        chunk.append(encryption)
        total += 1
        if len(chunk) >= batch:
            flush()
    tree = getattr(aggregation, "tree", None)
    if tree is not None and tree.children:
        # forwarded leaf masks ride the SAME chunked stream upward
        for encryption in store.iter_snapped_forwarded_masks(
            snap.aggregation, snap.id
        ):
            chunk.append(encryption)
            total += 1
            if len(chunk) >= batch:
                flush()
    # always write the final (possibly empty) chunk: chunk 0 must exist so
    # get_snapshot_mask distinguishes "masked round, zero participations"
    # from "never collected"
    if chunk or index == 0:
        flush()
    store.trim_snapshot_mask_chunks(snap.id, index)
    metrics.count("server.snapshot.masks_collected", total)


def snapshot(server, snap: Snapshot) -> bool:
    # the whole pipeline is serialized: a timed-out client retry arriving
    # while the original is still running must wait and then hit the
    # existence check, not race the freeze/enqueue (snapshot creation is
    # a rare control-plane operation; the lock costs nothing that matters)
    with server._snapshot_lock:
        return _snapshot_locked(server, snap)


def _snapshot_locked(server, snap: Snapshot) -> bool:
    aggregation = server.aggregation_store.get_aggregation(snap.aggregation)
    if aggregation is None:
        raise NotFound("lost aggregation")
    if server.aggregation_store.get_snapshot(snap.aggregation, snap.id) is not None:
        # create-once: the snapshot record is written last (below), so its
        # presence proves the whole pipeline already ran — a retry is a no-op
        log.debug("snapshot %s: already exists, skipping", snap.id)
        metrics.count("server.snapshot.duplicate")
        return False
    log.debug("snapshot %s: freezing participations", snap.id)
    with timed_phase("server.snapshot_freeze"):
        # first-write-wins, now store-arbitrated: snapshot_participations
        # is single-winner even across competing server processes, so a
        # crash-replay (record not yet committed, jobs possibly enqueued
        # and even clerked) AND a concurrent peer's pipeline both re-use
        # the ORIGINAL frozen set — re-freezing after a late participation
        # would mix share generations across clerk columns
        if not server.aggregation_store.snapshot_participations(
            snap.aggregation, snap.id
        ):
            log.debug("snapshot %s: freeze already installed (replay or "
                      "competing worker); converging on it", snap.id)
            metrics.count("server.snapshot.freeze_converged")
    # lifecycle: the round leaves collecting the moment its participation
    # set is frozen (CAS — contended pipelines note it exactly once)
    lifecycle.note_frozen(server, aggregation, snap.id)

    committee = server.get_committee(snap.aggregation)
    if committee is None:
        raise NotFound("lost committee")

    log.debug("snapshot %s: transposing encryptions", snap.id)
    with timed_phase("server.transpose"):
        columns = server.aggregation_store.iter_snapshot_clerk_jobs_data(
            snap.aggregation, snap.id, len(committee.clerks_and_keys)
        )

    if (
        getattr(server, "premix_paillier", False)
        and isinstance(
            aggregation.committee_encryption_scheme, PackedPaillierEncryption
        )
        and any(columns)
    ):
        log.debug("snapshot %s: premixing clerk columns homomorphically", snap.id)
        with timed_phase("server.premix"):
            columns = _premix_columns(server, aggregation, committee, columns)

    log.debug("snapshot %s: enqueueing %d clerking jobs", snap.id, len(columns))
    with timed_phase("server.enqueue_jobs"):
        enqueue_ctx = obs.current_context()
        jobs = []
        for (clerk_id, _), encryptions in zip(committee.clerks_and_keys, columns):
            job = ClerkingJob(
                id=clerking_job_id(snap.id, clerk_id),
                clerk=clerk_id,
                aggregation=snap.aggregation,
                snapshot=snap.id,
                encryptions=encryptions,
            )
            # remember which trace enqueued each job: clerk-side processing
            # (including a lease-reissued retry of the same deterministic
            # job id) re-parents to this round instead of its own poll
            obs.link_job(str(job.id), enqueue_ctx)
            jobs.append(job)
        # ONE bulk store write for the whole committee fan-out (a single
        # transaction on sqlite, one lock hold on memory/jsonfs, batched
        # round trips on mongo) instead of C commits of C full columns
        server.clerking_job_store.enqueue_clerking_jobs(jobs)
        # long-poll push plane: stamp enqueue time (server.job.pickup
        # histogram) and wake exactly the clerks that now have work, so a
        # parked GET /v1/clerking-jobs?wait=S answers immediately instead
        # of riding out its re-check tick (server/wakeup.py)
        server.note_jobs_enqueued(job.id for job in jobs)
        server.job_wakeup.notify(job.clerk for job in jobs)
    # lifecycle: jobs are durable, the committee can work — the round is
    # clerking and its deadline clock starts (lifecycle.py)
    lifecycle.note_clerking(server, snap.aggregation, snap.id)

    if aggregation.masking_scheme.has_mask:
        log.debug("snapshot %s: collecting recipient mask encryptions", snap.id)
        with timed_phase("server.collect_masks"):
            _collect_masks_streamed(server, aggregation, snap)

    # the snapshot record is the commit point and therefore goes LAST:
    # its presence proves jobs and masks are durable, so the existence
    # check above can safely short-circuit a retried create. A crash
    # mid-pipeline leaves no record and the retry re-runs everything —
    # job ids are deterministic, so the re-run upserts instead of
    # duplicating. The insert is single-winner across competing server
    # processes (store-level conditional insert): when a peer's pipeline
    # commits first, OUR pipeline has already upserted the exact same
    # uuid5(snapshot, clerk) job set, so losing is convergence — report
    # not-created and leave the winner's record untouched.
    if not server.aggregation_store.create_snapshot(snap):
        log.debug("snapshot %s: lost the record race to a competing "
                  "worker (identical job set already enqueued)", snap.id)
        metrics.count("server.snapshot.contended")
        return False

    log.debug("snapshot %s: done", snap.id)
    return True
