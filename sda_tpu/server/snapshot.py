"""The snapshot scheduler — the server-side "compute".

Reference: server/src/snapshot.rs:4-47. Creating a snapshot (1) freezes the
current participation set, (2) transposes participations x clerks into one
ClerkingJob per committee member, (3) records the snapshot, and (4) collects
the recipient-mask encryptions if the aggregation masks. All heavy lifting
is data movement; the field math happens at the clerks.
"""

from __future__ import annotations

import logging

from ..protocol import ClerkingJob, ClerkingJobId, NotFound, Snapshot
from ..utils import timed_phase

log = logging.getLogger(__name__)


def snapshot(server, snap: Snapshot) -> None:
    aggregation = server.aggregation_store.get_aggregation(snap.aggregation)
    if aggregation is None:
        raise NotFound("lost aggregation")
    log.debug("snapshot %s: freezing participations", snap.id)
    with timed_phase("server.snapshot_freeze"):
        server.aggregation_store.snapshot_participations(snap.aggregation, snap.id)

    committee = server.get_committee(snap.aggregation)
    if committee is None:
        raise NotFound("lost committee")

    log.debug("snapshot %s: transposing encryptions", snap.id)
    with timed_phase("server.transpose"):
        columns = server.aggregation_store.iter_snapshot_clerk_jobs_data(
            snap.aggregation, snap.id, len(committee.clerks_and_keys)
        )

    log.debug("snapshot %s: enqueueing %d clerking jobs", snap.id, len(columns))
    with timed_phase("server.enqueue_jobs"):
        for (clerk_id, _), encryptions in zip(committee.clerks_and_keys, columns):
            server.clerking_job_store.enqueue_clerking_job(
                ClerkingJob(
                    id=ClerkingJobId.random(),
                    clerk=clerk_id,
                    aggregation=snap.aggregation,
                    snapshot=snap.id,
                    encryptions=encryptions,
                )
            )

    server.aggregation_store.create_snapshot(snap)

    if aggregation.masking_scheme.has_mask:
        log.debug("snapshot %s: collecting recipient mask encryptions", snap.id)
        recipient_encryptions = []
        for participation in server.aggregation_store.iter_snapped_participations(
            snap.aggregation, snap.id
        ):
            if participation.recipient_encryption is None:
                raise NotFound("participation should have had a recipient encryption")
            recipient_encryptions.append(participation.recipient_encryption)
        server.aggregation_store.create_snapshot_mask(snap.id, recipient_encryptions)

    log.debug("snapshot %s: done", snap.id)
