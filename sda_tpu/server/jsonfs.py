"""JSON-file store backend: one file per protocol object, durable on write.

The reference's jfs backend (server/src/jfs_stores/): every resource becomes
a JSON file the moment it exists, so the server is crash-safe by
construction — restart resumes from the directory tree. Layout:

Each store class takes its own root; with ``new_jsonfs_server(root)`` the
resulting tree is:

    <root>/agents/agents/<agent-id>.json
    <root>/agents/profiles/<agent-id>.json
    <root>/agents/keys/<key-id>.json
    <root>/auths/<agent-id>.json
    <root>/agg/aggregations/<agg-id>.json
    <root>/agg/committees/<agg-id>.json
    <root>/agg/participations/<agg-id>/<participation-id>.json
    <root>/agg/snapshots/<agg-id>/<snapshot-id>.json
    <root>/agg/snapshot_parts/<snapshot-id>.json   (frozen participation ids)
    <root>/agg/masks/<snapshot-id>.json
    <root>/jobs/queue/<clerk-id>/<job-id>.json
    <root>/jobs/done/<clerk-id>/<job-id>.json
    <root>/jobs/results/<snapshot-id>/<job-id>.json

The job queue mirrors the reference's per-clerk directory queue with
queue -> done moves on result creation (jfs_stores/clerking_jobs.rs:36-59).
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import List, Optional

from .. import chaos
from ..utils import metrics
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    Encryption,
    NotFound,
    Participation,
    ParticipationConflict,
    Profile,
    Snapshot,
    SnapshotId,
    signed_encryption_key_from_obj,
)
from .stores import (
    AgentsStore,
    AggregationsStore,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
    auth_token,
)


def _write_json(path: Path, obj) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=".tmp-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_json_new(path: Path, obj) -> bool:
    """Create-if-absent, atomically even across OS processes: the payload
    lands in a temp file, then ``os.link`` publishes it — link(2) fails
    with EEXIST when the destination already exists, so exactly one of N
    racing writers wins and the losers see the winner's complete file
    (never a partial write). Returns whether THIS call created the file —
    the jsonfs arbiter for the contended-idempotency contract."""
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=".tmp-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _read_json(path: Path):
    if not path.exists():
        return None
    return json.loads(path.read_text())


def _ids_in(directory: Path) -> List[str]:
    if not directory.is_dir():
        return []
    return sorted(p.stem for p in directory.glob("*.json") if not p.name.startswith("."))


class _FsStore(BaseStore):
    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.RLock()

    def ping(self) -> None:
        if not self.root.is_dir():
            raise NotFound(f"store root {self.root} missing")

    @contextlib.contextmanager
    def _dir_lock(self, directory: Path):
        """Cross-PROCESS mutual exclusion over ``directory`` (flock on a
        dot-file inside it, so ``_ids_in`` never sees it). The in-process
        ``_lock`` only serializes threads; read-check-write sequences
        that must be atomic across fleet worker processes — the lease
        grant/release plane — take this too. Single-file publishes don't
        need it: ``os.link`` arbitration already is cross-process."""
        directory.mkdir(parents=True, exist_ok=True)
        fd = os.open(str(directory / ".dirlock"), os.O_CREAT | os.O_RDWR,
                     0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)


class JsonAuthTokensStore(_FsStore, AuthTokensStore):
    def upsert_auth_token(self, token):
        with self._lock:
            _write_json(self.root / f"{token.id}.json", {"id": str(token.id), "body": token.body})

    def get_auth_token(self, id):
        with self._lock:
            obj = _read_json(self.root / f"{id}.json")
            return None if obj is None else auth_token(type(id)(obj["id"]), obj["body"])

    def delete_auth_token(self, id):
        with self._lock:
            try:
                (self.root / f"{id}.json").unlink()
            except FileNotFoundError:
                pass


class JsonAgentsStore(_FsStore, AgentsStore):
    def create_agent(self, agent):
        with self._lock:
            _write_json(self.root / "agents" / f"{agent.id}.json", agent.to_obj())

    def get_agent(self, id):
        with self._lock:
            obj = _read_json(self.root / "agents" / f"{id}.json")
            return None if obj is None else Agent.from_obj(obj)

    def upsert_profile(self, profile):
        with self._lock:
            _write_json(self.root / "profiles" / f"{profile.owner}.json", profile.to_obj())

    def get_profile(self, owner):
        with self._lock:
            obj = _read_json(self.root / "profiles" / f"{owner}.json")
            return None if obj is None else Profile.from_obj(obj)

    def create_encryption_key(self, key):
        with self._lock:
            _write_json(self.root / "keys" / f"{key.body.id}.json", key.to_obj())

    def get_encryption_key(self, key):
        with self._lock:
            obj = _read_json(self.root / "keys" / f"{key}.json")
            return None if obj is None else signed_encryption_key_from_obj(obj)

    def suggest_committee(self):
        with self._lock:
            by_signer = {}
            for key_id in _ids_in(self.root / "keys"):
                signed = self.get_encryption_key(key_id)
                by_signer.setdefault(signed.signer, []).append(signed.body.id)
            return [
                ClerkCandidate(id=signer, keys=keys)
                for signer, keys in sorted(by_signer.items(), key=lambda kv: kv[0])
            ]


class JsonAggregationsStore(_FsStore, AggregationsStore):
    def list_aggregations(self, filter=None, recipient=None):
        with self._lock:
            out = []
            for agg_id in _ids_in(self.root / "aggregations"):
                agg = self.get_aggregation(agg_id)
                if filter is not None and filter not in agg.title:
                    continue
                if recipient is not None and agg.recipient != recipient:
                    continue
                out.append(agg.id)
            return out

    def create_aggregation(self, aggregation):
        with self._lock:
            _write_json(
                self.root / "aggregations" / f"{aggregation.id}.json", aggregation.to_obj()
            )

    def get_aggregation(self, aggregation):
        with self._lock:
            obj = _read_json(self.root / "aggregations" / f"{aggregation}.json")
            return None if obj is None else Aggregation.from_obj(obj)

    def delete_aggregation(self, aggregation):
        import shutil

        with self._lock:
            for sid in self.list_snapshots(aggregation):
                (self.root / "snapshot_parts" / f"{sid}.json").unlink(missing_ok=True)
                (self.root / "masks" / f"{sid}.json").unlink(missing_ok=True)
                shutil.rmtree(self.root / "masks" / str(sid),
                              ignore_errors=True)
            for sub in ("participations", "part_owners", "snapshots"):
                shutil.rmtree(self.root / sub / str(aggregation), ignore_errors=True)
            (self.root / "aggregations" / f"{aggregation}.json").unlink(missing_ok=True)
            (self.root / "committees" / f"{aggregation}.json").unlink(missing_ok=True)
            (self.root / "rounds" / f"{aggregation}.json").unlink(missing_ok=True)

    def get_committee(self, aggregation):
        with self._lock:
            obj = _read_json(self.root / "committees" / f"{aggregation}.json")
            return None if obj is None else Committee.from_obj(obj)

    def create_committee(self, committee):
        with self._lock:
            _write_json(
                self.root / "committees" / f"{committee.aggregation}.json", committee.to_obj()
            )

    def create_participation(self, participation):
        chaos.fail("store.create_participation")
        digest = participation.canonical_digest()
        agg = str(participation.aggregation)
        payload = (self.root / "participations" / agg
                   / f"{participation.id}.json")
        # the per-agent owner marker is the single-winner key: link(2)
        # create-if-absent arbitrates across OS processes, exactly like
        # the snapshot freeze (exactly-once ingestion contract,
        # stores.py). Marker FIRST, payload second: a crash between the
        # two leaves a claimed-but-unwritten slot that the replay below
        # repairs; payload-first would leave an UNclaimed payload a
        # recomputed bundle could double-count against.
        owner = (self.root / "part_owners" / agg
                 / f"{participation.participant}.json")
        with self._lock:
            if self.get_aggregation(participation.aggregation) is None:
                raise NotFound("aggregation not found")
            existing = _read_json(payload)
            if existing is not None:
                # same participation id: byte-identical replay succeeds
                # idempotently; different content never silently replaces
                if Participation.from_obj(existing).canonical_digest() \
                        == digest:
                    # heal the marker if a pre-exactly-once writer (or a
                    # crash) left the payload unclaimed
                    _write_json_new(owner, {"id": str(participation.id),
                                            "digest": digest})
                    return False
                raise ParticipationConflict(
                    f"participation {participation.id} already exists "
                    "with different content",
                    participant=participation.participant,
                    aggregation=participation.aggregation)
            if _write_json_new(owner, {"id": str(participation.id),
                                       "digest": digest}):
                _write_json_new(payload, participation.to_obj())
                return True
            claimed = _read_json(owner) or {}
            if claimed.get("digest") == digest:
                # replay of our own bytes; re-publish the payload in case
                # the original writer crashed between marker and payload
                _write_json_new(payload, participation.to_obj())
                return False
            raise ParticipationConflict(
                f"agent {participation.participant} already participated "
                f"in {participation.aggregation} "
                f"(participation {claimed.get('id')})",
                participant=participation.participant,
                aggregation=participation.aggregation)

    def create_snapshot(self, snapshot):
        chaos.fail("store.create_snapshot")
        # conditional create: link(2) beats N racing server processes
        # down to one winner; the record file never changes once present
        with self._lock:
            return _write_json_new(
                self.root / "snapshots" / str(snapshot.aggregation) / f"{snapshot.id}.json",
                snapshot.to_obj(),
            )

    def list_snapshots(self, aggregation):
        with self._lock:
            return [
                SnapshotId(s) for s in _ids_in(self.root / "snapshots" / str(aggregation))
            ]

    def get_snapshot(self, aggregation, snapshot):
        with self._lock:
            obj = _read_json(
                self.root / "snapshots" / str(aggregation) / f"{snapshot}.json"
            )
            return None if obj is None else Snapshot.from_obj(obj)

    def count_participations(self, aggregation):
        with self._lock:
            return len(_ids_in(self.root / "participations" / str(aggregation)))

    def snapshot_participations(self, aggregation, snapshot):
        # single-winner freeze: the frozen-id file IS both the marker and
        # the set, created atomically with link(2) — a loser returning
        # False can immediately read the winner's complete id list
        with self._lock:
            part_ids = _ids_in(self.root / "participations" / str(aggregation))
            return _write_json_new(
                self.root / "snapshot_parts" / f"{snapshot}.json", part_ids
            )

    def has_snapshot_freeze(self, aggregation, snapshot):
        with self._lock:
            # the frozen-id file is the durable marker (an empty list counts)
            return (self.root / "snapshot_parts" / f"{snapshot}.json").exists()

    def count_participations_snapshot(self, aggregation, snapshot):
        # the frozen id list already holds the answer — don't deserialize
        # every participation just to count them
        with self._lock:
            part_ids = _read_json(self.root / "snapshot_parts" / f"{snapshot}.json") or []
            return len(part_ids)

    def iter_snapped_participations(self, aggregation, snapshot):
        with self._lock:
            part_ids = _read_json(self.root / "snapshot_parts" / f"{snapshot}.json") or []
            out = []
            for pid in part_ids:
                obj = _read_json(
                    self.root / "participations" / str(aggregation) / f"{pid}.json"
                )
                if obj is not None:
                    out.append(Participation.from_obj(obj))
            return out

    def _iter_snapped_docs(self, aggregation, snapshot):
        """Streamed walk of the frozen set's documents: the id list is
        read once under the lock (small), then one document file is
        resident at a time — O(1) documents in memory at tree-scale
        counts, with the lock released between files so the snapshot
        pipeline's interleaved mask-chunk writes never queue behind a
        full-set scan."""
        with self._lock:
            part_ids = _read_json(
                self.root / "snapshot_parts" / f"{snapshot}.json") or []
        for pid in part_ids:
            with self._lock:
                obj = _read_json(
                    self.root / "participations" / str(aggregation)
                    / f"{pid}.json"
                )
            if obj is not None:
                yield obj

    def iter_snapped_recipient_encryptions(self, aggregation, snapshot):
        # mask-column read: decode only the recipient_encryption field of
        # each frozen document instead of re-materializing every
        # participation a second time
        for obj in self._iter_snapped_docs(aggregation, snapshot):
            enc = obj.get("recipient_encryption")
            yield None if enc is None else Encryption.from_obj(enc)

    def iter_snapped_forwarded_masks(self, aggregation, snapshot):
        # forwarded-mask column read (tree parents): same streamed walk
        for obj in self._iter_snapped_docs(aggregation, snapshot):
            for enc in obj.get("forwarded_masks") or ():
                yield Encryption.from_obj(enc)

    # -- round lifecycle ----------------------------------------------------
    def put_round_state(self, doc):
        with self._lock:
            _write_json(self.root / "rounds" / f"{doc['aggregation']}.json",
                        doc)

    def get_round_state(self, aggregation):
        with self._lock:
            return _read_json(self.root / "rounds" / f"{aggregation}.json")

    def list_round_states(self):
        with self._lock:
            out = []
            for agg_id in _ids_in(self.root / "rounds"):
                doc = _read_json(self.root / "rounds" / f"{agg_id}.json")
                if doc is not None:
                    out.append(doc)
            return out

    def transition_round_state(self, aggregation, from_states, doc):
        # single-winner CAS across fleet worker processes: the dir flock
        # makes the read-check-write atomic (link(2) arbitration only
        # covers create-if-absent; a transition REPLACES the file)
        with self._lock, self._dir_lock(self.root / "rounds"):
            path = self.root / "rounds" / f"{aggregation}.json"
            current = _read_json(path)
            if current is None or current.get("state") not in from_states:
                return False
            _write_json(path, doc)
            return True

    # -- recurring-round schedules -------------------------------------------
    def create_schedule_state(self, doc):
        # create-if-absent via link(2): installation is single-winner
        # across OS processes, so a booting scheduler can never reset an
        # advanced schedule (stores.py schedule contract)
        with self._lock:
            return _write_json_new(
                self.root / "schedules" / f"{doc['schedule']}.json", doc)

    def get_schedule_state(self, schedule):
        with self._lock:
            return _read_json(self.root / "schedules" / f"{schedule}.json")

    def list_schedule_states(self):
        with self._lock:
            out = []
            for name in _ids_in(self.root / "schedules"):
                doc = _read_json(self.root / "schedules" / f"{name}.json")
                if doc is not None:
                    out.append(doc)
            return out

    def transition_schedule_state(self, schedule, from_epoch, doc):
        # single-winner epoch CAS across fleet worker processes: the dir
        # flock makes the read-check-write atomic (same shape as
        # transition_round_state)
        with self._lock, self._dir_lock(self.root / "schedules"):
            path = self.root / "schedules" / f"{schedule}.json"
            current = _read_json(path)
            if current is None \
                    or int(current.get("epoch", -1)) != int(from_epoch):
                return False
            _write_json(path, doc)
            return True

    def create_snapshot_mask(self, snapshot, mask):
        self.put_snapshot_mask_chunk(snapshot, 0, mask)
        self.trim_snapshot_mask_chunks(snapshot, 1)

    def put_snapshot_mask_chunk(self, snapshot, index, encryptions):
        # one file per chunk under masks/<snapshot>/, pure upsert: file
        # writes are atomic (temp+replace) and a replaying or contended
        # pipeline rewrites byte-identical chunks (stores.py contract),
        # so readers always see a complete mask. Chunk 0 supersedes any
        # legacy single-file mask.
        with self._lock:
            directory = self.root / "masks" / str(snapshot)
            if index == 0:
                (self.root / "masks" / f"{snapshot}.json").unlink(
                    missing_ok=True)
            directory.mkdir(parents=True, exist_ok=True)
            _write_json(directory / f"{int(index):08d}.json",
                        [e.to_obj() for e in encryptions])

    def trim_snapshot_mask_chunks(self, snapshot, count):
        with self._lock:
            directory = self.root / "masks" / str(snapshot)
            if not directory.is_dir():
                return
            for path in directory.glob("*.json"):
                try:
                    if int(path.stem) >= int(count):
                        path.unlink(missing_ok=True)
                except ValueError:
                    continue  # not a chunk file

    def get_snapshot_mask(self, snapshot):
        with self._lock:
            directory = self.root / "masks" / str(snapshot)
            if directory.is_dir():
                out = []
                for path in sorted(directory.glob("*.json")):
                    out.extend(Encryption.from_obj(e)
                               for e in _read_json(path) or [])
                return out
            # pre-chunking layout: fall back to the legacy single file
            obj = _read_json(self.root / "masks" / f"{snapshot}.json")
            return None if obj is None else [Encryption.from_obj(e) for e in obj]


class JsonClerkingJobsStore(_FsStore, ClerkingJobsStore):
    def enqueue_clerking_job(self, job):
        chaos.fail("store.enqueue_clerking_job")
        with self._lock:
            if (self.root / "done" / str(job.clerk) / f"{job.id}.json").exists():
                return  # snapshot retry: this job already completed
            _write_json(
                self.root / "queue" / str(job.clerk) / f"{job.id}.json", job.to_obj()
            )

    def enqueue_clerking_jobs(self, jobs):
        jobs = list(jobs)
        for _ in jobs:
            chaos.fail("store.enqueue_clerking_job")
        with self._lock:  # one lock hold for the whole fan-out
            for job in jobs:
                if (self.root / "done" / str(job.clerk) / f"{job.id}.json").exists():
                    continue  # snapshot retry: this job already completed
                _write_json(
                    self.root / "queue" / str(job.clerk) / f"{job.id}.json",
                    job.to_obj(),
                )

    def poll_clerking_job(self, clerk):
        chaos.fail("store.poll_clerking_job")
        with self._lock:
            ids = _ids_in(self.root / "queue" / str(clerk))
            if not ids:
                return None
            obj = _read_json(self.root / "queue" / str(clerk) / f"{ids[0]}.json")
            return ClerkingJob.from_obj(obj)

    def lease_clerking_job(self, clerk, lease_seconds, now=None, owner=None):
        chaos.fail("store.poll_clerking_job")
        now = time.time() if now is None else now
        with self._lock, self._dir_lock(self.root / "queue" / str(clerk)):
            qdir = self.root / "queue" / str(clerk)
            # lease files are dot-prefixed so _ids_in never mistakes one
            # for a queued job; they survive restarts like everything else.
            # The dir lock makes the expiry-check -> lease-stamp sequence
            # atomic across fleet worker processes: two sdad's polling one
            # clerk identity cannot both stamp the same job
            for job_id in _ids_in(qdir):
                lease = _read_json(qdir / f".lease-{job_id}.json")
                if lease is not None and lease["expires"] > now:
                    continue  # actively leased by another worker
                obj = _read_json(qdir / f"{job_id}.json")
                if obj is None:
                    continue  # done-move by a peer since the listing
                if lease is not None:
                    metrics.count("server.job.reissued")
                expires = now + lease_seconds
                _write_json(qdir / f".lease-{job_id}.json",
                            {"expires": expires, "node": owner})
                return ClerkingJob.from_obj(obj), expires
            return None

    def release_clerking_job_lease(self, clerk, job, expires=None):
        # graceful drain: unlink the dot-lease file so any process's next
        # poll sees the job unleased; done jobs have left the queue dir.
        # Compare-and-release on the expiry instant: a lapsed lease
        # re-granted to a peer carries a NEW expiry and is left alone
        with self._lock, self._dir_lock(self.root / "queue" / str(clerk)):
            qdir = self.root / "queue" / str(clerk)
            if not (qdir / f"{job}.json").exists():
                return False
            lease_path = qdir / f".lease-{job}.json"
            lease = _read_json(lease_path)
            if lease is None or (expires is not None
                                 and lease["expires"] != expires):
                return False
            lease_path.unlink(missing_ok=True)
            return True

    def recall_clerking_job_leases(self, node_id):
        # the dead-node recovery step: unlink every lease file the dead
        # worker stamped, per clerk dir under that dir's flock (the same
        # arbitration the grant path takes, so a racing peer sweeper and
        # a racing poll serialize cleanly)
        recalled = 0
        base = self.root / "queue"
        with self._lock:
            if not base.is_dir():
                return 0
            for clerk_dir in sorted(p for p in base.iterdir() if p.is_dir()):
                with self._dir_lock(clerk_dir):
                    for job_id in _ids_in(clerk_dir):
                        lease_path = clerk_dir / f".lease-{job_id}.json"
                        lease = _read_json(lease_path)
                        if lease is None or lease.get("node") != node_id:
                            continue
                        lease_path.unlink(missing_ok=True)
                        recalled += 1
        return recalled

    def hedge_clerking_job(self, clerk, suspect_nodes, lease_seconds,
                           now=None, owner=None):
        # hedged execution: overwrite a SUSPECT holder's ACTIVE lease
        # with this caller's, under the clerk dir's flock (two hedging
        # processes race the same read-check-write; one wins). The
        # original holder may still finish — the done-move is what
        # commits, exactly once
        suspects = set(str(n) for n in suspect_nodes)
        if not suspects:
            return None
        now = time.time() if now is None else now
        with self._lock, self._dir_lock(self.root / "queue" / str(clerk)):
            qdir = self.root / "queue" / str(clerk)
            for job_id in _ids_in(qdir):
                lease = _read_json(qdir / f".lease-{job_id}.json")
                if lease is None or lease["expires"] <= now:
                    continue  # unleased/lapsed: the normal poll covers it
                if str(lease.get("node")) not in suspects:
                    continue
                obj = _read_json(qdir / f"{job_id}.json")
                if obj is None:
                    continue  # done-move by a peer since the listing
                expires = now + lease_seconds
                _write_json(qdir / f".lease-{job_id}.json",
                            {"expires": expires, "node": owner})
                return ClerkingJob.from_obj(obj), expires
            return None

    # -- fleet heartbeats ---------------------------------------------------
    def put_worker_heartbeat(self, doc):
        with self._lock:
            _write_json(self.root / "heartbeats" / f"{doc['node']}.json", doc)

    def get_worker_heartbeat(self, node):
        with self._lock:
            return _read_json(self.root / "heartbeats" / f"{node}.json")

    def list_worker_heartbeats(self):
        with self._lock:
            out = []
            base = self.root / "heartbeats"
            if not base.is_dir():
                return out
            for name in sorted(p.stem for p in base.glob("*.json")
                               if not p.name.startswith(".")):
                doc = _read_json(base / f"{name}.json")
                if doc is not None:
                    out.append(doc)
            return out

    def transition_worker_state(self, node, from_states, doc):
        # single-winner CAS across fleet worker processes: the dir flock
        # makes the read-check-write atomic (same shape as
        # transition_round_state)
        with self._lock, self._dir_lock(self.root / "heartbeats"):
            path = self.root / "heartbeats" / f"{node}.json"
            current = _read_json(path)
            if current is None or current.get("state") not in from_states:
                return False
            _write_json(path, doc)
            return True

    def list_snapshot_jobs(self, snapshot):
        # the sweeper's dead-clerk census: walk both queue trees, decode
        # only the snapshot field to filter — committee-width work, and
        # sweeps are rare control-plane reads
        with self._lock:
            out = []
            for sub, done in (("queue", False), ("done", True)):
                base = self.root / sub
                if not base.is_dir():
                    continue
                for clerk_dir in sorted(p for p in base.iterdir()
                                        if p.is_dir()):
                    for job_id in _ids_in(clerk_dir):
                        obj = _read_json(clerk_dir / f"{job_id}.json")
                        if obj is None or obj.get("snapshot") != str(snapshot):
                            continue
                        lease = 0.0
                        if not done:
                            lease_doc = _read_json(
                                clerk_dir / f".lease-{job_id}.json")
                            if lease_doc is not None:
                                lease = float(lease_doc.get("expires", 0.0))
                        out.append((ClerkingJobId(job_id),
                                    AgentId(clerk_dir.name), done, lease))
            return out

    def get_clerking_job(self, clerk, job):
        with self._lock:
            for sub in ("queue", "done"):
                obj = _read_json(self.root / sub / str(clerk) / f"{job}.json")
                if obj is not None:
                    return ClerkingJob.from_obj(obj)
            return None

    def create_clerking_result(self, result):
        chaos.fail("store.create_clerking_result")
        # the clerk dir flock makes the read-check-commit atomic across
        # fleet worker PROCESSES (the in-process lock cannot): when a
        # hedged copy races the original holder, exactly one performs the
        # queue->done move — the second finds the queue file gone, sees
        # the done marker, and drops its duplicate on the floor
        with self._lock, \
                self._dir_lock(self.root / "queue" / str(result.clerk)):
            queue_path = self.root / "queue" / str(result.clerk) / f"{result.job}.json"
            obj = _read_json(queue_path)
            if obj is None:
                if (self.root / "done" / str(result.clerk) / f"{result.job}.json").exists():
                    return  # duplicate result upload: idempotent
                raise NotFound("job not found for clerk")
            job = ClerkingJob.from_obj(obj)
            _write_json(
                self.root / "results" / str(job.snapshot) / f"{result.job}.json",
                result.to_obj(),
            )
            _write_json(self.root / "done" / str(result.clerk) / f"{job.id}.json", obj)
            queue_path.unlink(missing_ok=True)
            queue_path.with_name(f".lease-{result.job}.json").unlink(missing_ok=True)

    def purge_snapshot_jobs(self, snapshot):
        # the retention/delete cascade's job-store half: walk both queue
        # trees removing the snapshot's job files (and their dot-lease
        # files), then drop the whole results directory. Per-clerk dirs
        # are purged under their flock — the same arbitration the
        # grant/commit paths take, so a racing poll serializes cleanly
        import shutil

        removed = 0
        with self._lock:
            for sub in ("queue", "done"):
                base = self.root / sub
                if not base.is_dir():
                    continue
                for clerk_dir in sorted(p for p in base.iterdir()
                                        if p.is_dir()):
                    with self._dir_lock(clerk_dir):
                        for job_id in _ids_in(clerk_dir):
                            obj = _read_json(clerk_dir / f"{job_id}.json")
                            if obj is None \
                                    or obj.get("snapshot") != str(snapshot):
                                continue
                            (clerk_dir / f"{job_id}.json").unlink(
                                missing_ok=True)
                            (clerk_dir / f".lease-{job_id}.json").unlink(
                                missing_ok=True)
                            removed += 1
            results_dir = self.root / "results" / str(snapshot)
            if results_dir.is_dir():
                removed += len(_ids_in(results_dir))
                shutil.rmtree(results_dir, ignore_errors=True)
        return removed

    def list_results(self, snapshot):
        with self._lock:
            return [ClerkingJobId(i) for i in _ids_in(self.root / "results" / str(snapshot))]

    def get_result(self, snapshot, job):
        with self._lock:
            obj = _read_json(self.root / "results" / str(snapshot) / f"{job}.json")
            return None if obj is None else ClerkingResult.from_obj(obj)
