"""Server core: raw operations + the ACL-enforcing service wrapper.

Reference: server/src/server.rs. ``SdaServer`` is a thin delegation over the
four store interfaces plus auth-token checking; ``SdaServerService`` is the
``SdaService`` implementation that guards every mutating call with
"caller is the owner" checks (acl_agent_is, :203-209) and recipient-only /
clerk-only rules (:270-360). The server holds no in-memory protocol state —
every object is durable in a store the moment it exists, which is the
framework's checkpoint/resume story (SURVEY.md §5.4).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    InvalidCredentials,
    InvalidRequest,
    NotFound,
    Participation,
    ParticipationConflict,
    PermissionDenied,
    Pong,
    Profile,
    SdaService,
    Signed,
    Snapshot,
    SnapshotId,
    SnapshotResult,
    SnapshotStatus,
)
from .. import obs
from ..utils import metrics
from . import lifecycle
from . import snapshot as snapshot_mod
from .wakeup import JobWakeup, clamp_wait, longpoll_tick
from .stores import (
    AgentsStore,
    AggregationsStore,
    AuthToken,
    AuthTokensStore,
    ClerkingJobsStore,
)


class SdaServer:
    """Raw server operations over pluggable stores (server.rs:5-191)."""

    def __init__(
        self,
        agents_store: AgentsStore,
        auth_tokens_store: AuthTokensStore,
        aggregation_store: AggregationsStore,
        clerking_job_store: ClerkingJobsStore,
    ):
        self.agents_store = agents_store
        self.auth_tokens_store = auth_tokens_store
        self.aggregation_store = aggregation_store
        self.clerking_job_store = clerking_job_store
        #: opt-in: homomorphically combine each clerk's ciphertext column at
        #: snapshot time when the committee scheme is PackedPaillier
        #: (snapshot.py premixing) — clerk downloads shrink ~N x
        self.premix_paillier = False
        #: opt-in (like premixing): when set, a polled clerking job is
        #: LEASED for this many seconds — invisible to the clerk's other
        #: workers while held, reissued to the next live poller once the
        #: lease expires without a result. None keeps the reference's
        #: visible-poll semantics (the job is returned on every poll).
        self.clerking_lease_seconds: Optional[float] = None
        # serializes the snapshot pipeline WITHIN this process: a timed-out
        # client retrying a slow snapshot POST must queue behind the
        # original, not race its freeze/enqueue. ACROSS processes the
        # store-level single-winner freeze/record inserts arbitrate
        # (snapshot.py, contended-idempotency contract)
        self._snapshot_lock = threading.Lock()
        #: node identity in a fleet (sda_tpu/server/fleet.py); None when
        #: running solo. Flows into span attributes, /statusz, /metrics
        #: labels and the X-SDA-Node response header.
        self.node_id: Optional[str] = None
        # leases THIS worker granted and has not yet seen a result for —
        # what graceful drain hands back to the fleet (release_held_leases)
        self._granted_leases: dict = {}
        self._granted_lock = threading.Lock()
        #: long-poll push plane (server/wakeup.py): snapshot fan-out,
        #: drain lease handback and dead-worker lease recall notify the
        #: clerks that might now have work, so a parked
        #: ``GET /v1/clerking-jobs?wait=S`` wakes immediately instead of
        #: polling the store. Per-process: cross-worker events degrade to
        #: the long-poll re-check tick.
        self.job_wakeup = JobWakeup()
        # enqueue stamps for the server.job.pickup histogram: job id ->
        # monotonic enqueue time, observed (and popped) when THIS worker
        # grants the lease. A job picked up via a fleet peer has no stamp
        # here — counted, not observed (the latency is unknowable locally).
        self._job_enqueued_at: dict = {}
        self._job_enqueued_lock = threading.Lock()
        #: straggler hedging (server/health.py): when set to a staleness
        #: threshold in seconds, an empty lease poll may hedge a job whose
        #: holder's heartbeat is that stale — the hedged copy races the
        #: suspect, result commit stays single-winner. None = off.
        self.hedge_suspect_after_s: Optional[float] = None
        # suspect-set cache: one heartbeat census per poll would make the
        # hot empty-poll path a store scan; a short TTL is plenty (the
        # detector's own cadence is coarser than this)
        self._suspects_cache: tuple = (0.0, [])
        #: per-phase round deadlines for the lifecycle supervisor
        #: (lifecycle.py); the default (all None) tracks states but never
        #: expires anything — arm via sdad --round-collect-deadline /
        #: --round-clerk-deadline and sweep with --round-sweep
        self.round_deadlines = lifecycle.RoundDeadlines()
        #: retention policy for terminal rounds (service/retention.py);
        #: None keeps every revealed/failed round forever (the
        #: pre-service behavior) — arm via sdad --retain-revealed /
        #: --retain-failed and sweep with --round-sweep
        self.retention_policy = None

    # -- health ------------------------------------------------------------
    def ping(self) -> Pong:
        self.agents_store.ping()
        return Pong(running=True)

    # -- agents ------------------------------------------------------------
    def create_agent(self, agent: Agent) -> None:
        self.agents_store.create_agent(agent)

    def get_agent(self, id: AgentId) -> Optional[Agent]:
        return self.agents_store.get_agent(id)

    def upsert_profile(self, profile: Profile) -> None:
        self.agents_store.upsert_profile(profile)

    def get_profile(self, agent: AgentId) -> Optional[Profile]:
        return self.agents_store.get_profile(agent)

    def create_encryption_key(self, key: Signed) -> None:
        self.agents_store.create_encryption_key(key)

    def get_encryption_key(self, key: EncryptionKeyId) -> Optional[Signed]:
        return self.agents_store.get_encryption_key(key)

    # -- aggregations ------------------------------------------------------
    def list_aggregations(self, filter=None, recipient=None) -> List[AggregationId]:
        return self.aggregation_store.list_aggregations(filter, recipient)

    def get_aggregation(self, aggregation: AggregationId) -> Optional[Aggregation]:
        return self.aggregation_store.get_aggregation(aggregation)

    def get_committee(self, aggregation: AggregationId) -> Optional[Committee]:
        return self.aggregation_store.get_committee(aggregation)

    def create_aggregation(self, aggregation: Aggregation) -> None:
        self.aggregation_store.create_aggregation(aggregation)
        # lifecycle: the aggregation's round starts collecting the moment
        # the resource exists (the supervisor's state machine is durable
        # in the same store the aggregation is)
        lifecycle.note_collecting(self, aggregation)

    def delete_aggregation(self, aggregation: AggregationId) -> None:
        """Full cascade, not just the aggregation doc: every artifact the
        round ever produced leaves both stores (the aggregation store's
        own cascade covers round doc, participations + owner markers,
        snapshots, freezes and mask chunks; the clerking-job store purge
        covers jobs, leases and results per snapshot). Retention
        (service/retention.py) depends on this being a FULL purge — a
        long-running service deleting revealed rounds must leave store
        size flat, not leak job payloads forever."""
        self.purge_aggregation(aggregation)

    def purge_aggregation(self, aggregation: AggregationId) -> dict:
        """The delete/retention cascade; returns ``{"snapshots", "jobs"}``
        tallies (jobs = clerking jobs + results removed). Idempotent —
        purging an unknown or already-purged aggregation removes
        nothing."""
        snapshots = self.aggregation_store.list_snapshots(aggregation)
        jobs = 0
        for snapshot_id in snapshots:
            jobs += int(self.clerking_job_store.purge_snapshot_jobs(
                snapshot_id) or 0)
        self.aggregation_store.delete_aggregation(aggregation)
        if jobs:
            metrics.count("server.purge.jobs", jobs)
        return {"snapshots": len(snapshots), "jobs": jobs}

    def suggest_committee(self, aggregation: AggregationId) -> List[ClerkCandidate]:
        if self.aggregation_store.get_aggregation(aggregation) is None:
            raise NotFound("aggregation not found")
        return self.agents_store.suggest_committee()

    def create_committee(self, committee: Committee) -> None:
        agg = self.aggregation_store.get_aggregation(committee.aggregation)
        if agg is None:
            raise NotFound("aggregation not found")
        expected = agg.committee_sharing_scheme.output_size
        if expected != len(committee.clerks_and_keys):
            raise InvalidRequest(
                f"expected {expected} clerks in the committee, "
                f"found {len(committee.clerks_and_keys)} instead"
            )
        self.aggregation_store.create_committee(committee)

    # -- participation -----------------------------------------------------
    def create_participation(self, participation: Participation) -> None:
        with obs.span("server.create_participation",
                      attributes={"participation": str(participation.id),
                                  "aggregation":
                                  str(participation.aggregation)}
                      ) as span:
            try:
                created = self.aggregation_store.create_participation(
                    participation)
            except ParticipationConflict:
                # detected equivocation / double participation: counted
                # here (every backend raises through this seam), mapped
                # to HTTP 409 by the transport
                span.set_attribute("conflict", True)
                metrics.count("server.participation.equivocation")
                raise
            if created is False:
                # byte-identical replay (crash/retry or journal resume):
                # idempotent success, nothing changed — tagged so a
                # forensics pass counts distinct participations exactly
                span.set_attribute("replayed", True)
                metrics.count("server.participation.replayed")
            else:
                # True, or None from a pre-exactly-once third-party store
                metrics.count("server.participation.created")

    # -- status / snapshots ------------------------------------------------
    def get_aggregation_status(
        self, aggregation: AggregationId
    ) -> Optional[AggregationStatus]:
        agg = self.aggregation_store.get_aggregation(aggregation)
        if agg is None:
            return None
        threshold = agg.committee_sharing_scheme.reconstruction_threshold
        snapshots = []
        for sid in self.aggregation_store.list_snapshots(aggregation):
            count = len(self.clerking_job_store.list_results(sid))
            snapshots.append(
                SnapshotStatus(
                    id=sid,
                    number_of_clerking_results=count,
                    result_ready=count >= threshold,
                )
            )
        return AggregationStatus(
            aggregation=aggregation,
            number_of_participations=self.aggregation_store.count_participations(aggregation),
            snapshots=snapshots,
        )

    def create_snapshot(self, snapshot: Snapshot) -> None:
        with obs.span("server.snapshot",
                      attributes={"snapshot": str(snapshot.id),
                                  "aggregation": str(snapshot.aggregation)}):
            if snapshot_mod.snapshot(self, snapshot):
                metrics.count("server.snapshot.created")

    # -- clerking ----------------------------------------------------------
    def note_jobs_enqueued(self, job_ids) -> None:
        """Stamp the enqueue instant of freshly fanned-out clerking jobs
        (snapshot.py) so the grant path can observe enqueue->lease latency
        as the ``server.job.pickup`` histogram — the metric the long-poll
        plane exists to collapse (docs/load.md). Bounded: past the size
        threshold, aged-out stamps (jobs granted via a peer) are swept
        and the oldest evicted, so fleet-mode fan-out faster than the
        age cutoff still can't grow the table or turn every fan-out into
        an O(table) rebuild."""
        now = time.monotonic()
        with self._job_enqueued_lock:
            if len(self._job_enqueued_at) >= 4096:
                cutoff = now - 600.0
                self._job_enqueued_at = {
                    j: t for j, t in self._job_enqueued_at.items()
                    if t > cutoff
                }
                overflow = len(self._job_enqueued_at) - 4096
                if overflow > 0:
                    stamps = self._job_enqueued_at
                    for job in sorted(stamps, key=stamps.get)[:overflow]:
                        del stamps[job]
            for job_id in job_ids:
                self._job_enqueued_at[job_id] = now

    def _observe_pickup(self, job_id) -> None:
        with self._job_enqueued_lock:
            enqueued = self._job_enqueued_at.pop(job_id, None)
        if enqueued is not None:
            metrics.observe("server.job.pickup", time.monotonic() - enqueued)
        else:
            # granted here, enqueued elsewhere (a fleet peer's fan-out or
            # a pre-restart round): the latency is unknowable locally
            metrics.count("server.job.pickup_unstamped")

    def sweep_granted_leases(self, now: Optional[float] = None) -> int:
        """Drop lapsed entries from the per-worker granted-lease table —
        a result posted via a PEER worker (or a lapsed lease a peer
        reissued) never comes back through this worker's create_result,
        so lapsed entries would otherwise accumulate forever. Shared by
        both HTTP planes (grant path + /statusz), so fleet-mode lease
        accounting cannot drift between implementations. Returns how many
        entries were swept."""
        now = time.time() if now is None else now
        with self._granted_lock:
            before = len(self._granted_leases)
            self._granted_leases = {
                j: ce for j, ce in self._granted_leases.items()
                if ce[1] > now
            }
            return before - len(self._granted_leases)

    def held_lease_count(self) -> int:
        """Live (unlapsed) leases this worker currently holds — the
        shared /statusz figure for both HTTP planes."""
        self.sweep_granted_leases()
        with self._granted_lock:
            return len(self._granted_leases)

    def _suspect_nodes(self) -> list:
        """Fleet workers that currently look unhealthy (stale heartbeat or
        an explicit suspect mark) — the hedging plane's shadow-execution
        targets. TTL-cached so empty polls stay cheap."""
        if self.hedge_suspect_after_s is None:
            return []
        now = time.monotonic()
        cached_at, suspects = self._suspects_cache
        if now - cached_at < 0.5:
            return suspects
        from . import health

        suspects = health.suspect_nodes(
            self.clerking_job_store, self.hedge_suspect_after_s,
            exclude=self.node_id)
        self._suspects_cache = (now, suspects)
        return suspects

    def poll_clerking_job(self, clerk: AgentId) -> Optional[ClerkingJob]:
        with obs.span("server.poll_job",
                      attributes={"clerk": str(clerk)}) as poll_span:
            if self.clerking_lease_seconds is not None:
                leased = self.clerking_job_store.lease_clerking_job(
                    clerk, self.clerking_lease_seconds, owner=self.node_id
                )
                if leased is None:
                    # straggler hedging: nothing unleased, but a job held
                    # by a SUSPECT worker may be hedged — the poller runs
                    # a speculative copy; whichever result lands first
                    # wins the single-winner commit, so a slow-but-alive
                    # holder costs duplicated work, never correctness
                    suspects = self._suspect_nodes()
                    if suspects:
                        leased = self.clerking_job_store.hedge_clerking_job(
                            clerk, suspects, self.clerking_lease_seconds,
                            owner=self.node_id)
                        if leased is not None:
                            poll_span.set_attribute("hedged", True)
                            metrics.count("server.job.hedged")
                            obs.add_event("job.hedged",
                                          job=str(leased[0].id),
                                          suspects=",".join(suspects))
                job = None
                if leased is not None:
                    job, expires = leased
                    poll_span.set_attribute("leased", True)
                    metrics.count("server.job.leased")
                    with self._granted_lock:
                        oversized = len(self._granted_leases) >= 256
                    if oversized:
                        self.sweep_granted_leases()
                    with self._granted_lock:
                        self._granted_leases[job.id] = (clerk, expires)
            else:
                job = self.clerking_job_store.poll_clerking_job(clerk)
            if job is not None:
                poll_span.set_attribute("job", str(job.id))
                # enqueue->lease latency: the polling-vs-long-poll headline
                self._observe_pickup(job.id)
            metrics.count("server.job.polled" if job else "server.job.poll_empty")
            return job

    def get_clerking_job(
        self, clerk: AgentId, job: ClerkingJobId
    ) -> Optional[ClerkingJob]:
        return self.clerking_job_store.get_clerking_job(clerk, job)

    def create_clerking_result(
        self, result: ClerkingResult, job: Optional[ClerkingJob] = None
    ) -> None:
        with obs.span("server.create_result",
                      attributes={"job": str(result.job)}):
            self.clerking_job_store.create_clerking_result(result)
        with self._granted_lock:
            self._granted_leases.pop(result.job, None)
        metrics.count("server.clerking_result.created")
        # lifecycle: a full committee's worth of results flips the round
        # to ready (threshold-satisfying partial sets stay clerking —
        # the sweeper decides whether the stragglers are dead). The
        # service wrapper already fetched the (payload-heavy) job for its
        # ACL check and passes it down; only direct core callers pay the
        # extra read.
        if job is None:
            job = self.clerking_job_store.get_clerking_job(
                result.clerk, result.job)
        if job is not None:
            lifecycle.note_result(self, job)

    def release_held_leases(self) -> int:
        """Graceful-drain step: hand every clerking-job lease this worker
        granted (and has no result for yet) back to the shared store, so
        a fleet peer's next poll reissues the job immediately instead of
        waiting out the visibility timeout. Returns how many leases were
        actually released (already-expired or just-completed ones are
        not)."""
        with self._granted_lock:
            held = list(self._granted_leases.items())
            self._granted_leases.clear()
        released = 0
        now = time.time()
        for job_id, (clerk, expires) in held:
            if expires <= now:
                # lapsed: a peer may already hold a fresh lease on this
                # job — it is not ours to release anymore
                continue
            try:
                if self.clerking_job_store.release_clerking_job_lease(
                    clerk, job_id, expires=expires
                ):
                    released += 1
            except Exception:  # drain must not die on one store hiccup
                continue
        if released:
            metrics.count("server.job.lease_released_on_drain", released)
            # same-process clerks parked on a long-poll should pick the
            # handed-back work up immediately; fleet peers' parked polls
            # catch it on their re-check tick
            self.job_wakeup.notify(clerk for _, (clerk, _) in held)
        return released

    def get_snapshot_result(
        self, aggregation: AggregationId, snapshot: SnapshotId
    ) -> Optional[SnapshotResult]:
        # the snapshot must exist under THIS aggregation — otherwise a caller
        # could read another aggregation's snapshot artifacts by id
        if self.aggregation_store.get_snapshot(aggregation, snapshot) is None:
            return None
        results = []
        for job_id in self.clerking_job_store.list_results(snapshot):
            result = self.clerking_job_store.get_result(snapshot, job_id)
            if result is None:
                raise NotFound("inconsistent storage")
            results.append(result)
        # lifecycle: a reconstruction-grade fetch is the reveal — the
        # round (ready, or degraded-completing-from-quorum) is done
        lifecycle.note_revealed(self, aggregation, snapshot, len(results))
        return SnapshotResult(
            snapshot=snapshot,
            number_of_participations=self.aggregation_store.count_participations_snapshot(
                aggregation, snapshot
            ),
            clerk_encryptions=results,
            recipient_encryptions=self.aggregation_store.get_snapshot_mask(snapshot),
        )

    def get_round_status(self, aggregation: AggregationId):
        """Lifecycle state of the aggregation's current round (the stored
        state-machine document plus the live result count), or None when
        nothing is tracked (pre-supervisor data)."""
        return lifecycle.round_status(self, aggregation)

    # -- auth tokens (used by the HTTP layer) ------------------------------
    def upsert_auth_token(self, token: AuthToken) -> None:
        self.auth_tokens_store.upsert_auth_token(token)

    def check_auth_token(self, token: AuthToken) -> Agent:
        import hmac

        stored = self.auth_tokens_store.get_auth_token(token.id)
        if stored is not None and hmac.compare_digest(
            stored.body.encode(), token.body.encode()
        ):
            agent = self.agents_store.get_agent(token.id)
            if agent is None:
                raise NotFound("agent not found")
            return agent
        raise InvalidCredentials()

    def delete_auth_token(self, agent: AgentId) -> None:
        self.auth_tokens_store.delete_auth_token(agent)


def _acl_agent_is(caller: Agent, agent_id: AgentId) -> None:
    """Every mutating call is guarded by caller identity (server.rs:203-209)."""
    if caller.id != agent_id:
        raise PermissionDenied()


class SdaServerService(SdaService):
    """ACL-enforcing SdaService over an SdaServer (server.rs:193-361)."""

    def __init__(self, server: SdaServer):
        self.server = server

    def ping(self) -> Pong:
        return self.server.ping()

    # -- agent service -----------------------------------------------------
    def create_agent(self, caller, agent):
        _acl_agent_is(caller, agent.id)
        self.server.create_agent(agent)

    def get_agent(self, caller, agent):
        return self.server.get_agent(agent)  # public, no acl

    def upsert_profile(self, caller, profile):
        _acl_agent_is(caller, profile.owner)
        self.server.upsert_profile(profile)

    def get_profile(self, caller, owner):
        return self.server.get_profile(owner)  # public, no acl

    def create_encryption_key(self, caller, key):
        _acl_agent_is(caller, key.signer)
        self.server.create_encryption_key(key)

    def get_encryption_key(self, caller, key):
        return self.server.get_encryption_key(key)  # public, no acl

    # -- aggregation service -----------------------------------------------
    def list_aggregations(self, caller, filter=None, recipient=None):
        return self.server.list_aggregations(filter, recipient)

    def get_aggregation(self, caller, aggregation):
        return self.server.get_aggregation(aggregation)

    def get_committee(self, caller, aggregation):
        return self.server.get_committee(aggregation)

    # -- recipient service -------------------------------------------------
    def _recipient_only(self, caller: Agent, aggregation: AggregationId) -> Aggregation:
        agg = self.server.get_aggregation(aggregation)
        if agg is None:
            raise NotFound("no aggregation found")
        _acl_agent_is(caller, agg.recipient)
        return agg

    def create_aggregation(self, caller, aggregation):
        _acl_agent_is(caller, aggregation.recipient)
        self.server.create_aggregation(aggregation)

    def delete_aggregation(self, caller, aggregation):
        self._recipient_only(caller, aggregation)
        self.server.delete_aggregation(aggregation)

    def suggest_committee(self, caller, aggregation):
        self._recipient_only(caller, aggregation)
        return self.server.suggest_committee(aggregation)

    def create_committee(self, caller, committee):
        self._recipient_only(caller, committee.aggregation)
        self.server.create_committee(committee)

    def get_aggregation_status(self, caller, aggregation):
        self._recipient_only(caller, aggregation)
        return self.server.get_aggregation_status(aggregation)

    def create_snapshot(self, caller, snapshot):
        self._recipient_only(caller, snapshot.aggregation)
        self.server.create_snapshot(snapshot)

    def get_snapshot_result(self, caller, aggregation, snapshot):
        self._recipient_only(caller, aggregation)
        return self.server.get_snapshot_result(aggregation, snapshot)

    def get_round_status(self, caller, aggregation):
        # recipient-only like status: the round's failure diagnosis names
        # dead clerks, which is committee topology the public cannot see
        self._recipient_only(caller, aggregation)
        return self.server.get_round_status(aggregation)

    # -- participation service ---------------------------------------------
    def create_participation(self, caller, participation):
        _acl_agent_is(caller, participation.participant)
        self.server.create_participation(participation)

    # -- clerking service --------------------------------------------------
    def get_clerking_job(self, caller, clerk):
        _acl_agent_is(caller, clerk)
        return self.server.poll_clerking_job(clerk)

    def await_clerking_job(self, caller, clerk, wait_s: float = 0.0):
        """Long-poll flavor of :meth:`get_clerking_job`: block up to
        ``wait_s`` (clamped to the long-poll bound) for a job to appear,
        parked on the server's job wakeup between store checks — the
        in-process mirror of ``GET /v1/clerking-jobs?wait=S``. Returns
        the job, or None when the wait expires empty. Not part of the
        ``SdaService`` seam: callers probe for it with ``getattr`` and
        fall back to plain polling (old peers, third-party seams)."""
        _acl_agent_is(caller, clerk)
        give_up = time.monotonic() + clamp_wait(wait_s)
        tick = longpoll_tick()
        while True:
            sub = self.server.job_wakeup.subscribe(clerk)
            try:
                # poll AFTER subscribing so an enqueue between the two
                # cannot be missed (it fires the live subscription)
                job = self.server.poll_clerking_job(clerk)
                remaining = give_up - time.monotonic()
                if job is not None or remaining <= 0:
                    return job
                sub.wait(min(tick, remaining))
            finally:
                self.server.job_wakeup.unsubscribe(sub)

    def create_clerking_result(self, caller, result):
        # double-check the job really belongs to the caller — a spoofed
        # result.clerk must not let one clerk overwrite another's work
        # (server.rs:345-360)
        job = self.server.get_clerking_job(result.clerk, result.job)
        if job is None:
            raise NotFound("job not found")
        _acl_agent_is(caller, job.clerk)
        self.server.create_clerking_result(result, job=job)
