"""Fleet health plane: worker heartbeats + the gray-failure detector.

PR 6's fleet only recovered held clerking-job leases through GRACEFUL
drain (SIGTERM → ``release_held_leases``), and PR 7's supervisor only
noticed a missing clerk after the full clerking deadline lapsed. A
SIGKILL'd worker, a kernel panic, or a partition between one worker and
the backend therefore stalled every lease that worker held until its
visibility timeout — minutes of round-stall for a millisecond failure.

This module closes that gap with the standard heartbeat/φ-style failure
detector shape (Bonawitz et al., MLSys 2019 single out exactly this
flakiness as what deployment must absorb):

- every ``sdad`` worker runs a :class:`HeartbeatWriter` that upserts a
  heartbeat row into the SHARED store (``put_worker_heartbeat``) every
  ``interval_s`` — the store arbitrates, so no gossip mesh is needed;
- the :class:`~sda_tpu.server.lifecycle.RoundSweeper` calls
  :func:`sweep_worker_health` each tick: a worker whose heartbeat is
  older than ``suspect_after_s`` is declared **suspect** (still maybe
  alive — straggler hedging may shadow its held jobs, ``server/core.py``),
  older than ``dead_after_s`` is declared **dead** and its held
  clerking-job leases are proactively RECALLED
  (``recall_clerking_job_leases``) so any peer's next poll reissues the
  work immediately instead of waiting out per-job lease expiry;
- both declarations are single-winner CAS transitions on the heartbeat
  row (``transition_worker_state`` — the same conditional-write contract
  as the PR 7 ``rounds`` table), so N sweeping workers recall a dead
  node's leases exactly once between them;
- a revived worker (partition healed) simply resumes writing ``alive``
  heartbeats — its recalled jobs may have been re-executed by a peer,
  which is safe because result commit is store-arbitrated single-winner
  (duplicate partial sums are impossible; docs/robustness.md).

Observability: ``server.fleet.{alive,suspect,dead}`` gauges,
``server.fleet.suspect``/``server.fleet.dead`` transition counters,
``server.job.lease_recalled`` recall tally, span events per transition,
and the ``fleet_health`` table on ``/statusz`` / ``sda-fleet``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from .. import obs
from ..utils import metrics

log = logging.getLogger(__name__)

#: Heartbeat states. ``alive`` is written by the worker itself; the
#: detector CASes ``alive -> suspect -> dead``; a clean drain writes the
#: terminal ``drained`` so the detector never has to diagnose it.
STATES = ("alive", "suspect", "dead", "drained")


def heartbeat_doc(node_id: str, *, state: str = "alive", seq: int = 0,
                  started_at: Optional[float] = None,
                  now: Optional[float] = None) -> dict:
    now = time.time() if now is None else now
    return {
        "node": str(node_id),
        "state": state,
        "ts": now,
        "seq": int(seq),
        "started_at": now if started_at is None else started_at,
    }


class HeartbeatWriter:
    """Background thread: one ``alive`` heartbeat row per ``interval_s``,
    written through the shared job store; a clean stop writes the
    terminal ``drained`` row so peers never diagnose this worker."""

    def __init__(self, store, node_id: str, interval_s: float = 1.0):
        self.store = store
        self.node_id = str(node_id)
        self.interval_s = float(interval_s)
        self._seq = 0
        self._started_at = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self, now: Optional[float] = None) -> None:
        """One heartbeat, synchronously (also used as the first beat so
        the row exists before the worker serves traffic)."""
        self._seq += 1
        self.store.put_worker_heartbeat(heartbeat_doc(
            self.node_id, seq=self._seq, started_at=self._started_at,
            now=now))

    def start(self) -> "HeartbeatWriter":
        self.beat()
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeat-{self.node_id}", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception:  # a beat lost to a store hiccup is just a
                # stale-r heartbeat; the writer must outlive it
                log.exception("heartbeat write failed; retrying next tick")
                metrics.count("server.fleet.heartbeat_error")

    def stop(self, drained: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if drained:
            try:
                self._seq += 1
                self.store.put_worker_heartbeat(heartbeat_doc(
                    self.node_id, state="drained", seq=self._seq,
                    started_at=self._started_at))
            except Exception:
                log.debug("drained heartbeat write failed", exc_info=True)


def fleet_health_report(store, now: Optional[float] = None) -> dict:
    """The live health table (``/statusz``, ``sda-fleet``): every known
    worker with its state and heartbeat age."""
    now = time.time() if now is None else now
    try:
        docs = store.list_worker_heartbeats()
    except Exception:
        return {}
    return {
        doc["node"]: {
            "state": doc.get("state"),
            "age_s": round(max(0.0, now - float(doc.get("ts") or 0.0)), 3),
            "seq": doc.get("seq"),
        }
        for doc in docs
    }


def suspect_nodes(store, suspect_after_s: float,
                  now: Optional[float] = None,
                  exclude: Optional[str] = None) -> List[str]:
    """Workers that LOOK unhealthy right now — explicitly marked suspect,
    or with a stale-but-not-yet-diagnosed heartbeat. The hedging plane
    reads this (it must not wait for a sweeper to run the CAS); ``dead``
    nodes are excluded because their leases are already recalled."""
    now = time.time() if now is None else now
    out = []
    try:
        docs = store.list_worker_heartbeats()
    except Exception:
        return out
    for doc in docs:
        node = doc.get("node")
        if node is None or node == exclude:
            continue
        state = doc.get("state")
        stale = now - float(doc.get("ts") or 0.0)
        if state == "suspect" or (state == "alive"
                                  and stale > suspect_after_s):
            out.append(node)
    return sorted(out)


def sweep_worker_health(server, now: Optional[float] = None, *,
                        suspect_after_s: float,
                        dead_after_s: float) -> List[dict]:
    """One failure-detector pass over the shared heartbeat table; returns
    the transitions THIS sweeper won (the fleet CAS contract: N sweepers
    race, each declaration happens exactly once fleet-wide).

    A worker is *suspect* after ``suspect_after_s`` without a beat and
    *dead* after ``dead_after_s`` — crossing straight to dead is allowed
    (a sweeper that was itself stalled must not need two passes). The
    winner of the dead CAS recalls the node's held clerking-job leases,
    turning a SIGKILL'd or partitioned worker from a round-stalling event
    into a bounded-MTTR blip."""
    now = time.time() if now is None else now
    store = server.clerking_job_store
    actions: List[dict] = []
    try:
        docs = store.list_worker_heartbeats()
    except Exception:
        log.exception("heartbeat census failed; skipping health sweep")
        return actions
    tally = {state: 0 for state in STATES}
    own = getattr(server, "node_id", None)
    for doc in docs:
        node = doc.get("node")
        state = doc.get("state")
        if state in tally:
            tally[state] += 1
        if node is None or node == own or state not in ("alive", "suspect"):
            continue  # terminal (dead/drained) rows need no diagnosis;
            # never diagnose ourselves — our own writer is the evidence
        stale = now - float(doc.get("ts") or 0.0)
        if stale > dead_after_s:
            dead = dict(doc, state="dead", diagnosed_at=now,
                        stale_s=round(stale, 3))
            if store.transition_worker_state(node, ("alive", "suspect"),
                                             dead):
                recalled = 0
                try:
                    recalled = store.recall_clerking_job_leases(node)
                except Exception:
                    log.exception("lease recall for dead node %s failed "
                                  "(per-job expiry still covers it)", node)
                metrics.count("server.fleet.dead")
                if recalled:
                    metrics.count("server.job.lease_recalled", recalled)
                    # recalled jobs are poll-visible again RIGHT NOW: wake
                    # every clerk parked on this worker's long-poll plane
                    # (the recall doesn't know which clerks the dead node
                    # served; waking all is cheap and correct)
                    server.job_wakeup.notify_all()
                obs.add_event("fleet.dead", node=node, recalled=recalled,
                              stale_s=round(stale, 3))
                log.warning("fleet worker %s declared dead (%.2fs since "
                            "last heartbeat); recalled %d held lease(s)",
                            node, stale, recalled)
                actions.append({"node": node, "to": "dead",
                                "recalled_leases": recalled,
                                "stale_s": round(stale, 3)})
        elif state == "alive" and stale > suspect_after_s:
            suspect = dict(doc, state="suspect", diagnosed_at=now,
                           stale_s=round(stale, 3))
            if store.transition_worker_state(node, ("alive",), suspect):
                metrics.count("server.fleet.suspect")
                obs.add_event("fleet.suspect", node=node,
                              stale_s=round(stale, 3))
                log.info("fleet worker %s suspect (%.2fs since last "
                         "heartbeat); peers may hedge its held jobs",
                         node, stale)
                actions.append({"node": node, "to": "suspect",
                                "stale_s": round(stale, 3)})
    for state in ("alive", "suspect", "dead"):
        metrics.gauge_set(f"server.fleet.{state}", tally[state])
    return actions
