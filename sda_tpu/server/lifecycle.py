"""Round lifecycle supervisor: explicit state machine + deadline sweeper.

SDA's whole premise is weak, sporadic devices, and packed-Shamir sharing
exists precisely so a round survives missing clerks — but nothing ever
*decided* a clerk was gone: a permanently dead clerk's job lease-reissued
forever and an additive round hung silently with no terminal state. This
module closes that gap (secure-aggregation systems at population scale
treat dropout recovery as a first-class protocol phase — Bonawitz et al.,
MLSys 2019): every aggregation round carries an explicit, store-persisted
state machine, and a background sweeper drives the terminal transitions
under configurable per-phase deadlines.

States::

    collecting --snapshot--> frozen --jobs enqueued--> clerking
    clerking --all C results--> ready --reveal--> revealed        (terminal)
    clerking --dead clerks, quorum reachable--> degraded --reveal--> revealed
    clerking --dead clerks, quorum unreachable OR additive--> failed (terminal)
    collecting/frozen --deadline--> expired                       (terminal)

``ready`` means the FULL committee reported; ``degraded`` means the
sweeper detected permanently dead clerks but the surviving quorum can
(or already did) satisfy ``reconstruction_threshold``, so the existing
quorum reconstruction (``crypto/sharing.py``) completes the round from
survivors. Additive sharing cannot lose a single share
(``reconstruction_threshold == committee size``), so a dead clerk
transitions the round to ``failed`` with a machine-readable reason
instead of hanging forever.

Dead-clerk detection: past the clerking deadline, an undone clerking job
with no ACTIVE lease (``leased_until <= now`` — lapsed, or never polled
at all) marks its clerk dead. A slow-but-alive clerk always holds a live
lease while working and is spared; a clerk that died holding a lease is
detected one lease period after the deadline at the latest.

Fleet safety: every transition is a store-arbitrated compare-and-swap
(``transition_round_state`` on all four backends — the PR 6 single-winner
conditional-write pattern), so in an N-worker fleet over one shared store
exactly one worker performs each sweep action per round; the losers
observe the winner's transition and move on.

Observability: transitions count ``server.round.state.<state>``, sweep
latency lands in the ``server.round.sweep`` histogram (``/metrics``),
per-state gauges ride ``server.rounds.<state>``, transitions emit span
events, and ``/statusz`` serves the rounds table (``rounds_report``).
The recipient-facing view is ``GET /v1/aggregations/{id}/round``
(:class:`~sda_tpu.protocol.RoundStatus`) and the blocking client call
``SdaClient.await_result(deadline=...)``, which raises typed
``RoundFailed`` / ``RoundExpired`` carrying the server's diagnosis.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from .. import obs
from ..obs import recorder
from ..utils import metrics
from ..protocol import (
    AdditiveSharing,
    AggregationId,
    RoundStatus,
    SnapshotId,
)

log = logging.getLogger(__name__)

#: Every state the machine can be in, in rough lifecycle order.
STATES = (
    "collecting", "frozen", "clerking", "ready", "revealed",
    "degraded", "failed", "expired",
)

#: States no sweeper or protocol event ever leaves.
TERMINAL_STATES = frozenset({"revealed", "failed", "expired"})

#: Bounded transition history kept in the round document.
_HISTORY_LIMIT = 16


@dataclass
class RoundDeadlines:
    """Per-phase wall-clock budgets; ``None`` disables that deadline.

    ``collecting_s``: aggregation creation -> snapshot (else ``expired``).
    ``clerking_s``: job fan-out -> every result in; past it the sweeper
    runs dead-clerk detection (``degraded`` / ``failed``) and expires
    rounds stuck mid-snapshot (``frozen``).
    """

    collecting_s: Optional[float] = None
    clerking_s: Optional[float] = None


def scheme_kind(scheme) -> str:
    """``"additive"`` (no share may be lost) vs ``"shamir"`` (any quorum
    of ``reconstruction_threshold`` shares reconstructs)."""
    return "additive" if isinstance(scheme, AdditiveSharing) else "shamir"


def new_round_doc(aggregation, deadlines: Optional[RoundDeadlines]) -> dict:
    """Fresh ``collecting`` record for a just-created aggregation. The
    scheme facts the sweeper needs later (kind, committee size,
    reconstruction threshold) are denormalized in so a sweep never has to
    re-parse the aggregation resource — and so is the tree linkage
    (``parent``/``children``/``level``, from ``Aggregation.tree``): the
    sweeper's leaf-failure propagation and the ``/statusz`` tree view
    walk round documents alone, never the aggregation resources."""
    scheme = aggregation.committee_sharing_scheme
    now = time.time()
    deadline = None
    if deadlines is not None and deadlines.collecting_s:
        deadline = now + deadlines.collecting_s
    tree = getattr(aggregation, "tree", None)
    return {
        "aggregation": str(aggregation.id),
        # tenant = the aggregation's recipient: the multi-tenant service
        # plane rolls /statusz up per recipient and retention reports
        # name the tenant whose round was purged (service/)
        "tenant": str(aggregation.recipient),
        "state": "collecting",
        "snapshot": None,
        "scheme": scheme_kind(scheme),
        "committee_size": int(scheme.output_size),
        "reconstruction_threshold": int(scheme.reconstruction_threshold),
        "dead_clerks": [],
        "reason": None,
        "deadline_at": deadline,
        "updated_at": now,
        "history": [["collecting", round(now, 3)]],
        "parent": (str(tree.parent)
                   if tree is not None and tree.parent is not None else None),
        "children": ([str(c) for c in tree.children]
                     if tree is not None else []),
        "level": (int(tree.level) if tree is not None else None),
        "group": (tree.group if tree is not None else None),
    }


def _advanced(doc: dict, state: str, *, snapshot=None, deadline_at=...,
              reason=None, dead_clerks=None) -> dict:
    """The successor document for a transition (pure; the CAS publishes)."""
    now = time.time()
    new = dict(doc)
    new["state"] = state
    if snapshot is not None:
        new["snapshot"] = str(snapshot)
    if deadline_at is not ...:
        new["deadline_at"] = deadline_at
    if reason is not None:
        new["reason"] = reason
    if dead_clerks is not None:
        new["dead_clerks"] = [str(c) for c in dead_clerks]
    new["updated_at"] = now
    history = list(doc.get("history") or [])
    history.append([state, round(now, 3)])
    new["history"] = history[-_HISTORY_LIMIT:]
    return new


def transition(store, aggregation, from_states, state: str, **changes) -> bool:
    """Store-arbitrated state transition: read the current document, build
    the successor, publish with a conditional write keyed on the FROM
    state. Exactly one of N racing workers wins (the fleet contract);
    returns whether THIS call performed the transition."""
    doc = store.get_round_state(aggregation)
    if doc is None or doc.get("state") not in from_states:
        return False
    new = _advanced(doc, state, **changes)
    if not store.transition_round_state(aggregation, from_states, new):
        return False
    metrics.count(f"server.round.state.{state}")
    obs.add_event(f"round.{state}", aggregation=str(aggregation),
                  previous=doc.get("state"))
    # durable round ledger: the flight recorder spools every transition
    # so sda-trace can replay the state story after the fleet is gone
    recorder.record({
        "t": "round",
        "aggregation": str(aggregation),
        "state": state,
        "previous": doc.get("state"),
        **({"reason": changes["reason"]} if changes.get("reason") else {}),
        **({"tenant": doc["tenant"]} if doc.get("tenant") else {}),
    })
    return True


# ---------------------------------------------------------------------------
# protocol-event notes (called from server core / the snapshot pipeline)

def note_collecting(server, aggregation) -> None:
    """A fresh aggregation starts its round in ``collecting``.

    Create-if-absent: ``create_aggregation`` is a retry-safe upsert
    (``_IDEMPOTENT_POST_ROUTES``), so a replayed create after a lost
    response must NOT reset an in-flight round back to collecting —
    deleting the aggregation removes the record, so a genuinely new
    aggregation always starts fresh."""
    if server.aggregation_store.get_round_state(aggregation.id) is not None:
        return
    server.aggregation_store.put_round_state(
        new_round_doc(aggregation, getattr(server, "round_deadlines", None)))
    metrics.count("server.round.state.collecting")
    recorder.record({
        "t": "round",
        "aggregation": str(aggregation.id),
        "state": "collecting",
        "previous": None,
        # the round's tenant: recipients are the scheduler's tenant key
        # (service/scheduler.py), which sda-trace slo groups budgets by
        "tenant": str(aggregation.recipient),
    })


def note_frozen(server, aggregation, snapshot_id) -> None:
    """The snapshot pipeline froze the participation set."""
    store = server.aggregation_store
    doc = store.get_round_state(aggregation.id)
    if doc is None:
        # pre-supervisor aggregation (or a store emptied under us): mint
        # the record on the fly so the rest of the lifecycle is tracked
        store.put_round_state(_advanced(
            new_round_doc(aggregation, getattr(server, "round_deadlines",
                                               None)),
            "frozen", snapshot=snapshot_id, deadline_at=_clerking_deadline(
                server)))
        return
    if doc["state"] in TERMINAL_STATES:
        return  # terminal verdicts are never resurrected (a stale
        # snapshot pipeline racing an expired round keeps the verdict)
    if doc["state"] == "frozen" and doc.get("snapshot") == str(snapshot_id):
        return  # replay of the same pipeline: already noted
    transition(store, aggregation.id, (doc["state"],), "frozen",
               snapshot=snapshot_id, deadline_at=_clerking_deadline(server))


def _clerking_deadline(server) -> Optional[float]:
    deadlines = getattr(server, "round_deadlines", None)
    if deadlines is not None and deadlines.clerking_s:
        return time.time() + deadlines.clerking_s
    return None


def note_clerking(server, aggregation_id, snapshot_id) -> None:
    """The snapshot pipeline enqueued the clerking jobs: the round is
    live for the committee (also re-entered by a later pipelined snapshot
    of the same aggregation — the record tracks the current round)."""
    store = server.aggregation_store
    doc = store.get_round_state(aggregation_id)
    if doc is None:
        return  # nothing tracked for this aggregation; stay silent
    if doc["state"] in TERMINAL_STATES:
        return  # terminal verdicts are never resurrected
    if doc["state"] == "clerking" and doc.get("snapshot") == str(snapshot_id):
        return  # contended/replayed pipeline already converged here
    transition(store, aggregation_id, (doc["state"],), "clerking",
               snapshot=snapshot_id, deadline_at=_clerking_deadline(server))


def note_result(server, job) -> None:
    """A clerking result landed; when the FULL committee has reported the
    round is ``ready`` (threshold-satisfying partial sets stay
    ``clerking``/``degraded`` — ``result_ready`` is the recipient's
    signal, ``ready`` is the everything-done state)."""
    store = server.aggregation_store
    doc = store.get_round_state(job.aggregation)
    if (doc is None or doc.get("snapshot") != str(job.snapshot)
            or doc["state"] != "clerking"):
        return
    results = len(server.clerking_job_store.list_results(job.snapshot))
    if results >= int(doc.get("committee_size") or 0):
        transition(store, job.aggregation, ("clerking",), "ready")


def note_revealed(server, aggregation_id, snapshot_id, results: int) -> None:
    """The recipient fetched a reconstruction-grade snapshot result."""
    store = server.aggregation_store
    doc = store.get_round_state(aggregation_id)
    if doc is None or doc.get("snapshot") != str(snapshot_id):
        return
    if doc["state"] not in ("clerking", "ready", "degraded"):
        return
    if results >= int(doc.get("reconstruction_threshold") or 0):
        transition(store, aggregation_id, (doc["state"],), "revealed")


def round_status(server, aggregation_id) -> Optional[RoundStatus]:
    """The recipient-facing view: the stored round document plus the LIVE
    result count (never denormalized — it changes under the round)."""
    doc = server.aggregation_store.get_round_state(aggregation_id)
    if doc is None:
        return None
    results = 0
    if doc.get("snapshot"):
        results = len(server.clerking_job_store.list_results(
            SnapshotId(doc["snapshot"])))
    return RoundStatus(
        aggregation=AggregationId(doc["aggregation"]),
        state=doc["state"],
        snapshot=SnapshotId(doc["snapshot"]) if doc.get("snapshot") else None,
        scheme=doc.get("scheme"),
        committee_size=doc.get("committee_size") or 0,
        reconstruction_threshold=doc.get("reconstruction_threshold") or 0,
        results=results,
        dead_clerks=doc.get("dead_clerks") or [],
        reason=doc.get("reason"),
        deadline_at=doc.get("deadline_at"),
        updated_at=doc.get("updated_at"),
        history=doc.get("history") or [],
        parent=doc.get("parent"),
        children=doc.get("children") or [],
    )


def rounds_report(server, limit: int = 16) -> dict:
    """The ``/statusz`` rounds table, built for LONG-LIVED services: a
    thousand-round deployment is mostly terminal history, and the rounds
    an operator needs are the live ones. The ``recent`` table therefore
    fills with live (non-terminal) rounds first, most recently updated
    first, and only pads the remainder with terminal rounds — and the
    output stays O(limit) regardless of how many rounds the store holds.
    ``by_tenant`` is the multi-tenant rollup (state counts per recipient,
    bounded to the ``limit`` busiest tenants; ``tenants_omitted`` says
    how many fell off)."""
    docs = server.aggregation_store.list_round_states()
    by_state: dict = {}
    by_tenant: dict = {}
    live = 0
    for doc in docs:
        state = doc.get("state", "?")
        by_state[state] = by_state.get(state, 0) + 1
        if state not in TERMINAL_STATES:
            live += 1
        tenant = doc.get("tenant") or "?"
        by_tenant.setdefault(tenant, {})[state] = (
            by_tenant.get(tenant, {}).get(state, 0) + 1)
    freshest = sorted(docs, key=lambda d: d.get("updated_at") or 0.0,
                      reverse=True)
    recent = [d for d in freshest
              if d.get("state") not in TERMINAL_STATES][:limit]
    if len(recent) < limit:
        recent += [d for d in freshest
                   if d.get("state") in TERMINAL_STATES
                   ][:limit - len(recent)]
    tenants = sorted(by_tenant.items(),
                     key=lambda kv: (-sum(kv[1].values()), kv[0]))
    return {
        "count": len(docs),
        "live": live,
        "by_state": dict(sorted(by_state.items())),
        "by_tenant": {tenant: dict(sorted(states.items()))
                      for tenant, states in tenants[:limit]},
        "tenants_omitted": max(0, len(tenants) - limit),
        "recent": [
            {
                "aggregation": d.get("aggregation"),
                "tenant": d.get("tenant"),
                "state": d.get("state"),
                "snapshot": d.get("snapshot"),
                "reason": d.get("reason"),
                "dead_clerks": d.get("dead_clerks") or None,
                "updated_at": d.get("updated_at"),
                # tree linkage: a stuck hierarchical round is diagnosable
                # from ANY worker's /statusz — the root row names its
                # children, each leaf row names its parent and level
                "parent": d.get("parent"),
                "children": d.get("children") or None,
                "level": d.get("level"),
            }
            for d in recent
        ],
    }


# ---------------------------------------------------------------------------
# the sweeper

class RoundSweeper:
    """Background deadline/dead-clerk sweeper for one ``sdad`` worker.

    Every ``interval_s`` it lists the store's round records and, for each
    non-terminal round past its phase deadline, performs the terminal
    diagnosis — expired collection, stalled snapshot, dead clerks with
    quorum-degraded completion or unrecoverable failure. All actions are
    CAS transitions, so N workers sweeping one shared store perform each
    action exactly once between them.

    ``heartbeat_suspect_s`` / ``heartbeat_dead_s`` additionally arm the
    FLEET failure detector (``server/health.py``) on the same cadence: a
    peer worker whose heartbeat goes stale past the suspect threshold is
    declared suspect (hedging may shadow its jobs), past the dead
    threshold it is declared dead and its held clerking-job leases are
    proactively recalled — bounded MTTR instead of per-job lease expiry.
    Both declarations ride the same single-winner CAS discipline.
    """

    def __init__(self, server, interval_s: float = 1.0, *,
                 heartbeat_suspect_s: Optional[float] = None,
                 heartbeat_dead_s: Optional[float] = None):
        self.server = server
        self.interval_s = float(interval_s)
        self.heartbeat_suspect_s = heartbeat_suspect_s
        self.heartbeat_dead_s = heartbeat_dead_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RoundSweeper":
        self._thread = threading.Thread(
            target=self._run, name="round-sweeper", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep_once()
            except Exception:  # the sweeper must outlive store hiccups
                log.exception("round sweep failed; retrying next tick")
                metrics.count("server.round.sweep_error")

    def sweep_once(self, now: Optional[float] = None) -> dict:
        """One sweep pass; returns ``{"rounds", "actions"}`` where each
        action names a transition THIS worker won."""
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        actions: List[dict] = []
        with obs.span("server.round.sweep") as sweep_span:
            if self.heartbeat_dead_s is not None:
                # fleet health first: a recalled lease makes the jobs of a
                # SIGKILL'd worker pollable before the round diagnosis
                # below could mistake them for dead-clerk work
                from . import health

                suspect_s = (self.heartbeat_suspect_s
                             if self.heartbeat_suspect_s is not None
                             else self.heartbeat_dead_s / 2)
                actions.extend(health.sweep_worker_health(
                    self.server, now, suspect_after_s=suspect_s,
                    dead_after_s=self.heartbeat_dead_s))
            docs = self.server.aggregation_store.list_round_states()
            by_state: dict = {}
            for doc in docs:
                state = doc.get("state", "?")
                by_state[state] = by_state.get(state, 0) + 1
            for state in STATES:
                metrics.gauge_set(f"server.rounds.{state}",
                                  by_state.get(state, 0))
            for doc in docs:
                if doc.get("state") in TERMINAL_STATES \
                        or doc.get("state") == "ready":
                    continue  # ready waits on the recipient, not on us
                action = self._sweep_round(doc, now)
                if action is not None:
                    # fold the verdict into OUR listing too: the tree
                    # pass below reads these docs, and the store write
                    # inside transition() doesn't update them
                    doc["state"] = action["to"]
                    if action.get("reason") is not None:
                        doc["reason"] = action["reason"]
                    if action.get("dead_clerks") is not None:
                        doc["dead_clerks"] = action["dead_clerks"]
                    actions.append(action)
                    obs.add_event("round.sweep_action", **action)
            # tree propagation AFTER per-round diagnosis: a leaf the pass
            # above just declared failed/expired fails its ancestors in
            # the SAME sweep (no extra tick of latency)
            actions.extend(self._sweep_tree(docs))
            # retention LAST: a round the diagnosis above just made
            # terminal starts its TTL clock now; rounds whose TTL lapsed
            # are expired (CAS) and cascade-purged from every backend
            # (service/retention.py; armed via server.retention_policy)
            policy = getattr(self.server, "retention_policy", None)
            if policy is not None and policy.enabled:
                from ..service import retention

                actions.extend(retention.sweep_retention(
                    self.server, docs, now=now))
            sweep_span.set_attribute("rounds", len(docs))
            sweep_span.set_attribute("actions", len(actions))
        metrics.observe("server.round.sweep", time.perf_counter() - t0)
        return {"rounds": len(docs), "actions": actions}

    # -- tree propagation ---------------------------------------------------
    def _sweep_tree(self, docs: List[dict]) -> List[dict]:
        """Hierarchical-round failure propagation (``sda_tpu/tree``).

        A leaf that went ``degraded`` needs no propagation — its relay
        completes from the surviving quorum and feeds the parent round
        normally. But a leaf that reached a DEAD terminal state
        (``failed``/``expired``) can never produce its partial aggregate,
        so every ancestor is unrecoverable: fail the parent round with a
        machine-readable reason NAMING the leaf, instead of letting the
        root hang until its own deadline with no diagnosis. CAS
        transitions keep this exactly-once across a sweeping fleet, and
        re-listing is unnecessary — a parent failed here is seen by its
        own parent on the next sweep tick (one tick per tree level)."""
        by_id = {d.get("aggregation"): d for d in docs}
        actions: List[dict] = []
        for doc in docs:
            state = doc.get("state")
            if state in TERMINAL_STATES or not doc.get("children"):
                continue
            for child_id in doc["children"]:
                child = by_id.get(str(child_id))
                if child is None or child.get("state") not in ("failed",
                                                               "expired"):
                    continue
                where = ""
                if child.get("level") is not None:
                    where = (f" (level {child['level']}"
                             + (f", group {child['group']}"
                                if child.get("group") is not None else "")
                             + ")")
                reason = (
                    f"child round {child_id}{where} is {child['state']}: "
                    f"{child.get('reason') or 'no reason recorded'}")
                aggregation = AggregationId(doc["aggregation"])
                if transition(self.server.aggregation_store, aggregation,
                              (state,), "failed", reason=reason,
                              dead_clerks=child.get("dead_clerks") or None):
                    # fold into our listing: an ancestor later in this
                    # same pass sees the propagated failure immediately
                    doc["state"] = "failed"
                    doc["reason"] = reason
                    metrics.count("server.round.tree_failed")
                    log.warning("round %s -> failed (tree): %s",
                                aggregation, reason)
                    actions.append({"aggregation": str(aggregation),
                                    "to": "failed", "reason": reason})
                break  # one verdict per parent per sweep is enough
        return actions

    # -- per-round diagnosis ------------------------------------------------
    def _sweep_round(self, doc: dict, now: float) -> Optional[dict]:
        deadline = doc.get("deadline_at")
        if deadline is None or now < deadline:
            return None
        state = doc["state"]
        aggregation = AggregationId(doc["aggregation"])
        if state == "collecting":
            reason = ("no snapshot within the collecting deadline "
                      f"({doc['deadline_at']:.3f})")
            if transition(self.server.aggregation_store, aggregation,
                          ("collecting",), "expired", reason=reason):
                return {"aggregation": str(aggregation), "to": "expired",
                        "reason": reason}
            return None
        if state == "frozen":
            reason = ("snapshot pipeline stalled past the clerking "
                      "deadline (frozen set installed, jobs never "
                      "enqueued)")
            if transition(self.server.aggregation_store, aggregation,
                          ("frozen",), "expired", reason=reason):
                return {"aggregation": str(aggregation), "to": "expired",
                        "reason": reason}
            return None
        if state in ("clerking", "degraded"):
            return self._sweep_clerking(doc, aggregation, now)
        return None

    def _sweep_clerking(self, doc: dict, aggregation,
                        now: float) -> Optional[dict]:
        """Dead-clerk detection past the clerking deadline. A job is dead
        when undone with no ACTIVE lease — lapsed (the clerk died holding
        it, past reissue) or never polled at all (the clerk never showed
        up); an actively leased job means someone is working right now."""
        snapshot = SnapshotId(doc["snapshot"])
        jobs = self.server.clerking_job_store.list_snapshot_jobs(snapshot)
        if not jobs:
            return None  # backend cannot enumerate: no diagnosis possible
        dead = sorted(
            str(clerk)
            for (_job, clerk, done, leased_until) in jobs
            if not done and leased_until <= now
        )
        if not dead:
            return None  # every missing job is actively leased: alive
        results = len(self.server.clerking_job_store.list_results(snapshot))
        threshold = int(doc.get("reconstruction_threshold") or 0)
        committee = int(doc.get("committee_size") or len(jobs))
        reachable = committee - len(dead)
        if doc.get("scheme") == "additive":
            to = "failed"
            reason = (f"additive sharing cannot recover {len(dead)} dead "
                      f"clerk(s): every share is required "
                      f"(reconstruction_threshold == committee size "
                      f"{committee})")
        elif reachable >= threshold or results >= threshold:
            to = "degraded"
            reason = (f"{len(dead)} dead clerk(s) detected past the "
                      f"clerking deadline; completing from the surviving "
                      f"quorum ({max(reachable, results)} >= "
                      f"reconstruction threshold {threshold})")
        else:
            to = "failed"
            reason = (f"quorum unreachable: {len(dead)} dead clerk(s) "
                      f"leave at most {reachable} results, below the "
                      f"reconstruction threshold {threshold}")
        if doc["state"] == "degraded" and to == "degraded":
            return None  # already diagnosed; nothing new to record
        if transition(self.server.aggregation_store, aggregation,
                      (doc["state"],), to, reason=reason, dead_clerks=dead):
            metrics.count("server.round.dead_clerks", len(dead))
            log.warning("round %s -> %s: %s", aggregation, to, reason)
            return {"aggregation": str(aggregation), "to": to,
                    "reason": reason, "dead_clerks": dead}
        return None
