"""L3/L4: server core, ACL service wrapper, snapshot scheduler, stores."""

from __future__ import annotations

from .core import SdaServer, SdaServerService
from .memory import (
    MemoryAgentsStore,
    MemoryAggregationsStore,
    MemoryAuthTokensStore,
    MemoryClerkingJobsStore,
)
from .jsonfs import (
    JsonAgentsStore,
    JsonAggregationsStore,
    JsonAuthTokensStore,
    JsonClerkingJobsStore,
)
from .sqlite import (
    SqliteAgentsStore,
    SqliteAggregationsStore,
    SqliteAuthTokensStore,
    SqliteClerkingJobsStore,
    SqliteDb,
)
from .stores import (
    AgentsStore,
    AggregationsStore,
    AuthToken,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
    auth_token,
)


def new_memory_server() -> SdaServerService:
    """Whole server in process memory — test/simulation fixture."""
    return SdaServerService(
        SdaServer(
            agents_store=MemoryAgentsStore(),
            auth_tokens_store=MemoryAuthTokensStore(),
            aggregation_store=MemoryAggregationsStore(),
            clerking_job_store=MemoryClerkingJobsStore(),
        )
    )


def new_sqlite_server(path) -> SdaServerService:
    """Single-file database server — the production-database tier
    (reference analog: the MongoDB backend, server-store-mongodb/)."""
    db = SqliteDb(path)
    return SdaServerService(
        SdaServer(
            agents_store=SqliteAgentsStore(db),
            auth_tokens_store=SqliteAuthTokensStore(db),
            aggregation_store=SqliteAggregationsStore(db),
            clerking_job_store=SqliteClerkingJobsStore(db),
        )
    )


def new_mongo_server(uri_or_db, dbname: str = "sda") -> SdaServerService:
    """MongoDB-backed server (reference: server-store-mongodb/). Accepts a
    connection URI (needs pymongo) or a pymongo-compatible Database object."""
    from . import mongo

    if isinstance(uri_or_db, str):
        if not mongo.available():
            raise RuntimeError(
                "pymongo is not installed; pass a pymongo-compatible Database "
                "or use new_sqlite_server for the in-image production tier"
            )
        import pymongo

        db = pymongo.MongoClient(uri_or_db)[dbname]
    else:
        db = uri_or_db
    return SdaServerService(
        SdaServer(
            agents_store=mongo.MongoAgentsStore(db),
            auth_tokens_store=mongo.MongoAuthTokensStore(db),
            aggregation_store=mongo.MongoAggregationsStore(db),
            clerking_job_store=mongo.MongoClerkingJobsStore(db),
        )
    )


def new_jsonfs_server(directory) -> SdaServerService:
    """Durable JSON-file-backed server (reference: new_jfs_server,
    server/src/lib.rs:34-45)."""
    from pathlib import Path

    root = Path(directory)
    return SdaServerService(
        SdaServer(
            agents_store=JsonAgentsStore(root / "agents"),
            auth_tokens_store=JsonAuthTokensStore(root / "auths"),
            aggregation_store=JsonAggregationsStore(root / "agg"),
            clerking_job_store=JsonClerkingJobsStore(root / "jobs"),
        )
    )
