"""In-memory store backend: dicts under a lock.

The fastest fixture backend (the reference's analog is the jfs tempdir
store used by integration tests); also the store of choice for
simulated-pod runs where the server is pure control plane.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .. import chaos
from ..utils import metrics
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkCandidate,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    NotFound,
    Participation,
    ParticipationConflict,
    ParticipationId,
    Snapshot,
    SnapshotId,
)
from .stores import (
    AgentsStore,
    AggregationsStore,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
)


class _Locked(BaseStore):
    def __init__(self):
        self._lock = threading.RLock()

    def ping(self) -> None:
        pass


class MemoryAuthTokensStore(_Locked, AuthTokensStore):
    def __init__(self):
        super().__init__()
        self._tokens = {}

    def upsert_auth_token(self, token):
        with self._lock:
            self._tokens[token.id] = token

    def get_auth_token(self, id):
        with self._lock:
            return self._tokens.get(id)

    def delete_auth_token(self, id):
        with self._lock:
            self._tokens.pop(id, None)


class MemoryAgentsStore(_Locked, AgentsStore):
    def __init__(self):
        super().__init__()
        self._agents: Dict[AgentId, Agent] = {}
        self._profiles = {}
        self._keys = {}

    def create_agent(self, agent):
        with self._lock:
            self._agents[agent.id] = agent

    def get_agent(self, id):
        with self._lock:
            return self._agents.get(id)

    def upsert_profile(self, profile):
        with self._lock:
            self._profiles[profile.owner] = profile

    def get_profile(self, owner):
        with self._lock:
            return self._profiles.get(owner)

    def create_encryption_key(self, key):
        with self._lock:
            self._keys[key.body.id] = key

    def get_encryption_key(self, key):
        with self._lock:
            return self._keys.get(key)

    def suggest_committee(self):
        with self._lock:
            by_signer: Dict[AgentId, List] = {}
            for signed in self._keys.values():
                by_signer.setdefault(signed.signer, []).append(signed.body.id)
            return [
                ClerkCandidate(id=signer, keys=keys)
                for signer, keys in sorted(by_signer.items(), key=lambda kv: kv[0])
            ]


class MemoryAggregationsStore(_Locked, AggregationsStore):
    def __init__(self):
        super().__init__()
        self._aggregations: Dict[AggregationId, Aggregation] = {}
        self._committees: Dict[AggregationId, Committee] = {}
        # insertion-ordered so snapshots freeze a deterministic set
        self._participations: Dict[AggregationId, OrderedDict] = {}
        # exactly-once ingestion index: (aggregation, participant) ->
        # (participation id, canonical digest) — the single-winner key
        self._part_owners: Dict[AggregationId, Dict] = {}
        self._snapshots: Dict[AggregationId, OrderedDict] = {}
        self._snapshot_parts: Dict[SnapshotId, List[ParticipationId]] = {}
        self._snapshot_masks = {}
        self._rounds: Dict[str, dict] = {}  # aggregation id str -> doc
        self._schedules: Dict[str, dict] = {}  # schedule name -> doc

    def list_aggregations(self, filter=None, recipient=None):
        with self._lock:
            out = []
            for agg in self._aggregations.values():
                if filter is not None and filter not in agg.title:
                    continue
                if recipient is not None and agg.recipient != recipient:
                    continue
                out.append(agg.id)
            return out

    def create_aggregation(self, aggregation):
        with self._lock:
            self._aggregations[aggregation.id] = aggregation
            self._participations.setdefault(aggregation.id, OrderedDict())
            self._snapshots.setdefault(aggregation.id, OrderedDict())

    def get_aggregation(self, aggregation):
        with self._lock:
            return self._aggregations.get(aggregation)

    def delete_aggregation(self, aggregation):
        with self._lock:
            self._aggregations.pop(aggregation, None)
            self._committees.pop(aggregation, None)
            self._participations.pop(aggregation, None)
            self._part_owners.pop(aggregation, None)
            self._rounds.pop(str(aggregation), None)
            for sid in self._snapshots.pop(aggregation, OrderedDict()):
                self._snapshot_parts.pop(sid, None)
                self._snapshot_masks.pop(sid, None)

    def get_committee(self, aggregation):
        with self._lock:
            return self._committees.get(aggregation)

    def create_committee(self, committee):
        with self._lock:
            self._committees[committee.aggregation] = committee

    def create_participation(self, participation):
        chaos.fail("store.create_participation")
        digest = participation.canonical_digest()
        # the whole check-and-insert under ONE lock hold is the arbiter:
        # two racing uploaders of one (aggregation, participant) key admit
        # exactly one winner (exactly-once ingestion contract, stores.py)
        with self._lock:
            if participation.aggregation not in self._aggregations:
                raise NotFound("aggregation not found")
            parts = self._participations[participation.aggregation]
            existing = parts.get(participation.id)
            if existing is not None:
                # same participation id: byte-identical replay is an
                # idempotent success; different content must never
                # silently replace the earlier bundle
                if existing.canonical_digest() == digest:
                    return False
                raise ParticipationConflict(
                    f"participation {participation.id} already exists "
                    "with different content",
                    participant=participation.participant,
                    aggregation=participation.aggregation)
            owners = self._part_owners.setdefault(participation.aggregation, {})
            owned = owners.get(participation.participant)
            if owned is not None:
                # the same agent under a NEW id: a recompute-with-fresh-
                # randomness (or equivocation) that would double-count
                raise ParticipationConflict(
                    f"agent {participation.participant} already "
                    f"participated in {participation.aggregation} "
                    f"(participation {owned[0]})",
                    participant=participation.participant,
                    aggregation=participation.aggregation)
            owners[participation.participant] = (participation.id, digest)
            parts[participation.id] = participation
            return True

    def create_snapshot(self, snapshot):
        chaos.fail("store.create_snapshot")
        # conditional insert: first writer wins, the record never changes
        # after it exists (contended-idempotency contract, stores.py)
        with self._lock:
            snapshots = self._snapshots[snapshot.aggregation]
            if snapshot.id in snapshots:
                return False
            snapshots[snapshot.id] = snapshot
            return True

    def list_snapshots(self, aggregation):
        with self._lock:
            return list(self._snapshots.get(aggregation, OrderedDict()))

    def get_snapshot(self, aggregation, snapshot):
        with self._lock:
            return self._snapshots.get(aggregation, OrderedDict()).get(snapshot)

    def count_participations(self, aggregation):
        with self._lock:
            return len(self._participations.get(aggregation, OrderedDict()))

    def snapshot_participations(self, aggregation, snapshot):
        # single-winner: the dict insert under the lock is the arbiter;
        # a loser returns False and the winner's frozen set is already
        # readable (same lock serializes freeze and read)
        with self._lock:
            if snapshot in self._snapshot_parts:
                return False
            self._snapshot_parts[snapshot] = list(
                self._participations.get(aggregation, OrderedDict())
            )
            return True

    def has_snapshot_freeze(self, aggregation, snapshot):
        with self._lock:
            return snapshot in self._snapshot_parts  # even when frozen empty

    def iter_snapped_participations(self, aggregation, snapshot):
        with self._lock:
            part_ids = self._snapshot_parts.get(snapshot, [])
            parts = self._participations.get(aggregation, OrderedDict())
            return [parts[pid] for pid in part_ids if pid in parts]

    # -- round lifecycle ----------------------------------------------------
    def put_round_state(self, doc):
        with self._lock:
            self._rounds[doc["aggregation"]] = dict(doc)

    def get_round_state(self, aggregation):
        with self._lock:
            doc = self._rounds.get(str(aggregation))
            return None if doc is None else dict(doc)

    def list_round_states(self):
        with self._lock:
            return [dict(d) for d in self._rounds.values()]

    def transition_round_state(self, aggregation, from_states, doc):
        # single-winner CAS: the state check + publish under one lock is
        # the arbiter (same contract the sqlite/jsonfs/mongo stores keep
        # across OS processes)
        with self._lock:
            current = self._rounds.get(str(aggregation))
            if current is None or current.get("state") not in from_states:
                return False
            self._rounds[str(aggregation)] = dict(doc)
            return True

    # -- recurring-round schedules -------------------------------------------
    def create_schedule_state(self, doc):
        # conditional insert under the store lock: installation is
        # single-winner, a booting scheduler can never reset an advanced
        # schedule (stores.py schedule contract)
        with self._lock:
            if doc["schedule"] in self._schedules:
                return False
            self._schedules[doc["schedule"]] = dict(doc)
            return True

    def get_schedule_state(self, schedule):
        with self._lock:
            doc = self._schedules.get(str(schedule))
            return None if doc is None else dict(doc)

    def list_schedule_states(self):
        with self._lock:
            return [dict(d) for d in self._schedules.values()]

    def transition_schedule_state(self, schedule, from_epoch, doc):
        # single-winner epoch CAS: the epoch check + publish under one
        # lock hold is the arbiter (same contract the sqlite/jsonfs/mongo
        # stores keep across OS processes)
        with self._lock:
            current = self._schedules.get(str(schedule))
            if current is None \
                    or int(current.get("epoch", -1)) != int(from_epoch):
                return False
            self._schedules[str(schedule)] = dict(doc)
            return True

    def create_snapshot_mask(self, snapshot, mask):
        with self._lock:
            self._snapshot_masks[snapshot] = {0: list(mask)}

    def put_snapshot_mask_chunk(self, snapshot, index, encryptions):
        # pure chunk upsert keyed by index: replays/contended peers
        # rewrite identical chunks, so readers always see a complete
        # mask (stores.py contract); trim drops any excess at the end
        with self._lock:
            chunks = self._snapshot_masks.setdefault(snapshot, {})
            chunks[int(index)] = list(encryptions)

    def trim_snapshot_mask_chunks(self, snapshot, count):
        with self._lock:
            chunks = self._snapshot_masks.get(snapshot)
            if chunks is not None:
                for ix in [ix for ix in chunks if ix >= int(count)]:
                    del chunks[ix]

    def get_snapshot_mask(self, snapshot):
        with self._lock:
            chunks = self._snapshot_masks.get(snapshot)
            if chunks is None:
                return None
            return [e for ix in sorted(chunks) for e in chunks[ix]]


class MemoryClerkingJobsStore(_Locked, ClerkingJobsStore):
    def __init__(self):
        super().__init__()
        self._queues: Dict[AgentId, OrderedDict] = {}
        self._done: Dict[AgentId, Dict[ClerkingJobId, ClerkingJob]] = {}
        self._results: Dict[SnapshotId, OrderedDict] = {}
        self._leases: Dict[ClerkingJobId, float] = {}  # job id -> expires_at
        self._lease_owners: Dict[ClerkingJobId, str] = {}  # -> node_id
        self._heartbeats: Dict[str, dict] = {}  # node id -> heartbeat doc

    def enqueue_clerking_job(self, job):
        chaos.fail("store.enqueue_clerking_job")
        with self._lock:
            if job.id in self._done.get(job.clerk, {}):
                return  # snapshot retry: this job already completed
            self._queues.setdefault(job.clerk, OrderedDict())[job.id] = job

    def enqueue_clerking_jobs(self, jobs):
        jobs = list(jobs)
        for _ in jobs:
            chaos.fail("store.enqueue_clerking_job")
        with self._lock:  # one lock hold for the whole fan-out
            for job in jobs:
                if job.id in self._done.get(job.clerk, {}):
                    continue  # snapshot retry: this job already completed
                self._queues.setdefault(job.clerk, OrderedDict())[job.id] = job

    def poll_clerking_job(self, clerk):
        chaos.fail("store.poll_clerking_job")
        with self._lock:
            queue = self._queues.get(clerk)
            if not queue:
                return None
            return next(iter(queue.values()))

    def lease_clerking_job(self, clerk, lease_seconds, now=None, owner=None):
        chaos.fail("store.poll_clerking_job")
        now = time.time() if now is None else now
        with self._lock:
            for job in self._queues.get(clerk, OrderedDict()).values():
                expiry = self._leases.get(job.id)
                if expiry is not None and expiry > now:
                    continue  # actively leased by another worker of this clerk
                if expiry is not None:
                    metrics.count("server.job.reissued")
                expires = now + lease_seconds
                self._leases[job.id] = expires
                self._lease_owners[job.id] = owner
                return job, expires
            return None

    def release_clerking_job_lease(self, clerk, job, expires=None):
        # graceful drain: drop the visibility timeout so the next poller
        # (another worker of this clerk) gets the job immediately —
        # compare-and-release: a lapsed lease re-granted to a peer (new
        # expiry) is the peer's to release, not ours
        with self._lock:
            if job not in self._queues.get(clerk, OrderedDict()):
                return False  # done (or never enqueued): nothing to release
            current = self._leases.get(job)
            if current is None or (expires is not None and current != expires):
                return False
            del self._leases[job]
            self._lease_owners.pop(job, None)
            return True

    def recall_clerking_job_leases(self, node_id):
        # the dead-node recovery step: every lease the dead worker granted
        # goes back to "unleased" so any peer's next poll reissues it now
        with self._lock:
            recalled = [
                job_id for job_id, owner in self._lease_owners.items()
                if owner == node_id and job_id in self._leases
            ]
            for job_id in recalled:
                self._leases.pop(job_id, None)
                self._lease_owners.pop(job_id, None)
            return len(recalled)

    def hedge_clerking_job(self, clerk, suspect_nodes, lease_seconds,
                           now=None, owner=None):
        # hedged execution: re-grant a SUSPECT holder's active lease to
        # this caller; the original may still finish — result commit is
        # single-winner, so the race is safe
        now = time.time() if now is None else now
        suspects = set(suspect_nodes)
        if not suspects:
            return None
        with self._lock:
            for job in self._queues.get(clerk, OrderedDict()).values():
                expiry = self._leases.get(job.id)
                if expiry is None or expiry <= now:
                    continue  # unleased/lapsed: the normal poll covers it
                if self._lease_owners.get(job.id) not in suspects:
                    continue
                expires = now + lease_seconds
                self._leases[job.id] = expires
                self._lease_owners[job.id] = owner
                return job, expires
            return None

    # -- fleet heartbeats ---------------------------------------------------
    def put_worker_heartbeat(self, doc):
        with self._lock:
            self._heartbeats[doc["node"]] = dict(doc)

    def get_worker_heartbeat(self, node):
        with self._lock:
            doc = self._heartbeats.get(str(node))
            return None if doc is None else dict(doc)

    def list_worker_heartbeats(self):
        with self._lock:
            return [dict(d) for d in self._heartbeats.values()]

    def transition_worker_state(self, node, from_states, doc):
        # single-winner CAS under the store lock (the fleet contract:
        # exactly one sweeper declares a node suspect/dead)
        with self._lock:
            current = self._heartbeats.get(str(node))
            if current is None or current.get("state") not in from_states:
                return False
            self._heartbeats[str(node)] = dict(doc)
            return True

    def list_snapshot_jobs(self, snapshot):
        # the sweeper's dead-clerk census: queued jobs with their lease
        # expiry, done jobs flagged done (lease irrelevant once complete)
        with self._lock:
            out = []
            for clerk, queue in self._queues.items():
                for job in queue.values():
                    if str(job.snapshot) == str(snapshot):
                        out.append((job.id, clerk, False,
                                    float(self._leases.get(job.id, 0.0))))
            for clerk, done in self._done.items():
                for job in done.values():
                    if str(job.snapshot) == str(snapshot):
                        out.append((job.id, clerk, True, 0.0))
            return sorted(out, key=lambda entry: str(entry[0]))

    def get_clerking_job(self, clerk, job):
        with self._lock:
            found = self._queues.get(clerk, OrderedDict()).get(job)
            if found is None:
                found = self._done.get(clerk, {}).get(job)
            return found

    def create_clerking_result(self, result):
        chaos.fail("store.create_clerking_result")
        with self._lock:
            queue = self._queues.get(result.clerk, OrderedDict())
            job = queue.pop(result.job, None)
            if job is None and result.job not in self._done.get(result.clerk, {}):
                raise NotFound("job not found for clerk")
            if job is not None:
                self._leases.pop(job.id, None)
                self._lease_owners.pop(job.id, None)
                self._done.setdefault(result.clerk, {})[job.id] = job
                self._results.setdefault(job.snapshot, OrderedDict())[result.job] = result

    def purge_snapshot_jobs(self, snapshot):
        # the retention/delete cascade's job-store half: queued AND done
        # jobs of the snapshot leave, with their leases and results —
        # nothing the round ever produced survives the purge
        with self._lock:
            removed = 0
            for table in (self._queues, self._done):
                for clerk in list(table):
                    jobs = table[clerk]
                    for job_id in [jid for jid, job in jobs.items()
                                   if str(job.snapshot) == str(snapshot)]:
                        del jobs[job_id]
                        self._leases.pop(job_id, None)
                        self._lease_owners.pop(job_id, None)
                        removed += 1
                    if not jobs:
                        del table[clerk]
            removed += len(self._results.pop(snapshot, OrderedDict()))
            return removed

    def list_results(self, snapshot):
        with self._lock:
            return list(self._results.get(snapshot, OrderedDict()))

    def get_result(self, snapshot, job):
        with self._lock:
            return self._results.get(snapshot, OrderedDict()).get(job)
