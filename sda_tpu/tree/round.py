"""The tree driver: run a planned hierarchical round through the real
server stack — in-process stores or the HTTP fleet — level by level.

One call drives the whole protocol the planner laid out
(``tree/plan.py``): agents and keys registered, every node's aggregation
uploaded by ITS recipient (the root, or a relay), committees elected
deterministically from a shared clerk pool, leaf participants masked and
sharded in, then levels complete bottom-up — each relay awaits its
round, re-shares the masked total and forwards the leaf masks in-band
(``client/relay.py``), until the root's ordinary flat reveal unmasks the
population total.

Failure semantics ride the round lifecycle supervisor
(``server/lifecycle.py``):

- a leaf whose committee loses clerks down to a surviving quorum goes
  ``degraded`` and its SURVIVORS feed up — the root result is unchanged;
- a leaf that cannot reconstruct (additive sharing, quorum lost) goes
  ``failed``, the sweeper's tree propagation fails every ancestor with a
  machine-readable reason naming the leaf, and the driver surfaces the
  typed ``RoundFailed`` from the root instead of hanging;
- chaos dropout at the leaves (``participant.dies``) shrinks the
  expected sum exactly like the flat chaos drill — the optional flat
  reference round re-runs the surviving inputs through an ordinary flat
  aggregation on the same stack and pins bit-exactness.

Span linkage: the whole run executes under one ``tree.round`` span, with
one ``tree.node`` span per aggregation — a root round's timeline
contains its children (docs/observability.md).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from .. import chaos, obs
from ..utils import metrics
from .plan import TreePlan, plan_tree

class TreeRoundReport(dict):
    """Plain dict with attribute sugar; one JSON-able report per run."""

    __getattr__ = dict.get


def _make_schemes(sharing: str, modulus: int, share_count: int):
    from ..chaos.drill import golden_packed_scheme
    from ..protocol import AdditiveSharing

    if sharing == "additive":
        return AdditiveSharing(share_count=share_count, modulus=modulus)
    if sharing == "packed":
        # the golden drill committee, ONE definition shared with the
        # chaos and load drills
        scheme = golden_packed_scheme()
        if modulus != scheme.prime_modulus:
            raise ValueError(
                f"packed drill scheme is pinned to modulus "
                f"{scheme.prime_modulus}")
        return scheme
    raise ValueError(f"unknown sharing {sharing!r}")


def _make_masking(masking: str, modulus: int, dim: int):
    from ..protocol import ChaChaMasking, FullMasking, NoMasking

    if masking == "none":
        return NoMasking()
    if masking == "full":
        return FullMasking(modulus)
    if masking == "chacha":
        return ChaChaMasking(modulus, dim, 128)
    raise ValueError(f"unknown masking {masking!r}")


def run_tree_round(
    inputs,
    *,
    group_size: int,
    fanout: Optional[int] = None,
    modulus: int = 433,
    sharing: str = "additive",
    share_count: int = 3,
    masking: str = "full",
    store: str = "memory",
    store_path=None,
    http: bool = False,
    seed: int = 0,
    dropout_rate: float = 0.0,
    dead_clerks_leaf: int = 0,
    flat_reference: bool = True,
    timeout_s: float = 120.0,
    clerking_deadline_s: float = 1.5,
    sweep_interval_s: float = 0.2,
    lease_seconds: float = 0.75,
    service=None,
    reset_obs: bool = True,
    return_output: bool = False,
    taint_participants=None,
    collect_leaf_subtotals: bool = False,
) -> TreeRoundReport:
    """Drive one full tree round; returns the report dict.

    ``inputs`` is the ``[N, dim]`` integer matrix of device vectors
    (values in ``[0, modulus)``). ``dropout_rate`` arms the
    ``participant.dies`` chaos failpoint at the leaves; a dead device
    never contributes and the expected sum excludes it.
    ``dead_clerks_leaf`` permanently kills that many clerks of the first
    planned leaf's committee and arms the lifecycle sweeper: with packed
    Shamir the leaf completes ``degraded`` from the surviving quorum and
    the root reveal is unchanged; with additive sharing the leaf goes
    terminal ``failed`` and the ROOT round fails with a reason naming
    the leaf. ``service`` injects an existing in-process service (tests);
    otherwise one is built from ``store``/``http``.

    ``reset_obs=False`` keeps the caller's span/metrics/failpoint state
    (an embedding workload — the FL scenario runs one tree round per
    FedAvg round under its own trace — must not have its telemetry wiped
    per call). ``return_output=True`` attaches the revealed root vector
    as ``report["output_values"]`` (an int64 ndarray — NOT JSON-able, so
    it is opt-in; the JSON-bound ``sda-sim --tree`` profile leaves it
    off).

    ``taint_participants`` names device INDICES whose share uploads are
    adversarially tainted (the ``participant.taint_shares`` chaos kind is
    armed around exactly their participate calls — index-addressed, so
    the attacker set stays fixed even when dropout kills other devices).
    ``collect_leaf_subtotals=True`` has the ROOT additionally unmask each
    leaf's masked subtotal individually (decrypting the leaf's mask
    ciphertexts, which are sealed to the root anyway) and attaches
    ``report["leaf_subtotals"]`` — the data robust (trimmed-mean)
    recipient aggregation consumes. Depth-2 trees only: deeper trees
    interleave relay re-masking, so per-leaf unmasking no longer
    decomposes. This is recipient post-processing — the protocol reveal
    and its exactness check are untouched — and it is also precisely
    what robust aggregation LEAKS relative to the flat protocol: the
    root learns per-leaf group subtotals, not just the population total
    (docs/federated.md's threat-model section).
    """
    from ..client import SdaClient, relay as relay_mod
    from ..crypto import MemoryKeystore, sodium
    from ..protocol import RoundFailed, ServerError, SodiumEncryption

    if not sodium.available():
        raise RuntimeError("tree rounds need libsodium (real crypto)")
    inputs = np.asarray(inputs, dtype=np.int64)
    if inputs.ndim != 2:
        raise ValueError("inputs must be [participants, dim]")
    n, dim = inputs.shape
    scheme = _make_schemes(sharing, modulus, share_count)
    masking_scheme = _make_masking(masking, modulus, dim)

    if reset_obs:
        obs.reset_all()
        chaos.reset()
    own_service = service is None
    http_server = None
    if own_service:
        from ..server import (
            new_jsonfs_server, new_memory_server, new_sqlite_server)

        if store == "memory":
            service_impl = new_memory_server()
        elif store == "sqlite":
            service_impl = new_sqlite_server(store_path or ":memory:")
        elif store == "jsonfs":
            if store_path is None:
                raise ValueError("store='jsonfs' needs store_path")
            service_impl = new_jsonfs_server(store_path)
        else:
            raise ValueError(f"unknown store {store!r}")
    else:
        service_impl = service
    server = service_impl.server
    if dead_clerks_leaf:
        from ..server import lifecycle

        server.clerking_lease_seconds = lease_seconds
        server.round_deadlines = lifecycle.RoundDeadlines(
            clerking_s=clerking_deadline_s)
        sweeper = lifecycle.RoundSweeper(
            server, interval_s=sweep_interval_s).start()
    else:
        sweeper = None
    if http and own_service:
        from ..http import SdaHttpClient, SdaHttpServer

        http_server = SdaHttpServer(service_impl, bind="127.0.0.1:0")
        http_server.start_background()

        def client_service():
            return SdaHttpClient(http_server.address, token="tree-drill",
                                 max_retries=8, backoff_base=0.01,
                                 backoff_cap=0.1)
    else:
        def client_service():
            return service_impl

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        client = SdaClient(agent, keystore, client_service())
        client.upload_agent()
        return client

    def keyed(client):
        client.upload_encryption_key(client.new_encryption_key())
        return client

    report = TreeRoundReport(
        mode=f"tree round over {'HTTP' if http else 'in-process'} "
             f"({store} store)",
        participants=n, dim=dim, modulus=modulus, sharing=sharing,
        masking=masking, group_size=group_size, seed=seed,
        dropout_rate=dropout_rate, dead_clerks_leaf=dead_clerks_leaf,
    )
    try:
        with obs.span("tree.round", attributes={"participants": n,
                                                "seed": seed}):
            # -- identities (no chaos during setup: the drill targets the
            # round, exactly like chaos/drill.py)
            participants = [new_client() for _ in range(n)]
            # shard on seed-derived STABLE keys, not the freshly minted
            # agent uuids: the drill's plan (group memberships, dropout
            # impact, aggregation ids) must reproduce at a fixed seed.
            # Production sharding keys on real agent ids via plan_tree
            # directly — the ring mapping is the same either way.
            device_keys = [f"dev-{seed}-{ix}" for ix in range(n)]
            plan: TreePlan = plan_tree(
                device_keys, group_size=group_size, fanout=fanout,
                seed=f"tree-{seed}")
            participant_of = dict(zip(device_keys, participants))
            nodes = plan.nodes()
            relay_nodes = plan.relay_nodes()

            root = new_client()
            root_key = root.new_encryption_key()
            root.upload_encryption_key(root_key)
            relay_clients: Dict[str, SdaClient] = {}
            relay_ids = []
            for node in relay_nodes:
                client = new_client()
                key = client.new_encryption_key()
                client.upload_encryption_key(key)
                relay_clients[node.path] = client
                relay_ids.append((client.agent.id, key))

            # disjoint per-node committees from one clerk pool, so a
            # dead clerk at one leaf cannot bleed into another round
            committee_size = scheme.output_size
            pool = [keyed(new_client()) for _ in range(
                committee_size * len(nodes))]
            committees: Dict[str, List] = {}
            for ix, node in enumerate(nodes):
                committees[node.path] = pool[ix * committee_size:
                                             (ix + 1) * committee_size]

            aggregations = plan.build_aggregations(
                title=f"tree-{seed}",
                vector_dimension=dim,
                modulus=modulus,
                masking_scheme=masking_scheme,
                leaf_sharing=scheme,
                recipient_encryption_scheme=SodiumEncryption(),
                committee_encryption_scheme=SodiumEncryption(),
                root_recipient=root.agent.id,
                root_recipient_key=root_key,
                relays=relay_ids,
            )
            report["groups"] = len(plan.leaves())
            report["depth"] = plan.depth()
            report["levels"] = plan.level_table(scheme)
            if collect_leaf_subtotals and plan.depth() != 2:
                raise ValueError(
                    f"collect_leaf_subtotals needs a depth-2 tree (leaf "
                    f"relays feeding the root directly); this plan is "
                    f"depth {plan.depth()} — deeper levels re-mask, so "
                    "per-leaf unmasking no longer decomposes")

            def recipient_of(node):
                return (root if node.is_root
                        else relay_clients[node.path])

            for node in nodes:
                owner = recipient_of(node)
                owner.upload_aggregation(aggregations[node.path])
                owner.begin_aggregation_with(
                    node.aggregation_id,
                    [c.agent.id for c in committees[node.path]])

            # -- targeted leaf dead clerks: latch K members of the FIRST
            # leaf's committee permanently dead before any poll (ring
            # shards can come up empty and are dropped at plan time, so
            # never assume a particular group index survived)
            victims = []
            if dead_clerks_leaf:
                leaf0 = plan.leaves()[0]
                for clerk in committees[leaf0.path][:dead_clerks_leaf]:
                    clerk._dead = True
                    victims.append(str(clerk.agent.id))
                report["dead_clerks"] = victims
                report["dead_clerk_leaf"] = leaf0.path

            # -- leaf participation under chaos dropout
            if dropout_rate:
                chaos.configure("participant.dies", kill=True,
                                rate=dropout_rate, seed=seed)
            alive_rows: List[np.ndarray] = []
            leaf_of = {}
            for leaf in plan.leaves():
                for member in leaf.members:
                    leaf_of[member] = leaf
            taint_set = {int(i) for i in (taint_participants or ())}
            if taint_set and (min(taint_set) < 0 or max(taint_set) >= n):
                raise ValueError(
                    f"taint_participants indices must be in [0, {n}); "
                    f"got {sorted(taint_set)}")
            for ix, (key, row) in enumerate(zip(device_keys, inputs)):
                participant = participant_of[key]
                # the taint failpoint is armed around exactly this
                # device's upload: always-trigger, cleared immediately —
                # attacker identity is the caller's plan, not a rate draw
                if ix in taint_set:
                    chaos.configure("participant.taint_shares", taint=True)
                try:
                    participant.participate(
                        [int(x) for x in row], leaf_of[key].aggregation_id)
                finally:
                    if ix in taint_set:
                        chaos.clear("participant.taint_shares")
                if not participant._dead:
                    alive_rows.append(row)
            chaos.reset()  # dropout targets devices, not the levels above
            report["participants_dropped"] = n - len(alive_rows)

            # -- complete levels bottom-up
            by_level: Dict[int, List] = {}
            for node in nodes:
                by_level.setdefault(node.level, []).append(node)
            node_states: Dict[str, dict] = {}
            failed_paths: set = set()
            leaf_subtotals: List[dict] = []

            def pump(level_nodes) -> None:
                """Clerk the committees until every round at this level
                is result-ready or terminally diagnosed."""
                give_up = time.monotonic() + timeout_s
                pending = {node.path for node in level_nodes}
                while pending and time.monotonic() < give_up:
                    for path in list(pending):
                        for clerk in committees[path]:
                            try:
                                clerk.run_chores(-1)
                            except ServerError:
                                metrics.count("tree.clerk.transient")
                        node = next(x for x in level_nodes
                                    if x.path == path)
                        owner = recipient_of(node)
                        try:
                            status = owner.service.get_aggregation_status(
                                owner.agent, node.aggregation_id)
                            state = owner.service.get_round_status(
                                owner.agent, node.aggregation_id)
                        except ServerError:
                            continue
                        ready = any(s.result_ready
                                    for s in (status.snapshots
                                              if status else []))
                        # done on the round VERDICT (ready / degraded /
                        # terminal), or on bare result_ready when nothing
                        # tracks the round — same rule the relay applies
                        if state is None:
                            if ready:
                                pending.discard(path)
                        elif state.state in ("failed", "expired") or (
                                ready and state.state in ("ready",
                                                          "degraded",
                                                          "revealed")):
                            pending.discard(path)
                    if pending:
                        time.sleep(0.02)
                if pending:
                    raise TimeoutError(
                        f"tree level stalled: {sorted(pending)} not "
                        f"ready within {timeout_s}s")

            for level in sorted(by_level, reverse=True):
                if level == 0:
                    break  # the root completes below, after all relays
                level_nodes = by_level[level]
                for node in level_nodes:
                    skip = {c.path for c in node.children} & failed_paths
                    if skip:
                        # a failed child makes this round unrecoverable;
                        # never snapshot it — the sweeper's propagation
                        # delivers the verdict
                        failed_paths.add(node.path)
                        continue
                    with obs.span("tree.node", attributes={
                            "path": node.path, "level": node.level,
                            "aggregation": str(node.aggregation_id)}):
                        recipient_of(node).end_aggregation(
                            node.aggregation_id)
                active = [x for x in level_nodes
                          if x.path not in failed_paths]
                if active:
                    pump(active)
                for node in active:
                    client = relay_clients[node.path]
                    try:
                        total = relay_mod.relay_up(
                            client, node.aggregation_id,
                            node.parent.aggregation_id,
                            deadline=timeout_s)
                        node_states[node.path] = {
                            "level": node.level, "group": node.group,
                            "state": total.state or "revealed",
                            "participations": total.participations,
                            "results": total.results,
                        }
                        if collect_leaf_subtotals:
                            # the root unmasks THIS leaf individually:
                            # the leaf's mask ciphertexts are sealed to
                            # the root anyway (TreeLink redirects the
                            # seal), so no extra key material changes
                            # hands — only what the root LEARNS does
                            leaf_subtotals.append(_unmask_leaf_subtotal(
                                root, aggregations[node.path], total,
                                masking_scheme, modulus, node.path))
                    except RoundFailed as e:  # RoundExpired subclasses it
                        failed_paths.add(node.path)
                        node_states[node.path] = {
                            "level": node.level, "group": node.group,
                            "state": e.state or "failed",
                            "reason": e.reason,
                            "dead_clerks": [str(c) for c in e.dead_clerks],
                        }

            # -- the root round
            output = None
            failure = None
            root_node = plan.root
            if {c.path for c in root_node.children} & failed_paths:
                failed_paths.add(root_node.path)
            if root_node.path not in failed_paths:
                with obs.span("tree.node", attributes={
                        "path": root_node.path, "level": 0,
                        "aggregation": str(root_node.aggregation_id)}):
                    root.end_aggregation(root_node.aggregation_id)
                pump([root_node])
            try:
                output = root.await_result(
                    root_node.aggregation_id, deadline=timeout_s,
                    poll_interval=0.05)
            except RoundFailed as e:
                failure = {"type": type(e).__name__, "state": e.state,
                           "reason": e.reason,
                           "dead_clerks": [str(c) for c in e.dead_clerks]}
            final_root = root.service.get_round_status(
                root.agent, root_node.aggregation_id)
            node_states[root_node.path] = {
                "level": 0, "group": None,
                "state": final_root.state if final_root else None,
                "reason": final_root.reason if final_root else None,
            }
            report["node_states"] = node_states
            report["root_state"] = (final_root.state if final_root
                                    else None)
            report["root_reason"] = (final_root.reason if final_root
                                     else None)
            report["root_children"] = ([str(c) for c in
                                        final_root.children]
                                       if final_root else None)
            report["failure"] = failure
            if collect_leaf_subtotals:
                # ndarrays, like output_values: opt-in, not JSON-able
                report["leaf_subtotals"] = leaf_subtotals

            expected = (np.stack(alive_rows).sum(axis=0) % modulus
                        if alive_rows else np.zeros(dim, dtype=np.int64))
            if output is not None:
                revealed = output.positive().values
                report["exact"] = bool((revealed == expected).all())
                report["relays"] = int(output.participations or 0)
                if return_output:
                    report["output_values"] = revealed
                if dim <= 16:
                    report["output"] = [int(v) for v in revealed]
            else:
                report["exact"] = False

            # -- flat reference: the SAME surviving inputs through an
            # ordinary flat round on the same stack, revealed by a fresh
            # recipient — the bit-exactness bar for the hierarchy
            if flat_reference and alive_rows and output is not None:
                flat = _run_flat_reference(
                    new_client, keyed, np.stack(alive_rows), modulus, dim,
                    scheme, masking_scheme, timeout_s)
                report["flat_exact"] = bool(
                    (revealed == flat).all())
            elif flat_reference:
                report["flat_exact"] = None
    finally:
        failpoints = chaos.report()
        chaos.reset()
        if sweeper is not None:
            sweeper.stop()
        if http_server is not None:
            http_server.shutdown()

    counters = metrics.counter_report()
    report["counters"] = {
        k: v for k, v in counters.items()
        if k.startswith(("relay.", "tree.", "chaos.", "participant.",
                         "clerk.share.", "server.round.",
                         "server.snapshot."))
    }
    report["failpoints"] = failpoints or None
    # span linkage proof: the whole run is ONE trace rooted at
    # tree.round, so the root round's timeline contains its children
    timelines = obs.round_timelines()
    tree_trace = next((t for t in timelines if t["root"] == "tree.round"),
                      None)
    report["trace_spans"] = tree_trace["spans"] if tree_trace else 0
    report["trace_lanes"] = tree_trace["lanes"] if tree_trace else []
    return report


def _unmask_leaf_subtotal(root, aggregation, total, masking_scheme,
                          modulus, path):
    """Unmask ONE leaf's masked subtotal with the root's key: the leaf's
    mask ciphertexts ride the ``MaskedLeafTotal`` sealed to the root
    (``Aggregation.mask_seal_target``), so the root can subtract their
    combination from the masked values exactly like the flat reveal does
    for the population total — just scoped to one leaf. Returns the
    ``leaf_subtotals`` entry robust aggregation consumes."""
    values = np.asarray(total.values, dtype=np.int64)
    encs = total.mask_encryptions or []
    if encs:
        _, mask_key_id = aggregation.mask_seal_target()
        decryptor = root.crypto.new_share_decryptor(
            mask_key_id, aggregation.recipient_encryption_scheme)
        decrypted = [decryptor.decrypt(e) for e in encs]
        mask = root.crypto.new_mask_combiner(masking_scheme).combine(
            decrypted)
        values = values - np.asarray(mask, dtype=np.int64)
    return {"path": path,
            "participations": int(total.participations or 0),
            "values": np.mod(values, modulus).astype(np.int64)}


def _run_flat_reference(new_client, keyed, rows, modulus, dim, scheme,
                        masking_scheme, timeout_s):
    """One ordinary flat round over ``rows`` on the same service; returns
    the revealed vector (positive representatives)."""
    from ..protocol import Aggregation, AggregationId, SodiumEncryption

    recipient = new_client()
    recipient_key = recipient.new_encryption_key()
    recipient.upload_encryption_key(recipient_key)
    clerks = [keyed(new_client()) for _ in range(scheme.output_size)]
    aggregation = Aggregation(
        id=AggregationId.random(),
        title="tree-flat-reference",
        vector_dimension=dim,
        modulus=modulus,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=masking_scheme,
        committee_sharing_scheme=scheme,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation_with(
        aggregation.id, [c.agent.id for c in clerks])
    for row in rows:
        participant = new_client()
        participant.participate([int(x) for x in row], aggregation.id)
    recipient.end_aggregation(aggregation.id)
    give_up = time.monotonic() + timeout_s
    while time.monotonic() < give_up:
        for clerk in clerks:
            clerk.run_chores(-1)
        status = recipient.service.get_aggregation_status(
            recipient.agent, aggregation.id)
        if status and status.snapshots and status.snapshots[0].result_ready:
            break
        time.sleep(0.02)
    return recipient.await_result(
        aggregation.id, deadline=max(1.0, give_up - time.monotonic()),
        poll_interval=0.05).positive().values
