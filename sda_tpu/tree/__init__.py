"""Hierarchical (tree) aggregation — population-scale rounds over
recursive committees.

Flat committees cap out structurally: every clerk touches every
participation, so per-clerk round cost is O(participants) no matter how
many workers serve the fleet. The standard scale move for secure
aggregation at population scale (Bonawitz et al., "Towards Federated
Learning at Scale", MLSys 2019) is hierarchy: shard the population into
leaf groups whose committees produce encrypted partial aggregates
feeding a parent round — recursively, so every committee's cost is
O(group size) and the tree covers any population.

Privacy composes per level (docs/scaling.md):

- leaf participants seal masks to the **root** recipient
  (``TreeLink.mask_recipient_key``), shares to their leaf committee;
- each leaf's **relay** (``client/relay.py``) reconstructs only the
  *masked* leaf total, re-shares it into the parent round and forwards
  the mask ciphertexts upward unopened;
- only the root, holding the single mask key, unmasks — with the
  ordinary flat reveal.

Modules: :mod:`sda_tpu.tree.plan` (the planner: ring sharding, privacy /
quorum composition tables, aggregation construction),
:mod:`sda_tpu.tree.round` (the driver: runs every level through the real
server stack with lifecycle, chaos and span linkage), and
:mod:`sda_tpu.tree.sim` (the population-scale simulator behind the
``participants=1e5`` bench record, with bounded per-node memory asserted).
"""

from .plan import TreeNode, TreePlan, plan_tree, shard_groups  # noqa: F401
from .round import TreeRoundReport, run_tree_round  # noqa: F401
from .sim import simulate_population_round  # noqa: F401
