"""The tree planner: deterministic sharding + privacy/quorum composition.

Planning a population-scale round means answering three questions before
any resource exists:

1. **Who aggregates with whom?** ``shard_groups`` assigns participants to
   G leaf groups with the SAME consistent-hash ring the serving fleet
   routes aggregations with (``server/routing.py``): deterministic from
   the key alone (every planner computes the same shards with no
   coordination), balanced across groups, and minimal-movement when G
   changes by one — a population re-planned at G+1 keeps ~(G/(G+1)) of
   its assignments, so device-side caches and journals stay warm.
2. **Does privacy compose?** ``TreePlan.level_table`` lays out, per
   level, the committee's ``privacy_threshold`` (max colluding clerks
   that learn nothing) and ``reconstruction_threshold`` (min surviving
   results): an adversary must exceed some single level's privacy
   threshold — relays between levels see only masked totals, and every
   mask is sealed to the root.
3. **Does the arithmetic survive?** Shamir reconstruction returns the
   exact *integer* sum of the shared values, so each round's input count
   times the modulus must fit under the scheme's prime
   (``validate_headroom``). Relays reduce mod the aggregation modulus
   before re-sharing, so a parent needs headroom for its fan-in only —
   never for the whole population.

``TreePlan.build_aggregations`` then mints the actual resources: one
child aggregation per group plus a parent per internal node, each
carrying its :class:`~sda_tpu.protocol.TreeLink` (parent/children
linkage, level, group, and the root mask-recipient redirect). The
degenerate G=1 plan is a flat round plus one relay hop and reveals
bit-exactly the same output (tests/test_tree_plan.py,
tests/test_tree_round.py).
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Sequence

from ..protocol import (
    Aggregation,
    AggregationId,
    TreeLink,
)
from ..server.routing import DEFAULT_REPLICAS, HashRing

#: Namespace for deterministic aggregation ids minted by the planner
#: (uuid5 over plan-seed:node-path) — fixed-seed drills rebuild the exact
#: same tree, and a crash-replayed planner converges on the same ids.
_PLAN_NAMESPACE = uuid.UUID("8c90f3fa-52e9-4f19-9597-2b4b1be01877")


def shard_groups(
    keys: Sequence[str], groups: int, replicas: int = DEFAULT_REPLICAS
) -> List[List[str]]:
    """Assign ``keys`` (participant/agent ids) to ``groups`` leaf groups
    via the consistent-hash ring. Deterministic (SHA-256, no process
    state), near-balanced, and minimal-movement when ``groups`` changes
    by one — the Karger-ring properties the serving fleet already relies
    on, reused for population sharding."""
    if groups < 1:
        raise ValueError("need at least one group")
    ring = HashRing([f"group-{ix}" for ix in range(groups)],
                    replicas=replicas)
    out: List[List[str]] = [[] for _ in range(groups)]
    for key in keys:
        out[int(ring.node_for(str(key)).rsplit("-", 1)[1])].append(str(key))
    return out


class TreeNode:
    """One aggregation in the tree: the root (level 0), an internal
    relay node, or a leaf holding a participant shard."""

    __slots__ = ("path", "level", "group", "members", "children", "parent",
                 "aggregation_id")

    def __init__(self, path: str, level: int, group: Optional[int],
                 members: Optional[List[str]] = None):
        self.path = path          # stable tree-position label, e.g. "0/2"
        self.level = int(level)
        self.group = group        # leaf-group index (None for internal)
        self.members = list(members or [])  # participant keys (leaves)
        self.children: List["TreeNode"] = []
        self.parent: Optional["TreeNode"] = None
        self.aggregation_id: Optional[AggregationId] = None

    @property
    def is_leaf(self) -> bool:
        return not self.children

    @property
    def is_root(self) -> bool:
        return self.parent is None

    def fan_in(self) -> int:
        """Inputs this node's round aggregates: devices at a leaf,
        child relays at an internal node."""
        return len(self.members) if self.is_leaf else len(self.children)

    def walk(self):
        yield self
        for child in self.children:
            yield from child.walk()

    def __repr__(self):
        return (f"TreeNode(path={self.path!r}, level={self.level}, "
                f"fan_in={self.fan_in()})")


def plan_tree(
    participants: Sequence[str],
    *,
    group_size: int,
    fanout: Optional[int] = None,
    replicas: int = DEFAULT_REPLICAS,
    seed: str = "tree",
) -> "TreePlan":
    """Shard ``participants`` into leaf groups of about ``group_size``
    and stack relay levels until one root remains.

    ``fanout`` bounds an internal round's fan-in (child relays per
    parent); the default ``None`` means a single parent absorbs all G
    leaf relays — the 2-level tree. ``ceil(N / group_size)`` fixes the
    group COUNT; ring assignment is multinomial, so individual groups
    land *around* ``group_size``, not at-or-under it (size-sensitive
    scheme choices must check ``level_table``'s ``max_fan_in`` /
    ``validate_headroom``, which use the actual shards). A ring shard
    that comes up empty is dropped — every planned leaf has at least one
    member, and surviving groups keep their ring order. ``seed``
    namespaces the deterministic aggregation ids so independent trees
    never collide."""
    participants = [str(p) for p in participants]
    if not participants:
        raise ValueError("cannot plan a tree for zero participants")
    if group_size < 1:
        raise ValueError("group_size must be >= 1")
    if fanout is not None and fanout < 2:
        raise ValueError("fanout must be >= 2 (or None for one parent)")
    groups = max(1, -(-len(participants) // group_size))
    shards = shard_groups(participants, groups, replicas=replicas)
    # drop empty ring shards: a leaf round with no members has nothing
    # to aggregate and would feed a zero-length reconstruction upward
    nodes: List[TreeNode] = [
        TreeNode(path=f"leaf-{ix}", level=0, group=ix, members=shard)
        for ix, shard in enumerate(shards) if shard
    ]
    # stack levels bottom-up with contiguous chunking (the ring matters
    # for PARTICIPANT movement; internal nodes are plan-internal and
    # contiguous chunks keep sibling groups adjacent and deterministic).
    # A tree always has at least one relay hop — the degenerate G=1 plan
    # is one leaf under one root, the flat-equivalence fixture.
    height = 0
    while len(nodes) > 1 or height == 0:
        height += 1
        span = len(nodes) if fanout is None else fanout
        parents: List[TreeNode] = []
        for start in range(0, len(nodes), span):
            parent = TreeNode(path=f"l{height}-{start // span}",
                              level=0, group=None)
            for child in nodes[start:start + span]:
                child.parent = parent
                parent.children.append(child)
            parents.append(parent)
        nodes = parents
    root = nodes[0]
    # levels number root-down (root 0), matching TreeLink/RoundStatus
    depth = height
    for node in root.walk():
        node.level = depth - _height_of(node)
    return TreePlan(root=root, participants=participants, seed=str(seed))


def _height_of(node: TreeNode) -> int:
    return 0 if node.is_leaf else 1 + max(_height_of(c)
                                          for c in node.children)


class TreePlan:
    """A planned tree: topology + composition tables + resource minting."""

    def __init__(self, root: TreeNode, participants: List[str], seed: str):
        self.root = root
        self.participants = participants
        self.seed = seed
        for node in root.walk():
            node.aggregation_id = AggregationId(
                uuid.uuid5(_PLAN_NAMESPACE, f"{seed}:{node.path}"))

    # -- topology ----------------------------------------------------------
    def nodes(self) -> List[TreeNode]:
        return list(self.root.walk())

    def leaves(self) -> List[TreeNode]:
        return [n for n in self.nodes() if n.is_leaf]

    def relay_nodes(self) -> List[TreeNode]:
        """Every node whose recipient is a relay (all but the root), in
        deterministic walk order — the order ``build_aggregations``
        expects relay identities in."""
        return [n for n in self.nodes() if not n.is_root]

    def depth(self) -> int:
        """Number of levels (a flat-equivalent G=1 tree has 2)."""
        return 1 + max(n.level for n in self.nodes())

    def group_of(self, participant: str) -> int:
        for leaf in self.leaves():
            if str(participant) in leaf.members:
                return leaf.group
        raise KeyError(f"{participant} is not in this plan")

    # -- composition tables ------------------------------------------------
    def level_table(self, leaf_sharing, internal_sharing=None) -> List[dict]:
        """Per-level privacy/quorum composition: for each level, the
        round count, worst-case fan-in, and the committee thresholds in
        force. ``internal_sharing`` defaults to ``leaf_sharing`` (one
        committee shape everywhere)."""
        internal_sharing = internal_sharing or leaf_sharing
        by_level: Dict[int, List[TreeNode]] = {}
        for node in self.nodes():
            by_level.setdefault(node.level, []).append(node)
        table = []
        for level in sorted(by_level):
            members = by_level[level]
            leaf_level = all(n.is_leaf for n in members)
            scheme = leaf_sharing if leaf_level else internal_sharing
            table.append({
                "level": level,
                "rounds": len(members),
                "kind": "leaf" if leaf_level else
                        ("root" if level == 0 else "internal"),
                "max_fan_in": max(n.fan_in() for n in members),
                "committee_size": int(scheme.output_size),
                "privacy_threshold": int(scheme.privacy_threshold),
                "reconstruction_threshold":
                    int(scheme.reconstruction_threshold),
            })
        return table

    def validate_headroom(self, modulus: int, leaf_sharing,
                          internal_sharing=None) -> None:
        """Exactness guard for the two-ring case: when the aggregation
        modulus is SMALLER than a Shamir scheme's prime, reducing the
        reconstructed value mod the modulus is only correct if the exact
        integer sum of the round's inputs (each < modulus) never wrapped
        mod the prime — so fan-in x modulus must fit under it. Relays
        reduce mod the aggregation modulus before re-sharing, so each
        round only needs headroom for its own fan-in, never the
        population's. One-ring rounds (additive, or modulus == prime,
        where all arithmetic IS mod p) are wrap-free by construction."""
        for row in self.level_table(leaf_sharing, internal_sharing):
            scheme = (leaf_sharing if row["kind"] == "leaf"
                      else internal_sharing or leaf_sharing)
            prime = getattr(scheme, "prime_modulus", None)
            if prime is None or prime == int(modulus):
                continue  # one ring end to end, wrap-free
            need = row["max_fan_in"] * (int(modulus) - 1)
            if need >= prime:
                raise ValueError(
                    f"level {row['level']}: fan-in {row['max_fan_in']} x "
                    f"modulus {modulus} needs sum headroom {need} >= the "
                    f"scheme prime {prime}; shrink group_size/fanout or "
                    f"pick a larger prime")

    # -- resource minting --------------------------------------------------
    def build_aggregations(
        self,
        *,
        title: str,
        vector_dimension: int,
        modulus: int,
        masking_scheme,
        leaf_sharing,
        recipient_encryption_scheme,
        committee_encryption_scheme,
        root_recipient,
        root_recipient_key,
        relays: Sequence,
        internal_sharing=None,
    ) -> Dict[str, Aggregation]:
        """Mint one Aggregation per tree node, TreeLink-wired.

        ``relays`` aligns with :meth:`relay_nodes`: one ``(agent_id,
        encryption_key_id)`` per non-root node, naming that node's relay
        recipient. Every node shares the masking scheme (leaf masks and
        relay masks must combine in one ring at the root) and the mask
        redirect points at the root recipient. Returns ``{node.path:
        Aggregation}``."""
        internal_sharing = internal_sharing or leaf_sharing
        self.validate_headroom(modulus, leaf_sharing, internal_sharing)
        if masking_scheme.has_mask and \
                getattr(masking_scheme, "modulus", modulus) != int(modulus):
            raise ValueError(
                "tree rounds unmask in one ring: masking modulus "
                f"{masking_scheme.modulus} != aggregation modulus {modulus}")
        relay_nodes = self.relay_nodes()
        if len(relays) != len(relay_nodes):
            raise ValueError(
                f"need {len(relay_nodes)} relay identities "
                f"(one per non-root node), got {len(relays)}")
        relay_of = dict(zip((n.path for n in relay_nodes), relays))
        out: Dict[str, Aggregation] = {}
        for node in self.nodes():
            if node.is_root:
                recipient, recipient_key = root_recipient, root_recipient_key
                mask_recipient = mask_key = None  # masks already seal here
            else:
                recipient, recipient_key = relay_of[node.path]
                mask_recipient, mask_key = root_recipient, root_recipient_key
            out[node.path] = Aggregation(
                id=node.aggregation_id,
                title=(title if node.is_root
                       else f"{title}/{node.path}"),
                vector_dimension=vector_dimension,
                modulus=modulus,
                recipient=recipient,
                recipient_key=recipient_key,
                masking_scheme=masking_scheme,
                committee_sharing_scheme=(leaf_sharing if node.is_leaf
                                          else internal_sharing),
                recipient_encryption_scheme=recipient_encryption_scheme,
                committee_encryption_scheme=committee_encryption_scheme,
                tree=TreeLink(
                    root=self.root.aggregation_id,
                    parent=(None if node.is_root
                            else node.parent.aggregation_id),
                    children=[c.aggregation_id for c in node.children],
                    level=node.level,
                    group=node.group,
                    mask_recipient=mask_recipient,
                    mask_recipient_key=mask_key,
                ),
            )
        return out


