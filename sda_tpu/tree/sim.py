"""Population-scale tree simulation: the ``participants=1e5`` record.

Running 10⁵ real sealed-box participations through HTTP is a throughput
benchmark, not a planning check — what population scale actually stresses
is the *shape* of the computation: does the planner shard 10⁵ devices
deterministically, does the modular tree algebra reveal the exact flat
sum, and does any single node ever have to materialize more than a
bounded batch? This simulator answers exactly those questions with the
real planner (ring sharding over 10⁵ keys) and the real tree algebra
(mask, per-leaf masked totals mod m, relay reduction, root unmask) — it
elides only the ciphertexts, whose per-item cost is already measured by
the HTTP drills at small scale.

Memory discipline mirrors the production pipeline
(``server/snapshot.py``'s chunked mask collection): every per-leaf pass
streams participant batches of ``batch`` rows, each batch's live arrays
are counted against ``peak_node_elements``, and the drill ASSERTS the
peak stays a function of the batch size, never of the population. Inputs
and masks are regenerated per-batch from seeded counters, so the flat
reference can re-walk the same population without holding it either.

The returned record is BENCH-shaped (``metric``/``value``/``unit``) and
rides the regression gate advisory in ci.sh via ``sda-bench --check``.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .plan import TreePlan, plan_tree

#: Live arrays per streamed batch: inputs, masks, masked (x batch x dim).
_ARRAYS_PER_BATCH = 3


_KIND_TAGS = {"x": 1, "m": 2}


def _batch_rng(seed: int, leaf_group: int, batch_ix: int, kind: str):
    # SeedSequence is deterministic across processes (unlike str hash),
    # which is what lets the flat reference re-walk the same population
    return np.random.default_rng(np.random.SeedSequence(
        [int(seed), int(leaf_group), int(batch_ix), _KIND_TAGS[kind]]))


def simulate_population_round(
    participants: int = 100_000,
    *,
    group_size: int = 4096,
    fanout: Optional[int] = None,
    dim: int = 8,
    modulus: int = (1 << 31) - 1,
    batch: int = 2048,
    seed: int = 0,
) -> dict:
    """Simulate one fixed-seed tree round at population scale.

    Returns the BENCH-style record with the verdicts the ci.sh drill
    gates on: ``exact`` (tree total == flat total, bit-exact),
    ``bounded`` (peak per-node elements never exceeded the streamed
    bound), and ``value`` = simulated participants aggregated per second
    (higher is better, advisory on CPU).
    """
    import tracemalloc

    if participants < 1:
        raise ValueError("need at least one participant")
    t0 = time.perf_counter()
    keys = [f"dev-{seed}-{ix}" for ix in range(participants)]
    plan: TreePlan = plan_tree(keys, group_size=group_size, fanout=fanout,
                               seed=f"sim-{seed}")
    leaves = plan.leaves()

    peak_node_elements = 0
    bound_elements = _ARRAYS_PER_BATCH * batch * dim

    def observe(*arrays) -> None:
        nonlocal peak_node_elements
        live = sum(int(a.size) for a in arrays)
        if live > peak_node_elements:
            peak_node_elements = live

    # the bounded-memory verdict must be a MEASUREMENT, not an
    # accounting identity: tracemalloc (numpy allocations route through
    # it) watches the whole streaming pass below — planning, which
    # legitimately holds the O(N) key list, stays outside the window.
    # Any future change that materializes the population inside the
    # pass blows the peak past the batch-derived bound and fails the
    # drill, whatever observe() happens to count.
    was_tracing = tracemalloc.is_tracing()
    if not was_tracing:
        tracemalloc.start()
    baseline, _ = tracemalloc.get_traced_memory()
    tracemalloc.reset_peak()

    # -- leaf passes: masked totals (what each leaf committee + relay
    # computes) and the root's mask total (what the forwarded ciphertexts
    # decrypt to at the root) — accumulated in O(batch) memory per node
    masked_leaf_totals = np.zeros((len(leaves), dim), dtype=np.int64)
    root_mask_total = np.zeros(dim, dtype=np.int64)
    flat_total = np.zeros(dim, dtype=np.int64)  # the reference walk
    for pos, leaf in enumerate(leaves):
        members = len(leaf.members)
        leaf_masked = np.zeros(dim, dtype=np.int64)
        for batch_ix, start in enumerate(range(0, members, batch)):
            rows = min(batch, members - start)
            inputs = _batch_rng(seed, leaf.group, batch_ix, "x").integers(
                0, modulus, size=(rows, dim), dtype=np.int64)
            masks = _batch_rng(seed, leaf.group, batch_ix, "m").integers(
                0, modulus, size=(rows, dim), dtype=np.int64)
            masked = (inputs + masks) % modulus
            observe(inputs, masks, masked)
            # object dtype for the column sums: rows x modulus exceeds
            # int64 long before 1e5 rows (bit-exactness, not speed)
            leaf_masked = (leaf_masked
                           + masked.astype(object).sum(axis=0)) % modulus
            root_mask_total = (root_mask_total
                               + masks.astype(object).sum(axis=0)) % modulus
            flat_total = (flat_total
                          + inputs.astype(object).sum(axis=0)) % modulus
        # the relay reduces mod m before re-sharing (client/relay.py)
        masked_leaf_totals[pos] = leaf_masked.astype(np.int64)

    # -- upper levels: each internal round sums its children's (already
    # reduced) relay inputs; the root unmasks with every forwarded mask
    tree_masked_total = (
        masked_leaf_totals.astype(object).sum(axis=0) % modulus)
    tree_total = (tree_masked_total - root_mask_total) % modulus
    exact = bool((tree_total == flat_total).all())
    _, traced_peak = tracemalloc.get_traced_memory()
    peak_pass_bytes = max(0, traced_peak - baseline)
    if not was_tracing:
        tracemalloc.stop()
    # the measured bound: the streamed batch arrays (int64 inputs/masks/
    # masked plus transient temporaries of the modular ops and the
    # object-dtype column sums) — a generous constant factor of the
    # batch footprint plus fixed slack, NEVER a function of N
    bound_pass_bytes = 8 * bound_elements * 4 + (1 << 20)
    seconds = time.perf_counter() - t0

    shard_sizes = [len(leaf.members) for leaf in leaves]
    return {
        "metric": (f"tree sim throughput ({participants} participants, "
                   f"depth {plan.depth()}, streamed batch {batch})"),
        "value": round(participants / max(seconds, 1e-9), 1),
        "unit": "participants/sec",
        "platform": "cpu",
        "seed": seed,
        "mode": "simulated tree round (real planner, modular algebra, "
                "streamed batches)",
        "participants": participants,
        "dim": dim,
        "modulus": modulus,
        "groups": len(leaves),
        "depth": plan.depth(),
        "group_min": min(shard_sizes),
        "group_max": max(shard_sizes),
        "levels": plan.level_table(_SimScheme()),
        "batch": batch,
        "seconds": round(seconds, 4),
        "exact": exact,
        # the bounded-memory verdict the acceptance gates on: measured
        # allocation peak of the streaming pass (tracemalloc) vs the
        # batch-derived bound — both independent of N — plus the
        # explicit per-batch element count as a cross-check
        "peak_node_elements": peak_node_elements,
        "bound_elements": bound_elements,
        "peak_pass_bytes": peak_pass_bytes,
        "bound_pass_bytes": bound_pass_bytes,
        "bounded": (peak_node_elements <= bound_elements
                    and peak_pass_bytes <= bound_pass_bytes),
    }


class _SimScheme:
    """Committee-shape stand-in for the simulator's level table (the sim
    has no crypto; the drill committees are the HTTP drills' business)."""

    output_size = 8
    privacy_threshold = 4
    reconstruction_threshold = 7
