"""Randomness policy for security-critical draws (shares, masks).

The reference draws every share/mask element from OsRng (additive.rs:17,
full.rs:16) — information-theoretically fresh. JAX's threefry keys are only
64 bits, so deriving a whole share vector from one PRNGKey would cap the
scheme's privacy at brute-forcible 2^63 work. Policy here:

- ``secure`` (default): draws come from the ChaCha20 PRG keyed with a fresh
  256-bit OS seed per operation (sda_tpu.fields.chacha) — computational
  security at the PRG level, host-side.
- ``fast``: on-device threefry from a 63-bit OS seed — for benchmarks and
  trusted-simulation runs where the adversary model is absent. Callers must
  opt in explicitly (``set_mode("fast")`` or the ``mode=`` argument).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import fields
from ..fields import chacha
from .core import fresh_prng_key

_MODE = "secure"
_MODES = ("secure", "fast")


def set_mode(mode: str) -> None:
    global _MODE
    if mode not in _MODES:
        raise ValueError(f"unknown randomness mode {mode!r}; choose from {_MODES}")
    _MODE = mode


def get_mode() -> str:
    return _MODE


def uniform(shape: Tuple[int, ...], modulus: int, mode: Optional[str] = None) -> np.ndarray:
    """Uniform int64 draws in [0, modulus) under the active policy."""
    mode = mode or _MODE
    if mode == "fast":
        return np.asarray(fields.uniform_mod(fresh_prng_key(), tuple(shape), modulus))
    n = int(np.prod(shape)) if shape else 1
    flat = chacha.expand_mask(chacha.random_seed(256), n, modulus)
    return flat.reshape(shape)
