"""L1b: crypto modules — sharing, masking, encryption, signing.

``CryptoModule`` is the factory facade the client roles use
(client/src/crypto/mod.rs:58-66): constructed over a keystore, it builds
scheme-dispatched maskers/generators/encryptors from the scheme values
carried in Aggregation resources.
"""

from __future__ import annotations

from typing import Optional

from ..protocol import (
    Agent,
    AgentId,
    EncryptionKeyId,
    Labelled,
    Signed,
    VerificationKeyId,
)
from . import encryption, masking, paillier, sharing, signing, sodium, varint
from .encryption import paillier_combine
from .core import (
    DecryptionKey,
    EncryptionKeypair,
    Keystore,
    MemoryKeystore,
    SignatureKeypair,
    fresh_prng_key,
)
from .signing import signature_is_valid


class CryptoModule:
    """Factory for all crypto primitives, bound to a keystore."""

    def __init__(self, keystore: Keystore):
        self.keystore = keystore

    # -- key generation ----------------------------------------------------
    def new_encryption_key(self, scheme=None) -> EncryptionKeyId:
        """Fresh encryption keypair; ``scheme`` selects the key type
        (default Sodium/Curve25519, PackedPaillierEncryption for Paillier)."""
        keypair = encryption.new_encryption_keypair(scheme)
        key_id = EncryptionKeyId.random()
        self.keystore.put_encryption_keypair(key_id, keypair)
        return key_id

    def new_verification_key(self) -> Labelled:
        return signing.new_labelled_verification_key(self.keystore)

    def sign_export(self, agent: Agent, key_id: EncryptionKeyId) -> Optional[Signed]:
        return signing.sign_export(agent, key_id, self.keystore)

    # -- masking -----------------------------------------------------------
    def new_secret_masker(self, scheme):
        return masking.new_secret_masker(scheme)

    def new_mask_combiner(self, scheme):
        return masking.new_mask_combiner(scheme)

    def new_secret_unmasker(self, scheme):
        return masking.new_secret_unmasker(scheme)

    # -- sharing -----------------------------------------------------------
    def new_share_generator(self, scheme):
        return sharing.new_share_generator(scheme)

    def new_share_combiner(self, scheme):
        return sharing.new_share_combiner(scheme)

    def new_secret_reconstructor(self, scheme, dimension: int):
        return sharing.new_secret_reconstructor(scheme, dimension)

    # -- encryption --------------------------------------------------------
    def new_share_encryptor(self, ek, scheme):
        return encryption.new_share_encryptor(ek, scheme)

    def new_share_decryptor(self, key_id, scheme):
        return encryption.new_share_decryptor(key_id, scheme, self.keystore)
