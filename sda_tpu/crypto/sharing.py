"""Scheme-dispatched sharing: generators, combiner, reconstructors.

The role-level interface of the reference (client/src/crypto/sharing/mod.rs:
ShareGenerator :14-17, ShareCombiner :23-25, SecretReconstructor :31-33),
re-based on the TPU kernels in sda_tpu.fields: additive sharing is a fused
draw-and-subtract; packed Shamir is a cached share-matrix matmul; both are
already batched over the full vector dimension (the reference's per-batch
loop, batched.rs:18-99, is a reshape here).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import fields
from ..fields import numtheory, oracle
from ..protocol import (
    AdditiveSharing,
    BasicShamirSharing,
    LinearSecretSharingScheme,
    PackedShamirSharing,
)
from . import rand

import os

#: Below this much output work (elements), run the exact host/NumPy oracle
#: path instead of dispatching to the device: a phone-sized vector (the
#: reference's design point, README.md:8-11) costs microseconds on host but
#: seconds of XLA compile + tunnel RTT per fresh shape on the accelerator.
#: Both paths are bit-identical given identical randomness (tests assert
#: device == oracle), so the dispatch is purely a latency decision.
HOST_PATH_MAX = int(os.environ.get("SDA_HOST_PATH_MAX", 1 << 16))


def _small(total_elements: int) -> bool:
    return total_elements <= HOST_PATH_MAX


def mod_combine(vectors: Sequence[np.ndarray], modulus: int) -> np.ndarray:
    """Elementwise modular sum across participants — the clerk kernel
    (combiner.rs:15-30); shared by share- and mask-combining."""
    vecs = [np.asarray(v, dtype=np.int64) for v in vectors]
    if not vecs:
        return np.zeros(0, dtype=np.int64)
    stacked = np.stack(vecs)
    if _small(stacked.size):
        # oracle.combine canonicalizes internally — no second % pass
        return oracle.combine(stacked, modulus)
    # Canonicalize before the device sum: modsum's overflow-exact chunking
    # derives its fan from the modulus and assumes residues in [0, m).
    # Fresh shares satisfy that, but Paillier-premixed clerk batches
    # decrypt to UNREDUCED sums (encryption.py PackedPaillierDecryptor),
    # and at wide component windows those could wrap an int64 partial sum.
    return np.asarray(fields.combine(jnp.asarray(stacked % modulus),
                                     modulus=modulus))


class ShareGenerator:
    def generate(self, secrets: Sequence[int]) -> List[np.ndarray]:
        """Secrets vector -> per-clerk share vectors (len == output_size)."""
        raise NotImplementedError


class ShareCombiner:
    def __init__(self, modulus: int):
        self.modulus = modulus

    def combine(self, share_vectors: Sequence[np.ndarray]) -> np.ndarray:
        return mod_combine(share_vectors, self.modulus)


class SecretReconstructor:
    def reconstruct(self, indexed_shares: Sequence[Tuple[int, np.ndarray]]) -> np.ndarray:
        """(clerk index, share vector) pairs -> secrets vector."""
        raise NotImplementedError


class AdditiveShareGenerator(ShareGenerator):
    def __init__(self, scheme: AdditiveSharing):
        self.scheme = scheme

    def generate(self, secrets):
        arr = np.asarray(secrets, dtype=np.int64)
        draws = rand.uniform((self.scheme.share_count - 1, arr.shape[-1]), self.scheme.modulus)
        if _small(self.scheme.share_count * arr.shape[-1]):
            return list(oracle.additive_share_from_randomness(
                arr, draws, modulus=self.scheme.modulus
            ))
        shares = fields.additive_share_from_randomness(
            jnp.asarray(arr), jnp.asarray(draws), modulus=self.scheme.modulus
        )
        return list(np.asarray(shares))


class AdditiveReconstructor(SecretReconstructor):
    def __init__(self, scheme: AdditiveSharing):
        self.scheme = scheme

    def reconstruct(self, indexed_shares):
        # additive sharing is n-of-n: a missing share makes the sum an
        # unrelated uniform value, so fail closed like the Shamir
        # reconstructor does below its quorum — silently summing a
        # partial set would reveal garbage as if it were the aggregate
        r = self.scheme.reconstruction_threshold
        if len(indexed_shares) < r:
            raise ValueError(
                f"need at least {r} shares to reconstruct, got "
                f"{len(indexed_shares)} (additive sharing cannot tolerate "
                f"share loss)"
            )
        return mod_combine([v for (_, v) in indexed_shares], self.scheme.modulus)


class PackedShamirShareGenerator(ShareGenerator):
    def __init__(self, scheme: PackedShamirSharing):
        self.scheme = scheme
        self._M_device = None

    @property
    def _M(self):
        # built lazily so host-path-only use never touches the device
        if self._M_device is None:
            self._M_device = jnp.asarray(
                numtheory.share_matrix_for(self.scheme))
        return self._M_device

    def generate(self, secrets):
        s = self.scheme
        arr = np.asarray(secrets, dtype=np.int64)
        B = -(-arr.shape[-1] // s.secret_count)
        randomness = rand.uniform((s.privacy_threshold, B), s.prime_modulus)
        if _small(s.share_count * B):
            return list(oracle.packed_share_from_randomness(arr, randomness, s))
        shares = fields.packed_share_from_randomness(
            jnp.asarray(arr), jnp.asarray(randomness), self._M,
            prime=s.prime_modulus, secret_count=s.secret_count,
        )
        return list(np.asarray(shares))


class PackedShamirReconstructor(SecretReconstructor):
    def __init__(self, scheme: PackedShamirSharing, dimension: int):
        self.scheme = scheme
        self.dimension = dimension

    def reconstruct(self, indexed_shares):
        s = self.scheme
        # fixed-survivor-count kernel (SURVEY §7d): any quorum of exactly
        # reconstruction_threshold shares interpolates the same polynomial,
        # so truncate larger survivor sets — the device matmul then has ONE
        # shape [r+1, B] per (scheme, dimension) and never recompiles as
        # clerks drop in and out (round-1 verdict: per-subset re-jits would
        # compile-storm 80-clerk committees)
        r = s.reconstruction_threshold
        if len(indexed_shares) < r:
            raise ValueError(
                f"need at least {r} shares to reconstruct, got "
                f"{len(indexed_shares)}"
            )
        indexed_shares = list(indexed_shares)[:r]
        indices = tuple(int(i) for (i, _) in indexed_shares)
        stacked_np = np.stack([np.asarray(v, dtype=np.int64) for (_, v) in indexed_shares])
        if _small(stacked_np.size):
            return oracle.packed_reconstruct(indices, stacked_np, s, self.dimension)
        L = jnp.asarray(numtheory.reconstruct_matrix_for(s, indices))
        return np.asarray(fields.packed_reconstruct(
            jnp.asarray(stacked_np), L, prime=s.prime_modulus, dimension=self.dimension
        ))


def new_share_generator(scheme: LinearSecretSharingScheme) -> ShareGenerator:
    if isinstance(scheme, AdditiveSharing):
        return AdditiveShareGenerator(scheme)
    if isinstance(scheme, (PackedShamirSharing, BasicShamirSharing)):
        # BasicShamir rides the packed machinery as its k=1 degenerate:
        # same [0; secrets; randomness] column layout, scheme-dispatched
        # matrices (numtheory.share_matrix_for)
        return PackedShamirShareGenerator(scheme)
    raise ValueError(f"unknown sharing scheme {scheme!r}")


def new_share_combiner(scheme: LinearSecretSharingScheme) -> ShareCombiner:
    if isinstance(scheme, AdditiveSharing):
        return ShareCombiner(scheme.modulus)
    if isinstance(scheme, (PackedShamirSharing, BasicShamirSharing)):
        return ShareCombiner(scheme.prime_modulus)
    raise ValueError(f"unknown sharing scheme {scheme!r}")


def new_secret_reconstructor(
    scheme: LinearSecretSharingScheme, dimension: int
) -> SecretReconstructor:
    if isinstance(scheme, AdditiveSharing):
        return AdditiveReconstructor(scheme)
    if isinstance(scheme, (PackedShamirSharing, BasicShamirSharing)):
        return PackedShamirReconstructor(scheme, dimension)
    raise ValueError(f"unknown sharing scheme {scheme!r}")
