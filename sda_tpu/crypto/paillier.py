"""Packed Paillier: additively homomorphic share-transport encryption.

The reference *declares* this scheme but ships it disabled —
``AdditiveEncryptionScheme::PackedPaillier`` is commented out with exactly
four parameters (`protocol/src/crypto.rs:164-174`): ``component_count``
(values packed per ciphertext), ``component_bitsize`` (bit window per
component), ``max_value_bitsize`` (bound on fresh values), and
``min_modulus_bitsize`` (plaintext-modulus floor). This module implements
the scheme for real, so a committee can *sum ciphertexts without ever
decrypting shares*: Paillier ciphertexts multiply to add their plaintexts,
and the bit-window headroom ``component_bitsize - max_value_bitsize``
guarantees packed components never carry into each other for up to
``2^headroom`` summands.

Everything here is host-side ``int`` arithmetic (public-key crypto has no
business on the MXU); the bulk field math stays on device. Keys are
CRT-accelerated on decrypt. No external dependencies — primality testing is
deterministic-for-64-bit / random-witness Miller-Rabin over ``secrets``.
"""

from __future__ import annotations

import functools
import math
import secrets
from dataclasses import dataclass
from typing import List, Sequence

# deterministic witness set: correct for all n < 3.3e24 (Sorenson & Webster)
_SMALL_WITNESSES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41)
_SMALL_PRIMES = [p for p in range(2, 1000) if all(p % q for q in range(2, p)) and p > 1]


def is_probable_prime(n: int, rounds: int = 40) -> bool:
    """Miller-Rabin. Deterministic below 3.3e24, else ``rounds`` random bases."""
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n == p:
            return True
        if n % p == 0:
            return False
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    if n < 3_317_044_064_679_887_385_961_981:
        witnesses: Sequence[int] = _SMALL_WITNESSES
    else:
        witnesses = [secrets.randbelow(n - 3) + 2 for _ in range(rounds)]
    for a in witnesses:
        x = pow(a % n, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def random_prime(bits: int) -> int:
    """A uniform ``bits``-bit probable prime (top two bits set so p*q is full-width)."""
    if bits < 8:
        raise ValueError("prime size must be at least 8 bits")
    while True:
        candidate = secrets.randbits(bits) | (1 << (bits - 1)) | (1 << (bits - 2)) | 1
        if is_probable_prime(candidate):
            return candidate


@dataclass(frozen=True)
class PaillierPublicKey:
    """n = p*q; g is fixed to n+1 (standard, makes encryption one mulmod)."""

    n: int

    @property
    def n_squared(self) -> int:
        return self.n * self.n

    @property
    def bitsize(self) -> int:
        return self.n.bit_length()

    def to_bytes(self) -> bytes:
        return self.n.to_bytes((self.n.bit_length() + 7) // 8, "big")

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PaillierPublicKey":
        return cls(int.from_bytes(raw, "big"))


@dataclass(frozen=True)
class PaillierSecretKey:
    """Factorisation of n, with CRT decryption precomputation."""

    p: int
    q: int

    @functools.cached_property
    def n(self) -> int:
        return self.p * self.q

    @functools.cached_property
    def _crt(self) -> tuple:
        """Per-key constants: (p^2, q^2, hp, hq, p^-1 mod q).

        hp = L((n+1)^(p-1) mod p^2)^-1 mod p = ((p-1)*q)^-1 mod p; likewise
        hq. Cached so decrypting a large ciphertext batch does one extended
        gcd per key, not three per ciphertext.
        """
        p, q = self.p, self.q
        hp = pow((p - 1) * q % p, -1, p)
        hq = pow((q - 1) * p % q, -1, q)
        return (p * p, q * q, hp, hq, pow(p, -1, q))

    def to_bytes(self) -> bytes:
        pb = self.p.to_bytes((self.p.bit_length() + 7) // 8, "big")
        qb = self.q.to_bytes((self.q.bit_length() + 7) // 8, "big")
        return len(pb).to_bytes(4, "big") + pb + qb

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PaillierSecretKey":
        plen = int.from_bytes(raw[:4], "big")
        return cls(int.from_bytes(raw[4 : 4 + plen], "big"),
                   int.from_bytes(raw[4 + plen :], "big"))


def _powmod(base: int, exp: int, mod: int) -> int:
    """pow() with the native Montgomery ladder when it wins (odd moduli at
    Paillier sizes — ~3-4x CPython's pow at 2048-bit keys, sda_native.cpp
    sda_powmod); falls back to builtin pow silently."""
    if exp >= 0 and (mod & 1) and mod.bit_length() >= 512:
        from .. import native

        if native.available():
            try:
                return native.powmod(base, exp, mod)
            except (ValueError, RuntimeError):
                pass
    return pow(base, exp, mod)


def keygen(modulus_bits: int) -> tuple[PaillierPublicKey, PaillierSecretKey]:
    """Fresh keypair with an exactly-``modulus_bits``-bit n."""
    half = modulus_bits // 2
    while True:
        p = random_prime(half)
        q = random_prime(modulus_bits - half)
        if p != q:
            n = p * q
            if n.bit_length() == modulus_bits:
                return PaillierPublicKey(n), PaillierSecretKey(p, q)


def encrypt(pk: PaillierPublicKey, m: int, r: int | None = None) -> int:
    """c = (1 + m*n) * r^n  mod n^2 (g = n+1 shortcut)."""
    if not 0 <= m < pk.n:
        raise ValueError("plaintext out of range [0, n)")
    n, n2 = pk.n, pk.n_squared
    if r is None:
        while True:
            r = secrets.randbelow(n)
            if r and math.gcd(r, n) == 1:
                break
    return (1 + m * n) % n2 * _powmod(r, n, n2) % n2


def add(pk: PaillierPublicKey, c1: int, c2: int) -> int:
    """Homomorphic plaintext addition: ciphertext multiplication mod n^2."""
    return c1 * c2 % pk.n_squared


def decrypt(sk: PaillierSecretKey, c: int) -> int:
    """CRT decryption: ~4x faster than the textbook lambda/mu path."""
    p, q, n = sk.p, sk.q, sk.n
    if not 0 <= c < n * n:
        raise ValueError("ciphertext out of range [0, n^2)")
    p2, q2, hp, hq, p_inv_q = sk._crt
    mp = (_powmod(c % p2, p - 1, p2) - 1) // p * hp % p
    mq = (_powmod(c % q2, q - 1, q2) - 1) // q * hq % q
    return mp + p * ((mq - mp) * p_inv_q % q)


# ---------------------------------------------------------------------------
# Component packing (crypto.rs:165-173 parameter semantics)

def pack(values: Sequence[int], component_bitsize: int) -> int:
    """Pack values little-endian-component-first into one plaintext int."""
    m = 0
    for i, v in enumerate(values):
        if v < 0 or v.bit_length() > component_bitsize:
            raise ValueError(
                f"component {v} exceeds the {component_bitsize}-bit window"
            )
        m |= v << (i * component_bitsize)
    return m


def unpack(m: int, component_count: int, component_bitsize: int) -> List[int]:
    mask = (1 << component_bitsize) - 1
    return [(m >> (i * component_bitsize)) & mask for i in range(component_count)]
