"""Batched Paillier ciphertext premixing on the accelerator.

The server's Paillier hot loop is homomorphic premix-combine: folding P
ciphertexts per (clerk, slot) with multiplication mod n^2
(reference server snapshot premixing, /root/reference/server/src/snapshot.rs:4-47,
with the PackedPaillier scheme /root/reference/protocol/src/crypto.rs:164-174).
Host bigint premix measures ~428k el/s (BENCH_SUITE paillier-2048 with the
native Montgomery ladder); a flagship round needs ~6M 4096-bit modmuls per
round, i.e. ~10 minutes of single-core host premix. This module is the
TPU-native prototype (round-3 verdict #7): ciphertexts as [B, L] arrays of
8-bit limbs in int32 lanes, batched Montgomery (CIOS) multiplication as
jit-compiled vector ops — the per-limb outer loop is sequential, but every
step is a [B, L] multiply-accumulate the VPU vectorizes across the batch.

Design notes:
- base 256 limbs: products <= 255^2, so an int32 lane accumulates ~512
  redundant partial products without overflow (max ~6.7e7 < 2^31) — no
  emulated int64 anywhere.
- redundant CIOS: limbs grow past 256 during the loop and are normalized
  once at the end by an exact lax.scan carry pass, then conditionally
  reduced by one subtract-with-borrow scan (Montgomery output < 2m).
- fold-without-conversion: montmul(x, y) = x*y*R^-1, so folding P
  NORMAL-form ciphertexts gives prod * R^-(P-1); one extra montmul with
  the host-precomputed R^P mod m restores the exact product — no
  per-ciphertext Montgomery conversions.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


class MontgomeryContext:
    """Precomputed limb-domain constants for an odd modulus."""

    BASE = 256

    def __init__(self, modulus: int):
        if modulus <= 0 or modulus % 2 == 0:
            raise ValueError("Montgomery requires a positive odd modulus")
        self.modulus = modulus
        self.L = (modulus.bit_length() + 7) // 8
        self.m_limbs = np.array(
            [(modulus >> (8 * i)) & 0xFF for i in range(self.L)],
            dtype=np.int32)
        # n' = -m^-1 mod 256 (m odd -> invertible)
        self.n_prime = (-pow(modulus, -1, self.BASE)) % self.BASE
        self.R = pow(self.BASE, self.L, modulus)

    # -- host <-> limb conversion ----------------------------------------
    def to_limbs(self, values: Sequence[int]) -> np.ndarray:
        """[B] python ints (< modulus) -> [B, L] int32 limbs."""
        out = np.zeros((len(values), self.L), dtype=np.int32)
        for b, v in enumerate(values):
            if not 0 <= v < self.modulus:
                raise ValueError("value out of range for modulus")
            out[b] = [(v >> (8 * i)) & 0xFF for i in range(self.L)]
        return out

    def from_limbs(self, arr) -> List[int]:
        """[B, L] canonical limbs -> [B] python ints."""
        a = np.asarray(arr)
        return [sum(int(a[b, i]) << (8 * i) for i in range(a.shape[1]))
                for b in range(a.shape[0])]

    def fold_fix(self, count: int) -> np.ndarray:
        """[L] limbs of R^count mod m: folding ``count`` normal-form
        factors through montmul leaves prod * R^-(count-1); one final
        montmul by this constant (another * R^-1) restores the product."""
        return self.to_limbs([pow(self.R, count, self.modulus)])[0]

    # -- jittable kernels -------------------------------------------------
    def mont_mul_fn(self):
        """Batched montmul(a, b) = a*b*R^-1 mod m over [B, L] int32 limbs.

        Redundant CIOS: L sequential steps of [B, L] vector MACs, one
        exact carry-normalize scan, one conditional subtract scan.
        """
        import jax
        import jax.numpy as jnp

        L = self.L
        m_limbs = jnp.asarray(self.m_limbs)
        n_prime = jnp.int32(self.n_prime)

        def carry_normalize(t):  # [B, L+1] redundant -> canonical
            def step(carry, col):
                tot = col + carry
                return tot >> 8, tot & 0xFF

            carry, cols = jax.lax.scan(step, jnp.zeros(t.shape[0], jnp.int32),
                                       jnp.moveaxis(t, 1, 0))
            return jnp.moveaxis(cols, 0, 1), carry

        def cond_subtract(t, extra):  # t [B, L+1] canonical, extra [B]
            tm = jnp.concatenate(
                [m_limbs, jnp.zeros((1,), jnp.int32)])[None, :]

            def step(borrow, cols):
                tj, mj = cols
                d = tj - mj + borrow
                return d >> 8, d & 0xFF  # arithmetic shift: borrow in {-1,0}

            borrow, cols = jax.lax.scan(
                step, jnp.zeros(t.shape[0], jnp.int32),
                (jnp.moveaxis(t, 1, 0), jnp.broadcast_to(
                    jnp.moveaxis(tm, 1, 0), (t.shape[1], t.shape[0]))))
            diff = jnp.moveaxis(cols, 0, 1)
            # value >= m iff no final borrow (extra limbs beyond L+1 are
            # zero for Montgomery outputs < 2m)
            take_diff = ((borrow + extra) >= 0)[:, None]
            return jnp.where(take_diff, diff, t)

        def mont_mul(a, b):
            B = a.shape[0]
            t = jnp.zeros((B, L + 1), jnp.int32)

            def body(i, t):
                ai = jax.lax.dynamic_slice_in_dim(a, i, 1, axis=1)  # [B,1]
                t = t.at[:, :L].add(ai * b)
                u = ((t[:, 0] & 0xFF) * n_prime) & 0xFF  # [B]
                t = t.at[:, :L].add(u[:, None] * m_limbs[None, :])
                c0 = t[:, 0] >> 8  # t[:,0] == 0 mod 256 by choice of u
                t = jnp.roll(t, -1, axis=1)
                t = t.at[:, -1].set(0)
                t = t.at[:, 0].add(c0)
                return t

            t = jax.lax.fori_loop(0, L, body, t)
            t, extra = carry_normalize(t)
            return cond_subtract(t, extra)[:, :L + 1]

        return mont_mul

    def premix_fn(self):
        """Batched premix: [P, B, L] normal-form ciphertexts -> [B, L]
        exact product mod m (= Paillier homomorphic sum of P ciphertexts
        per batch lane). Jit once per (P, B) shape."""
        import jax
        import jax.numpy as jnp

        mont_mul = self.mont_mul_fn()

        def premix(cts, fix_limbs):
            # accept narrow dtypes so callers can feed uint8 limbs over
            # the wire (512 B/ciphertext instead of 2 KiB of int32)
            cts = cts.astype(jnp.int32)
            P = cts.shape[0]
            pad = jnp.zeros((cts.shape[1], 1), jnp.int32)
            acc = jnp.concatenate([cts[0], pad], axis=1)  # [B, L+1]

            def body(i, acc):
                return mont_mul(acc[:, :self.L], cts[i])

            acc = jax.lax.fori_loop(1, P, body, acc)
            fix = jnp.broadcast_to(fix_limbs[None, :],
                                   (cts.shape[1], self.L))
            return mont_mul(acc[:, :self.L], fix)[:, :self.L]

        return premix

    def premix_jit(self):
        """The jitted premix callable, built once and cached on self so
        repeated calls (the server premixes one block per (clerk, slot)
        per round) hit jax's compilation cache per input shape."""
        import jax

        if not hasattr(self, "_premix_jit"):
            self._premix_jit = jax.jit(self.premix_fn())
        return self._premix_jit

    def premix(self, cts_ints: Sequence[Sequence[int]]) -> List[int]:
        """Convenience host API: [P][B] python-int ciphertexts -> [B]
        products mod m. Builds limb arrays, runs the cached jitted kernel
        on the default device, converts back."""
        import jax.numpy as jnp

        P = len(cts_ints)
        cts = np.stack([self.to_limbs(row) for row in cts_ints])
        fix = self.fold_fix(P)
        out = self.premix_jit()(jnp.asarray(cts.astype(np.uint8)),
                                jnp.asarray(fix))
        return self.from_limbs(np.asarray(out))
