"""Share-transport encryption: sealed boxes over varint-packed shares.

Reference: client/src/crypto/encryption/{mod,sodium}.rs — shares are
zigzag-varint encoded then sealed to the receiver's Curve25519 key
(anonymous sender). The varint packing is part of the wire format and is
kept bit-compatible (sodium.rs:36-45).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..protocol import (
    AdditiveEncryptionScheme,
    Binary,
    Encryption,
    EncryptionKey,
    EncryptionKeyId,
    SodiumEncryption,
)
from . import sodium, varint
from .core import DecryptionKey, EncryptionKeypair, Keystore


class ShareEncryptor:
    def encrypt(self, shares: Sequence[int]) -> Encryption:
        raise NotImplementedError


class ShareDecryptor:
    def decrypt(self, encryption: Encryption) -> np.ndarray:
        raise NotImplementedError


class SodiumEncryptor(ShareEncryptor):
    def __init__(self, ek: EncryptionKey):
        if ek.variant != "Sodium":
            raise ValueError(f"unsupported encryption key variant {ek.variant}")
        self._pk = ek.value.data

    def encrypt(self, shares):
        payload = varint.encode(np.asarray(shares, dtype=np.int64))
        return Encryption("Sodium", Binary(sodium.seal(payload, self._pk)))


class SodiumDecryptor(ShareDecryptor):
    def __init__(self, key_id: EncryptionKeyId, keystore: Keystore):
        keypair = keystore.get_encryption_keypair(key_id)
        if keypair is None:
            raise ValueError("could not load keypair for decryption")
        self._pk = keypair.ek.value.data
        self._sk = keypair.dk.value.data

    def decrypt(self, encryption):
        if encryption.variant != "Sodium":
            raise ValueError(f"unsupported encryption variant {encryption.variant}")
        payload = sodium.seal_open(encryption.value.data, self._pk, self._sk)
        return varint.decode(payload)


def new_share_encryptor(ek: EncryptionKey, scheme: AdditiveEncryptionScheme) -> ShareEncryptor:
    if isinstance(scheme, SodiumEncryption):
        return SodiumEncryptor(ek)
    raise ValueError(f"unknown encryption scheme {scheme!r}")


def new_share_decryptor(
    key_id: EncryptionKeyId, scheme: AdditiveEncryptionScheme, keystore: Keystore
) -> ShareDecryptor:
    if isinstance(scheme, SodiumEncryption):
        return SodiumDecryptor(key_id, keystore)
    raise ValueError(f"unknown encryption scheme {scheme!r}")


def new_encryption_keypair() -> EncryptionKeypair:
    """Fresh Curve25519 keypair wrapped in protocol types (sodium.rs:95-109)."""
    from ..protocol import B32

    pk, sk = sodium.box_keypair()
    return EncryptionKeypair(
        ek=EncryptionKey("Sodium", B32(pk)),
        dk=DecryptionKey("Sodium", B32(sk)),
    )
