"""Share-transport encryption: sealed boxes over varint-packed shares.

Reference: client/src/crypto/encryption/{mod,sodium}.rs — shares are
zigzag-varint encoded then sealed to the receiver's Curve25519 key
(anonymous sender). The varint packing is part of the wire format and is
kept bit-compatible (sodium.rs:36-45).

Also implements the reference's *declared-but-disabled* PackedPaillier
scheme (crypto.rs:164-174) for real — additively homomorphic ciphertext
batches that let shares be summed without decryption (``paillier_combine``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Sequence, Tuple

import numpy as np

from ..protocol import (
    AdditiveEncryptionScheme,
    Binary,
    Encryption,
    EncryptionKey,
    EncryptionKeyId,
    PackedPaillierEncryption,
    SodiumEncryption,
)
from . import paillier, sodium, varint
from .core import DecryptionKey, EncryptionKeypair, Keystore


class ShareEncryptor:
    def encrypt(self, shares: Sequence[int]) -> Encryption:
        raise NotImplementedError


class ShareDecryptor:
    def decrypt(self, encryption: Encryption) -> np.ndarray:
        raise NotImplementedError


class SodiumEncryptor(ShareEncryptor):
    def __init__(self, ek: EncryptionKey):
        if ek.variant != "Sodium":
            raise ValueError(f"unsupported encryption key variant {ek.variant}")
        self._pk = ek.value.data

    def encrypt(self, shares):
        payload = varint.encode(np.asarray(shares, dtype=np.int64))
        return Encryption("Sodium", Binary(sodium.seal(payload, self._pk)))


class SodiumDecryptor(ShareDecryptor):
    def __init__(self, key_id: EncryptionKeyId, keystore: Keystore):
        keypair = keystore.get_encryption_keypair(key_id)
        if keypair is None:
            raise ValueError("could not load keypair for decryption")
        self._pk = keypair.ek.value.data
        self._sk = keypair.dk.value.data

    def decrypt(self, encryption):
        if encryption.variant != "Sodium":
            raise ValueError(f"unsupported encryption variant {encryption.variant}")
        payload = sodium.seal_open(encryption.value.data, self._pk, self._sk)
        return varint.decode(payload)


class PackedPaillierEncryptor(ShareEncryptor):
    """Shares -> one framed batch of packed Paillier ciphertexts.

    Wire format of the ``PackedPaillier`` payload: LEB128(share count),
    LEB128(summand count), then per ciphertext LEB128(byte length) +
    big-endian bytes. The last plaintext is zero-padded to
    ``component_count`` (stripped on decrypt via the recorded share count).
    A fresh encryption has summand count 1; ``paillier_combine`` adds the
    counts, so window-overflow validation survives nested/incremental
    combining.
    """

    def __init__(self, ek: EncryptionKey, scheme: PackedPaillierEncryption):
        if ek.variant != "PackedPaillier":
            raise ValueError(f"unsupported encryption key variant {ek.variant}")
        self._pk = paillier.PaillierPublicKey.from_bytes(ek.value.data)
        if self._pk.bitsize < scheme.min_modulus_bitsize:
            raise ValueError(
                f"{self._pk.bitsize}-bit key below the scheme's "
                f"{scheme.min_modulus_bitsize}-bit floor"
            )
        self._scheme = scheme

    def encrypt(self, shares):
        s = self._scheme
        values = [int(v) for v in np.asarray(shares, dtype=np.int64)]
        for v in values:
            if v < 0 or v.bit_length() > s.max_value_bitsize:
                raise ValueError(
                    f"share {v} outside the fresh-value bound "
                    f"2^{s.max_value_bitsize} (crypto.rs:169-171 semantics)"
                )
        out = [_leb128(len(values)), _leb128(1)]
        for i in range(0, len(values), s.component_count):
            m = paillier.pack(values[i : i + s.component_count], s.component_bitsize)
            c = paillier.encrypt(self._pk, m)
            raw = c.to_bytes((c.bit_length() + 7) // 8 or 1, "big")
            out.append(_leb128(len(raw)) + raw)
        return Encryption("PackedPaillier", Binary(b"".join(out)))


class PackedPaillierDecryptor(ShareDecryptor):
    def __init__(self, key_id: EncryptionKeyId, keystore: Keystore,
                 scheme: PackedPaillierEncryption):
        keypair = keystore.get_encryption_keypair(key_id)
        if keypair is None:
            raise ValueError("could not load keypair for decryption")
        if keypair.dk.variant != "PackedPaillier":
            raise ValueError(f"unsupported decryption key variant {keypair.dk.variant}")
        self._sk = paillier.PaillierSecretKey.from_bytes(keypair.dk.value.data)
        self._scheme = scheme

    def decrypt(self, encryption):
        if encryption.variant != "PackedPaillier":
            raise ValueError(f"unsupported encryption variant {encryption.variant}")
        s = self._scheme
        count, summands, ciphertexts = _unframe_paillier(encryption.value.data)
        if summands > s.additive_capacity:
            raise ValueError(
                f"batch records {summands} summands, over the scheme's "
                f"additive capacity of {s.additive_capacity}"
            )
        values: list = []
        for c in ciphertexts:
            m = paillier.decrypt(self._sk, c)
            values.extend(paillier.unpack(m, s.component_count, s.component_bitsize))
        if len(values) < count:
            raise ValueError("ciphertext batch shorter than its declared share count")
        return np.asarray(values[:count], dtype=np.int64)


def paillier_combine(ek: EncryptionKey, scheme: PackedPaillierEncryption,
                     encryptions: Sequence[Encryption]) -> Encryption:
    """Homomorphic share combine: multiply ciphertext batches componentwise.

    This is the point of PackedPaillier — a clerk (or the server itself) sums
    participants' share vectors *without decrypting anything*; the plaintext
    components add under the ciphertext product. All batches must have the
    same length; the accumulated fresh-summand count (tracked in the wire
    frame, so nested/incremental combines are safe) must stay within
    ``scheme.additive_capacity`` — then integer sums can't wrap inside the
    window and the recipient recovers the modular sum exactly by reducing
    the decrypted sums ``mod m``.
    """
    if not encryptions:
        raise ValueError("nothing to combine")
    if ek.variant != "PackedPaillier":
        raise ValueError(f"unsupported encryption key variant {ek.variant}")
    pk = paillier.PaillierPublicKey.from_bytes(ek.value.data)
    if pk.bitsize < scheme.min_modulus_bitsize:
        raise ValueError(
            f"{pk.bitsize}-bit key below the scheme's "
            f"{scheme.min_modulus_bitsize}-bit floor"
        )
    import os

    # default host path folds INCREMENTALLY (O(B) working set); only the
    # opt-in device path batches rows (its users accept the O(P*B) staging
    # in exchange for the kernel fold)
    device = os.environ.get("SDA_PREMIX_DEVICE") == "1"
    count: Optional[int] = None
    batch_len: Optional[int] = None
    total_summands = 0
    rows: list = []
    acc: list = []
    for e in encryptions:
        if e.variant != "PackedPaillier":
            raise ValueError(f"unsupported encryption variant {e.variant}")
        n, summands, cs = _unframe_paillier(e.value.data)
        total_summands += summands
        if count is None:
            count, batch_len = n, len(cs)
        elif n != count or len(cs) != batch_len:
            raise ValueError("mismatched batch shapes in homomorphic combine")
        if device:
            rows.append(list(cs))
        elif not acc:
            acc = list(cs)
        else:
            acc = [paillier.add(pk, a, c) for a, c in zip(acc, cs)]
    if device:
        acc = _premix_rows(pk, rows)
    # summand counts accumulate through nested combines, so the window-
    # overflow bound holds for the TOTAL number of fresh encryptions folded
    # in, not just this call's operand list
    if total_summands > scheme.additive_capacity:
        raise ValueError(
            f"{total_summands} accumulated summands exceed the scheme's "
            f"additive capacity of {scheme.additive_capacity}"
        )
    out = [_leb128(count), _leb128(total_summands)]
    for c in acc:
        raw = c.to_bytes((c.bit_length() + 7) // 8 or 1, "big")
        out.append(_leb128(len(raw)) + raw)
    return Encryption("PackedPaillier", Binary(b"".join(out)))


#: device premix engages only when the fold is big enough to amortize the
#: kernel dispatch (and, once per shape bucket, its compile)
_DEVICE_PREMIX_MIN_MODMULS = 64
#: rows per device fold chunk: bounds the [P, B, L] upload block (~23 KB
#: per row at 2048-bit keys) while keeping each dispatch large
_DEVICE_PREMIX_CHUNK_ROWS = 512

#: MontgomeryContext per n^2, tiny LRU: a long-lived broker rotates
#: committee keys, and each context pins compiled kernels — keep only the
#: few most recent instead of growing forever
_MONT_CTX_CACHE: "OrderedDict" = OrderedDict()
_MONT_CTX_CACHE_MAX = 4


def _premix_rows(pk, rows: list) -> list:
    """Fold [P][B] ciphertext ints to [B] products mod n^2 (the device
    leg of paillier_combine: bit-identical to the host paillier.add fold;
    the server's premix hot loop scales with P, reference
    server/src/snapshot.rs:4-47). Rows are chunked
    (_DEVICE_PREMIX_CHUNK_ROWS bounds every upload block), each chunk
    padded with ciphertext 1 — the multiplicative identity, so padding
    never changes the product — to a power-of-two row count that bounds
    the number of compiled shapes. Folds below the size floor, and any
    device failure, fall back to the host fold (premixing is an
    optimization, never a correctness dependency)."""
    if len(rows) <= 1:
        return list(rows[0]) if rows else []
    B = len(rows[0])
    if len(rows) * B >= _DEVICE_PREMIX_MIN_MODMULS:
        try:
            return _device_premix_rows(pk, rows)
        except Exception as e:  # noqa: BLE001 — optimization, not contract
            import logging

            logging.getLogger(__name__).warning(
                "device premix failed (%s: %s); falling back to host fold",
                type(e).__name__, e)
    acc = list(rows[0])
    for cs in rows[1:]:
        acc = [paillier.add(pk, a, c) for a, c in zip(acc, cs)]
    return acc


def _mont_ctx(modulus):
    from .paillier_tpu import MontgomeryContext

    ctx = _MONT_CTX_CACHE.get(modulus)
    if ctx is None:
        ctx = _MONT_CTX_CACHE[modulus] = MontgomeryContext(modulus)
        while len(_MONT_CTX_CACHE) > _MONT_CTX_CACHE_MAX:
            _MONT_CTX_CACHE.popitem(last=False)
    else:
        _MONT_CTX_CACHE.move_to_end(modulus)
    return ctx


def _device_premix_rows(pk, rows: list) -> list:
    ctx = _mont_ctx(pk.n_squared)
    B = len(rows[0])
    # tree reduction: every level folds chunks of at most
    # _DEVICE_PREMIX_CHUNK_ROWS rows, so no single dispatch (including
    # the reduction over partial products) exceeds the upload bound
    while len(rows) > 1:
        next_rows = []
        for lo in range(0, len(rows), _DEVICE_PREMIX_CHUNK_ROWS):
            chunk = rows[lo:lo + _DEVICE_PREMIX_CHUNK_ROWS]
            if len(chunk) == 1:
                next_rows.append(chunk[0])
                continue
            P = 1 << (len(chunk) - 1).bit_length()  # pow2 bucket
            chunk = chunk + [[1] * B] * (P - len(chunk))
            next_rows.append(ctx.premix(chunk))
        rows = next_rows
    return rows[0]


def _leb128(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_leb128(raw: bytes, pos: int) -> Tuple[int, int]:
    n = shift = 0
    while True:
        if pos >= len(raw):
            raise ValueError("truncated varint in PackedPaillier payload")
        if shift > 63:
            raise ValueError("oversized varint in PackedPaillier payload")
        b = raw[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _unframe_paillier(raw: bytes) -> Tuple[int, int, list]:
    count, pos = _read_leb128(raw, 0)
    summands, pos = _read_leb128(raw, pos)
    if summands < 1:
        raise ValueError("PackedPaillier batch records zero summands")
    ciphertexts = []
    while pos < len(raw):
        ln, pos = _read_leb128(raw, pos)
        if pos + ln > len(raw):
            raise ValueError("truncated ciphertext frame in PackedPaillier payload")
        ciphertexts.append(int.from_bytes(raw[pos : pos + ln], "big"))
        pos += ln
    return count, summands, ciphertexts


#: Public names for the LEB128 framing helpers: the binary wire codec
#: (``protocol/bincodec.py``) frames its lengths with the exact same
#: encoding the PackedPaillier payload uses.
leb128 = _leb128
read_leb128 = _read_leb128


def new_share_encryptor(ek: EncryptionKey, scheme: AdditiveEncryptionScheme) -> ShareEncryptor:
    if isinstance(scheme, SodiumEncryption):
        return SodiumEncryptor(ek)
    if isinstance(scheme, PackedPaillierEncryption):
        return PackedPaillierEncryptor(ek, scheme)
    raise ValueError(f"unknown encryption scheme {scheme!r}")


def new_share_decryptor(
    key_id: EncryptionKeyId, scheme: AdditiveEncryptionScheme, keystore: Keystore
) -> ShareDecryptor:
    if isinstance(scheme, SodiumEncryption):
        return SodiumDecryptor(key_id, keystore)
    if isinstance(scheme, PackedPaillierEncryption):
        return PackedPaillierDecryptor(key_id, keystore, scheme)
    raise ValueError(f"unknown encryption scheme {scheme!r}")


def new_encryption_keypair(
    scheme: Optional[AdditiveEncryptionScheme] = None,
) -> EncryptionKeypair:
    """Fresh keypair for ``scheme`` (default Sodium, sodium.rs:95-109):
    Curve25519 for Sodium, an exactly-min_modulus_bitsize-bit Paillier
    modulus for PackedPaillier."""
    from ..protocol import B32

    if scheme is None or isinstance(scheme, SodiumEncryption):
        pk, sk = sodium.box_keypair()
        return EncryptionKeypair(
            ek=EncryptionKey("Sodium", B32(pk)),
            dk=DecryptionKey("Sodium", B32(sk)),
        )
    if isinstance(scheme, PackedPaillierEncryption):
        ppk, psk = paillier.keygen(scheme.min_modulus_bitsize)
        return EncryptionKeypair(
            ek=EncryptionKey("PackedPaillier", Binary(ppk.to_bytes())),
            dk=DecryptionKey("PackedPaillier", Binary(psk.to_bytes())),
        )
    raise ValueError(f"unknown encryption scheme {scheme!r}")
