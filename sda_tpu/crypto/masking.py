"""Scheme-dispatched masking: None / Full / ChaCha.

Reference semantics (client/src/crypto/masking/): the participant masks its
secrets so the committee only ever sees ``secret + mask`` while the recipient
gets the mask (encrypted); unmasking subtracts the combined masks from the
reconstructed combined masked secrets.

- None (none.rs): empty mask, identity.
- Full (full.rs): per-element fresh uniform mask, uploaded in full — here
  generated on-device by threefry.
- ChaCha (chacha.rs): the uploaded "mask" is the PRG *seed* (u32 words,
  serialized as i64s); both sides expand it with the scheme's tagged
  ChaCha20 PRG (sda_tpu.fields.chacha) — the default CHACHA_PRG_RAND03 is
  the exact rand-0.3 ChaChaRng stream the reference draws (rand-0.3 wire
  interop), CHACHA_PRG_V1 the TPU-native opt-in spec.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import fields
from ..fields import chacha
from ..protocol import (
    ChaChaMasking,
    FullMasking,
    LinearMaskingScheme,
    NoMasking,
)
from . import rand
from .sharing import _small, mod_combine


class SecretMasker:
    def mask(self, secrets: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """-> (mask-to-upload, masked secrets)."""
        raise NotImplementedError


class MaskCombiner:
    def combine(self, masks: Sequence[np.ndarray]) -> np.ndarray:
        """Sum uploaded masks (expanding seeds where applicable)."""
        raise NotImplementedError


class SecretUnmasker:
    def unmask(self, mask: np.ndarray, masked: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class NoneMasker(SecretMasker, MaskCombiner, SecretUnmasker):
    def mask(self, secrets):
        return np.zeros(0, dtype=np.int64), np.asarray(secrets, dtype=np.int64)

    def combine(self, masks):
        assert all(len(m) == 0 for m in masks)
        return np.zeros(0, dtype=np.int64)

    def unmask(self, mask, masked):
        assert len(mask) == 0
        return np.asarray(masked, dtype=np.int64)


class FullMasker(SecretMasker, MaskCombiner, SecretUnmasker):
    def __init__(self, modulus: int):
        self.modulus = modulus

    def mask(self, secrets):
        arr = np.asarray(secrets, dtype=np.int64)
        masks = rand.uniform(arr.shape, self.modulus)
        if _small(arr.size):
            return masks, (arr + masks) % self.modulus
        masked = np.asarray(
            fields.modadd(jnp.asarray(arr), jnp.asarray(masks), self.modulus)
        )
        return masks, masked

    def combine(self, masks):
        return mod_combine(masks, self.modulus)

    def unmask(self, mask, masked):
        masked = np.asarray(masked, dtype=np.int64)
        mask = np.asarray(mask, dtype=np.int64)
        if _small(masked.size):
            return (masked - mask) % self.modulus
        return np.asarray(
            fields.modsub(jnp.asarray(masked), jnp.asarray(mask), self.modulus)
        )


class ChaChaMasker(SecretMasker, MaskCombiner, SecretUnmasker):
    def __init__(self, modulus: int, dimension: int, seed_bitsize: int,
                 prg: str = chacha.CHACHA_PRG_RAND03):
        if prg not in chacha._EXPANDERS:  # defense in depth vs the scheme
            raise ValueError(f"unknown ChaCha PRG {prg!r}")
        self.modulus = modulus
        self.dimension = dimension
        self.seed_bitsize = seed_bitsize
        self.prg = prg

    @staticmethod
    def _device_backend() -> bool:
        import jax

        try:
            return jax.default_backend() != "cpu"
        except Exception:
            return False

    def _expand(self, seed):
        from .. import native

        if self._device_backend():
            from ..fields import chacha_jax

            return chacha_jax.expand_mask(
                seed, self.dimension, self.modulus, prg=self.prg
            )
        if native.available():
            return native.chacha_expand_mask(
                seed, self.dimension, self.modulus, prg=self.prg
            )
        return chacha.expand_mask_for(self.prg, seed, self.dimension, self.modulus)

    def mask(self, secrets):
        secrets = np.asarray(secrets, dtype=np.int64)
        assert secrets.shape == (self.dimension,)
        seed = chacha.random_seed(self.seed_bitsize)
        mask_vec = self._expand(seed)
        masked = (secrets + mask_vec) % self.modulus
        return np.asarray(seed, dtype=np.int64), masked

    def combine(self, seeds):
        """Re-expand every participant's seed — the recipient hot loop
        (receive.rs:102-118 for the ChaCha case, chacha.rs:57-77); served by
        the native C++ kernel when present."""
        from .. import native

        if len(seeds) == 0:
            return np.zeros(self.dimension, dtype=np.int64)
        stacked = np.stack([np.asarray(s, dtype=np.int64) for s in seeds])
        if self._device_backend():
            from ..fields import chacha_jax

            # the expander takes any word sequence: hand it the stacked
            # rows directly, no per-word Python-int materialization
            return chacha_jax.combine_masks(
                stacked, self.dimension, self.modulus, prg=self.prg,
            )
        if native.available():
            return native.chacha_combine_masks(
                stacked, self.dimension, self.modulus, prg=self.prg
            )
        result = np.zeros(self.dimension, dtype=np.int64)
        for seed in stacked:
            expanded = chacha.expand_mask_for(
                self.prg, seed, self.dimension, self.modulus
            )
            result = (result + expanded) % self.modulus
        return result

    def unmask(self, mask, masked):
        return (np.asarray(masked, dtype=np.int64) - np.asarray(mask, dtype=np.int64)) % self.modulus


def new_secret_masker(scheme: LinearMaskingScheme) -> SecretMasker:
    return _dispatch(scheme)


def new_mask_combiner(scheme: LinearMaskingScheme) -> MaskCombiner:
    return _dispatch(scheme)


def new_secret_unmasker(scheme: LinearMaskingScheme) -> SecretUnmasker:
    return _dispatch(scheme)


def _dispatch(scheme: LinearMaskingScheme):
    if isinstance(scheme, NoMasking):
        return NoneMasker()
    if isinstance(scheme, FullMasking):
        return FullMasker(scheme.modulus)
    if isinstance(scheme, ChaChaMasking):
        return ChaChaMasker(
            scheme.modulus, scheme.dimension, scheme.seed_bitsize,
            prg=scheme.prg,
        )
    raise ValueError(f"unknown masking scheme {scheme!r}")
