"""Vectorized zigzag-varint codec for i64 share vectors.

Wire format matches the reference's share encoding: each i64 is zigzag-mapped
to u64 then LEB128-encoded with 7-bit groups and continuation bits (the
integer-encoding crate's VarInt for signed types, used inside sealed boxes at
client/src/crypto/encryption/sodium.rs:36-45, 84-90). Implemented in numpy
over the whole vector at once — no Python-per-element loops — so encoding a
million-share payload stays in the tens of milliseconds.
"""

from __future__ import annotations

import numpy as np

_MAX_BYTES = 10  # 64 bits / 7 bits per byte, rounded up


def encode(values: np.ndarray) -> bytes:
    """[N] int64 -> varint bytes (zigzag + LEB128)."""
    v = np.asarray(values, dtype=np.int64)
    u = ((v.astype(np.uint64) << np.uint64(1)) ^ (v >> np.int64(63)).astype(np.uint64))
    # bytes needed: 1 + #{j in 1..9 : u >= 2^(7j)}
    nbytes = np.ones(v.shape, dtype=np.int64)
    for j in range(1, _MAX_BYTES):
        nbytes += (u >= np.uint64(1 << (7 * j))).astype(np.int64)
    # 7-bit groups with continuation bits
    j_idx = np.arange(_MAX_BYTES, dtype=np.uint64)
    groups = (u[:, None] >> (np.uint64(7) * j_idx)) & np.uint64(0x7F)
    cont = (j_idx[None, :] < (nbytes[:, None] - 1)).astype(np.uint64) * np.uint64(0x80)
    mat = (groups | cont).astype(np.uint8)
    mask = j_idx[None, :] < nbytes[:, None].astype(np.uint64)
    return mat[mask].tobytes()


def decode(data: bytes) -> np.ndarray:
    """varint bytes -> [N] int64; raises ValueError on malformed input."""
    b = np.frombuffer(data, dtype=np.uint8)
    if b.size == 0:
        return np.zeros(0, dtype=np.int64)
    is_last = (b & 0x80) == 0
    if not is_last[-1]:
        raise ValueError("truncated varint stream (trailing continuation bit)")
    ends = np.nonzero(is_last)[0]
    starts = np.concatenate([[0], ends[:-1] + 1])
    lengths = ends - starts + 1
    if lengths.max() > _MAX_BYTES:
        raise ValueError("varint longer than 10 bytes")
    # a 10th byte may only carry the single remaining bit of a u64; anything
    # larger would silently wrap out of the 64-bit accumulator
    ten_byte_finals = b[ends[lengths == _MAX_BYTES]]
    if ten_byte_finals.size and ten_byte_finals.max() > 1:
        raise ValueError("varint overflows 64 bits")
    pos = np.arange(b.size, dtype=np.uint64) - np.repeat(
        starts.astype(np.uint64), lengths
    )
    contrib = (b & 0x7F).astype(np.uint64) << (np.uint64(7) * pos)
    u = np.add.reduceat(contrib, starts)
    return ((u >> np.uint64(1)).astype(np.int64)) ^ -(u & np.uint64(1)).astype(np.int64)
