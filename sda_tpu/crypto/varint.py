"""Vectorized zigzag-varint codec for i64 share vectors.

Wire format matches the reference's share encoding: each i64 is zigzag-mapped
to u64 then LEB128-encoded with 7-bit groups and continuation bits (the
integer-encoding crate's VarInt for signed types, used inside sealed boxes at
client/src/crypto/encryption/sodium.rs:36-45, 84-90). Implemented in numpy
over the whole vector at once — no Python-per-element loops — so encoding a
million-share payload stays in the tens of milliseconds.
"""

from __future__ import annotations

import numpy as np

_MAX_BYTES = 10  # 64 bits / 7 bits per byte, rounded up


def encode(values: np.ndarray) -> bytes:
    """[N] int64 -> varint bytes (zigzag + LEB128).

    Scatter-by-byte-index: pass ``j`` writes byte ``j`` of every varint
    still that long, directly into the output buffer at precomputed
    offsets. Touches ``sum(nbytes)`` elements total instead of the dense
    ``[N, 10]`` staging matrix a gather formulation needs (~3.5x faster
    at production dimension; output is bit-identical).
    """
    v = np.asarray(values, dtype=np.int64)
    if v.size == 0:
        return b""
    u = ((v.astype(np.uint64) << np.uint64(1)) ^ (v >> np.int64(63)).astype(np.uint64))
    # bytes needed: 1 + #{j in 1..9 : u >= 2^(7j)}
    nbytes = np.ones(v.shape, dtype=np.int64)
    for j in range(1, _MAX_BYTES):
        nbytes += (u >= np.uint64(1 << (7 * j))).astype(np.int64)
    offsets = np.cumsum(nbytes) - nbytes  # start of each value's frame
    out = np.empty(int(offsets[-1] + nbytes[-1]), dtype=np.uint8)
    alive = np.arange(v.size)
    for j in range(int(nbytes.max())):
        if j:
            alive = alive[nbytes[alive] > j]  # shrinking survivor set
        group = (u[alive] >> np.uint64(7 * j)) & np.uint64(0x7F)
        cont = np.where(nbytes[alive] - 1 > j, np.uint64(0x80), np.uint64(0))
        out[offsets[alive] + j] = (group | cont).astype(np.uint8)
    return out.tobytes()


def decode(data: bytes) -> np.ndarray:
    """varint bytes -> [N] int64; raises ValueError on malformed input.

    Gather formulation: after the one unavoidable byte-level pass that
    finds value boundaries, everything runs on value-count arrays — pass
    ``j`` gathers byte ``j`` of every varint that long and ORs its 7-bit
    group into a ``[N]`` u64 accumulator (~4x faster than per-byte
    shift/reduce at production dimension). Safe without overflow checks
    up to 9-byte varints (63 bits); streams containing a 10-byte varint
    take the checked slow lane.
    """
    b = np.frombuffer(data, dtype=np.uint8)
    if b.size == 0:
        return np.zeros(0, dtype=np.int64)
    is_last = (b & 0x80) == 0
    if not is_last[-1]:
        raise ValueError("truncated varint stream (trailing continuation bit)")
    ends = np.nonzero(is_last)[0]
    starts = np.empty(ends.size, dtype=np.int64)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lengths = ends - starts + 1
    maxlen = int(lengths.max())
    if maxlen > _MAX_BYTES:
        raise ValueError("varint longer than 10 bytes")
    if maxlen < _MAX_BYTES:
        padded = np.zeros(b.size + maxlen, dtype=np.uint8)
        padded[:b.size] = b
        u = np.zeros(ends.size, dtype=np.uint64)
        for j in range(maxlen):
            byte = padded[starts + j].astype(np.uint64) & np.uint64(0x7F)
            u |= np.where(j < lengths, byte, np.uint64(0)) << np.uint64(7 * j)
        return ((u >> np.uint64(1)).astype(np.int64)) ^ -(u & np.uint64(1)).astype(np.int64)
    # 10-byte lane: a 10th byte may only carry the single remaining bit of
    # a u64; anything larger would silently wrap out of the 64-bit
    # accumulator. Group sums via wrap-exact cumsum differences.
    ten_byte_finals = b[ends[lengths == _MAX_BYTES]]
    if ten_byte_finals.size and ten_byte_finals.max() > 1:
        raise ValueError("varint overflows 64 bits")
    pos = np.arange(b.size, dtype=np.uint64) - np.repeat(
        starts.astype(np.uint64), lengths
    )
    contrib = (b & np.uint8(0x7F)).astype(np.uint64) << (np.uint64(7) * pos)
    cumulative = np.cumsum(contrib, dtype=np.uint64)
    u = cumulative[ends].copy()
    u[1:] -= cumulative[ends[:-1]]
    return ((u >> np.uint64(1)).astype(np.int64)) ^ -(u & np.uint64(1)).astype(np.int64)
