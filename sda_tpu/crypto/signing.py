"""Ed25519 signing of protocol resources over canonical JSON.

Reference: client/src/crypto/signing/mod.rs — keys are generated into the
keystore; `sign_export` signs a Labelled encryption key with the agent's
signing key; `signature_is_valid` verifies any Signed<M> against the agent's
verification key, binding the claimed signer to the agent id (:106-132).
"""

from __future__ import annotations

from typing import Optional

from ..protocol import (
    Agent,
    B32,
    B64,
    EncryptionKeyId,
    Labelled,
    Signature,
    Signed,
    SigningKey,
    VerificationKey,
    VerificationKeyId,
    canonical_json,
)
from . import sodium
from .core import Keystore, SignatureKeypair


def new_signature_keypair() -> SignatureKeypair:
    vk, sk = sodium.sign_keypair()
    return SignatureKeypair(
        vk=VerificationKey("Sodium", B32(vk)),
        sk=SigningKey("Sodium", B64(sk)),
    )


def new_labelled_verification_key(keystore: Keystore) -> Labelled:
    """Generate + store a signature keypair; return the public half labelled
    by its fresh id (signing/mod.rs:46-60)."""
    keypair = new_signature_keypair()
    key_id = VerificationKeyId.random()
    keystore.put_signature_keypair(key_id, keypair)
    return Labelled(key_id, keypair.vk)


def sign_export(
    agent: Agent, key_id: EncryptionKeyId, keystore: Keystore
) -> Optional[Signed]:
    """Sign the agent's stored encryption key for upload (signing/mod.rs:72-103)."""
    enc_keypair = keystore.get_encryption_keypair(key_id)
    if enc_keypair is None:
        return None
    message = Labelled(key_id, enc_keypair.ek)
    sig_keypair = keystore.get_signature_keypair(agent.verification_key.id)
    if sig_keypair is None:
        return None
    raw_sig = sodium.sign_detached(message.canonical(), sig_keypair.sk.value.data)
    return Signed(
        signature=Signature("Sodium", B64(raw_sig)),
        signer=agent.id,
        body=message,
    )


def signature_is_valid(agent: Agent, signed: Signed) -> bool:
    """Verify a Signed<M> against the agent's verification key.

    Raises ValueError if the claimed signer is a different agent
    (signing/mod.rs:113-116).
    """
    if signed.signer != agent.id:
        raise ValueError("agent differs from claimed signer")
    vk = agent.verification_key.body
    sig = signed.signature
    if vk.variant != "Sodium" or sig.variant != "Sodium":
        raise ValueError("unsupported signature scheme")
    message = canonical_json(signed.body.to_obj())
    return sodium.verify_detached(sig.value.data, message, vk.value.data)
