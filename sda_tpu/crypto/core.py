"""Crypto module core: keypairs, keystore interface, RNG seeds.

Mirrors the reference's CryptoModule/Keystore plumbing
(client/src/crypto/mod.rs:33-66): type aliases Secret=Mask=Share=i64 become
int64 numpy/jnp arrays; the keystore stores encryption and signature
keypairs by id and is shared between the crypto module and the client store.
"""

from __future__ import annotations

import abc
import secrets as _secrets
from typing import Optional

import jax

from ..protocol import (
    B32,
    B64,
    Binary,
    EncryptionKey,
    EncryptionKeyId,
    SigningKey,
    VerificationKey,
    VerificationKeyId,
)


class DecryptionKey:
    """Secret half of an encryption keypair: 32-byte Curve25519 for
    ``Sodium``, a length-framed (p, q) factorisation for ``PackedPaillier``."""

    __slots__ = ("variant", "value")

    _PAYLOADS = {"Sodium": B32, "PackedPaillier": Binary}

    def __init__(self, variant: str, value):
        if variant not in self._PAYLOADS:
            raise ValueError(f"unknown decryption key variant {variant!r}")
        self.variant = variant
        self.value = value

    def to_obj(self):
        return {self.variant: self.value.to_obj()}

    @classmethod
    def from_obj(cls, obj):
        [(variant, payload)] = obj.items()
        if variant not in cls._PAYLOADS:
            raise ValueError(f"unknown decryption key variant {variant!r}")
        return cls(variant, cls._PAYLOADS[variant].from_obj(payload))


class EncryptionKeypair:
    """Public + secret encryption key (encryption/mod.rs:12-17)."""

    __slots__ = ("ek", "dk")

    def __init__(self, ek: EncryptionKey, dk: DecryptionKey):
        self.ek = ek
        self.dk = dk

    def to_obj(self):
        return {"ek": self.ek.to_obj(), "dk": self.dk.to_obj()}

    @classmethod
    def from_obj(cls, obj):
        return cls(EncryptionKey.from_obj(obj["ek"]), DecryptionKey.from_obj(obj["dk"]))


class SignatureKeypair:
    """Verification + signing key (signing/mod.rs:20-25)."""

    __slots__ = ("vk", "sk")

    def __init__(self, vk: VerificationKey, sk: SigningKey):
        self.vk = vk
        self.sk = sk

    def to_obj(self):
        return {"vk": self.vk.to_obj(), "sk": self.sk.to_obj()}

    @classmethod
    def from_obj(cls, obj):
        return cls(VerificationKey.from_obj(obj["vk"]), SigningKey.from_obj(obj["sk"]))


class Keystore(abc.ABC):
    """Typed keypair storage (client/src/crypto/mod.rs:43-52).

    Implementations: in-memory (tests), file-based (sda_tpu.store.Filebased).
    """

    @abc.abstractmethod
    def put_encryption_keypair(self, id: EncryptionKeyId, kp: EncryptionKeypair) -> None: ...

    @abc.abstractmethod
    def get_encryption_keypair(self, id: EncryptionKeyId) -> Optional[EncryptionKeypair]: ...

    @abc.abstractmethod
    def put_signature_keypair(self, id: VerificationKeyId, kp: SignatureKeypair) -> None: ...

    @abc.abstractmethod
    def get_signature_keypair(self, id: VerificationKeyId) -> Optional[SignatureKeypair]: ...


class MemoryKeystore(Keystore):
    def __init__(self):
        self._enc = {}
        self._sig = {}

    def put_encryption_keypair(self, id, kp):
        self._enc[id] = kp

    def get_encryption_keypair(self, id):
        return self._enc.get(id)

    def put_signature_keypair(self, id, kp):
        self._sig[id] = kp

    def get_signature_keypair(self, id):
        return self._sig.get(id)


def fresh_prng_key() -> jax.Array:
    """Threefry key seeded from OS entropy — the device-side randomness root.

    Replaces the reference's per-call OsRng (additive.rs:17, full.rs:16):
    bulk share/mask randomness is generated on-device from a 63-bit
    OS-entropy seed per operation (PRNGKey takes a signed int64).
    """
    seed = int.from_bytes(_secrets.token_bytes(8), "little") & ((1 << 63) - 1)
    return jax.random.PRNGKey(seed)
