"""Bounded crypto worker pool — host-side parallelism for sealed boxes.

libsodium calls go through ctypes, which releases the GIL for the duration
of the C call, so a small thread pool turns the client's per-item
encrypt/decrypt loops into genuinely parallel work on a multicore host.
The pool is shared, lazily created, and bounded (``SDA_CRYPTO_WORKERS``,
default ``min(8, cpu_count)``) so a process full of clients cannot fork an
unbounded thread army; ``SDA_CRYPTO_WORKERS=1`` (or ``0``) disables
threading entirely and every helper degrades to the plain sequential loop
— bit-identical results either way, the pool is a latency optimization,
never a correctness dependency.

``pmap`` is the order-preserving parallel map; ``prefetch_map`` is the
double-buffered pipeline primitive the clerk hot path uses: it yields
batch results in order while keeping the NEXT batch's items in flight on
the pool, so host crypto overlaps the consumer's (device) work without
ever staging more than ``prefetch + 1`` batches of decrypted material.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from typing import Callable, Iterator, List, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

_lock = threading.Lock()
_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
_pool_workers = 0


def worker_count() -> int:
    """Configured pool width; <=1 means sequential."""
    raw = os.environ.get("SDA_CRYPTO_WORKERS")
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return min(8, os.cpu_count() or 1)


def _get_pool(workers: int) -> concurrent.futures.ThreadPoolExecutor:
    global _pool, _pool_workers
    with _lock:
        if _pool is None or _pool_workers != workers:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="sda-crypto"
            )
            _pool_workers = workers
        return _pool


def reset() -> None:
    """Tear the shared pool down (tests; safe to call anytime)."""
    global _pool, _pool_workers
    with _lock:
        if _pool is not None:
            _pool.shutdown(wait=False)
        _pool, _pool_workers = None, 0


class _Now:
    """Pre-resolved future look-alike for the sequential fallback."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


def submit(fn: Callable[[], R]):
    """Run ``fn`` on the pool, returning a ``.result()``-able handle —
    the single-task overlap primitive (e.g. hiding a metadata fetch
    behind the decrypt pipeline). Sequential fallback runs ``fn``
    immediately, preserving call order and fail-fast semantics."""
    if worker_count() <= 1:
        return _Now(fn())
    return _get_pool(worker_count()).submit(fn)


def pmap(fn: Callable[[T], R], items: Sequence[T]) -> List[R]:
    """Order-preserving parallel map over the shared pool.

    Falls back to a plain loop when the pool is disabled or the input is
    too small to amortize the dispatch. The first worker exception
    propagates (remaining futures are cancelled best-effort), matching
    the sequential loop's fail-fast semantics.
    """
    items = list(items)
    workers = worker_count()
    if workers <= 1 or len(items) <= 1:
        return [fn(item) for item in items]
    pool = _get_pool(workers)
    futures = [pool.submit(fn, item) for item in items]
    try:
        return [f.result() for f in futures]
    except BaseException:
        for f in futures:
            f.cancel()
        raise


def prefetch_map(
    fn: Callable[[T], R],
    items: Sequence[T],
    batch_size: int,
    prefetch: int = 1,
) -> Iterator[List[R]]:
    """Yield ``fn``-mapped batches in order, keeping up to ``prefetch``
    later batches' items in flight while the caller consumes the current
    one — the decrypt/combine overlap of the clerk pipeline. Bounded
    staging: at most ``(prefetch + 1) * batch_size`` results exist at
    once. Sequential (zero threads, zero staging beyond one batch) when
    the pool is disabled.
    """
    items = list(items)
    batch_size = max(1, int(batch_size))
    workers = worker_count()
    if workers <= 1:
        for lo in range(0, len(items), batch_size):
            yield [fn(item) for item in items[lo:lo + batch_size]]
        return
    pool = _get_pool(workers)
    pending: List[concurrent.futures.Future] = []
    next_item = 0

    def fill(upto: int) -> None:
        nonlocal next_item
        upto = min(upto, len(items))
        while next_item < upto:
            pending.append(pool.submit(fn, items[next_item]))
            next_item += 1

    lo = 0
    try:
        while lo < len(items):
            hi = min(lo + batch_size, len(items))
            fill(hi + prefetch * batch_size)
            batch = [pending.pop(0).result() for _ in range(hi - lo)]
            yield batch
            lo = hi
    except BaseException:
        for f in pending:
            f.cancel()
        raise
