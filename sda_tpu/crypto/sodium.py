"""ctypes bindings to libsodium — the host-side curve crypto.

The reference reaches libsodium (C) through the sodiumoxide Rust crate
(client/src/crypto/encryption/sodium.rs, signing/mod.rs); here we bind the
same primitives directly: sealed boxes (Curve25519+XSalsa20+Poly1305,
anonymous sender) for share transport, Ed25519 detached signatures for
resource signing. Curve crypto stays on the CPU host — only bulk vector
algebra goes to the TPU.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional, Tuple

_SONAMES = ["libsodium.so.23", "libsodium.so", "libsodium.so.26", "libsodium.so.18"]

_lib: Optional[ctypes.CDLL] = None


class SodiumUnavailable(RuntimeError):
    pass


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    last = None
    names = list(_SONAMES)
    found = ctypes.util.find_library("sodium")
    if found:
        names.insert(0, found)
    for name in names:
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError as e:
            last = e
    else:
        raise SodiumUnavailable(f"libsodium not found: {last}")
    if lib.sodium_init() < 0:
        raise SodiumUnavailable("sodium_init failed")
    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except SodiumUnavailable:
        return False


SEAL_OVERHEAD = 48  # crypto_box_SEALBYTES: 32 ephemeral pk + 16 MAC
BOX_PK = 32
BOX_SK = 32
SIGN_PK = 32
SIGN_SK = 64
SIGN_BYTES = 64


def box_keypair() -> Tuple[bytes, bytes]:
    """Curve25519 (pk, sk) for sealed boxes (sodium.rs:95-109 keygen)."""
    lib = _load()
    pk = ctypes.create_string_buffer(BOX_PK)
    sk = ctypes.create_string_buffer(BOX_SK)
    if lib.crypto_box_keypair(pk, sk) != 0:
        raise RuntimeError("crypto_box_keypair failed")
    return pk.raw, sk.raw


def seal(message: bytes, pk: bytes) -> bytes:
    """Anonymous-sender sealed box (sodium.rs:42-45 encrypt path)."""
    lib = _load()
    out = ctypes.create_string_buffer(len(message) + SEAL_OVERHEAD)
    if lib.crypto_box_seal(out, message, ctypes.c_ulonglong(len(message)), pk) != 0:
        raise RuntimeError("crypto_box_seal failed")
    return out.raw


def seal_open(ciphertext: bytes, pk: bytes, sk: bytes) -> bytes:
    """Open a sealed box; raises ValueError on authentication failure
    (sodium.rs:78-82 decrypt path)."""
    lib = _load()
    if len(ciphertext) < SEAL_OVERHEAD:
        raise ValueError("ciphertext shorter than sealed-box overhead")
    out = ctypes.create_string_buffer(len(ciphertext) - SEAL_OVERHEAD)
    rc = lib.crypto_box_seal_open(
        out, ciphertext, ctypes.c_ulonglong(len(ciphertext)), pk, sk
    )
    if rc != 0:
        raise ValueError("sealed box decryption failure")
    return out.raw


def sign_keypair() -> Tuple[bytes, bytes]:
    """Ed25519 (vk 32B, sk 64B) (signing/mod.rs:28-41 keygen)."""
    lib = _load()
    pk = ctypes.create_string_buffer(SIGN_PK)
    sk = ctypes.create_string_buffer(SIGN_SK)
    if lib.crypto_sign_keypair(pk, sk) != 0:
        raise RuntimeError("crypto_sign_keypair failed")
    return pk.raw, sk.raw


def sign_detached(message: bytes, sk: bytes) -> bytes:
    """Detached Ed25519 signature (signing/mod.rs:95-99)."""
    lib = _load()
    sig = ctypes.create_string_buffer(SIGN_BYTES)
    siglen = ctypes.c_ulonglong(0)
    if lib.crypto_sign_detached(
        sig, ctypes.byref(siglen), message, ctypes.c_ulonglong(len(message)), sk
    ) != 0:
        raise RuntimeError("crypto_sign_detached failed")
    return sig.raw


def verify_detached(sig: bytes, message: bytes, pk: bytes) -> bool:
    """True iff the detached signature verifies (signing/mod.rs:119-130)."""
    lib = _load()
    rc = lib.crypto_sign_verify_detached(
        sig, message, ctypes.c_ulonglong(len(message)), pk
    )
    return rc == 0
