"""Model-scale device plane: pjit-sharded, HBM-streamed, Pallas-fused
rounds at FL-model dimension (dim >= 1e8).

SDA's original use case is aggregating locally trained ML models, yet
until this module the full mask -> share -> combine -> reconstruct round
at model dimension was never one benched configuration — the parts
existed (``fields/pallas_round`` fused kernel, ``fields/dimtile`` tile
scan, ``mesh/simpod`` shard stages, ``mesh/streaming`` block providers,
devprof HBM watermarks and roofline) but nothing composed them. Three
pieces close that gap:

- **The watermark tile rule** (:func:`watermark_dim_tile`): the dim-tile
  width is DERIVED from the devprof per-device HBM watermark
  (``obs.devprof.hbm_watermark``) and an explicit per-column byte model
  of the sharded round stage — not a magic chunk constant. Peak HBM
  stays under the watermark at any dimension by construction; every
  devscale record reports ``hbm_peak_bytes / watermark``.

- **The sharded scan round** (:class:`ModelScaleRound`): ONE jitted
  ``shard_map`` program over the ``('p', 'd')`` mesh whose per-device
  body streams its local dim shard through the
  :func:`~sda_tpu.fields.dimtile.scan_dim_tiles` schedule — per tile:
  mask + share + local combine (the fused Pallas kernel when active,
  dispatched per shard with per-(seed, shard, tile) PRNG keys), one
  ``psum_scatter`` clerk transpose, reconstruct, unmask. Peak live
  memory per device is one tile's intermediates, so the program holds
  the watermark even when the full-width round would not. Bit-exact vs
  the XLA lane and the host oracle for any keys — masks cancel within
  each tile and random polynomial rows are annihilated by
  reconstruction.

- **The host->device sink** (:class:`DeviceTileSink`,
  :class:`DeviceTileCombiner`): the clerk decrypt pipeline
  (``crypto/batch.prefetch_map``) lands decoded ``[B, tile]`` share
  bundles directly as device-resident tiles — decode runs on the
  bounded crypto pool while the PREVIOUS tile's host->HBM transfer and
  device fold are in flight (double buffering), so the streamed drivers
  consume device arrays instead of host arrays. ``DeviceTileCombiner``
  is the clerk-side consumer (``SDA_CLERK_DEVICE_TILES=1``), bit-exact
  with ``crypto.sharing.mod_combine``.

The benched configuration itself (profile, record, regression tags)
lives in ``loadgen/devscale.py`` behind ``sda-sim --devscale``;
docs/performance.md "Model scale" has the contract.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ..fields.dimtile import scan_dim_tiles, tile_plan
from ..fields.ops import FieldOps
from ..obs import devprof
from ..utils import metrics, timed_phase
from .simpod import (
    _build_matrices,
    _check_collective_headroom,
    _check_mask_modulus,
    _check_masking_supported,
    _dim_grain,
    _mask_stage,
    _normalize_survivors,
    _pallas_stage,
    _reconstruct_stage,
    _resolve_pallas,
    _scheme_modulus,
    _shard_map,
    _share_sum_stage,
    _tile_key,
    default_mesh_shape,
    make_mesh,
)

__all__ = [
    "DeviceTileCombiner",
    "DeviceTileSink",
    "ModelScaleRound",
    "bytes_per_dim_column",
    "stream_schedule",
    "watermark_dim_tile",
]


# ---------------------------------------------------------------------------
# The watermark tile-width rule


def bytes_per_dim_column(scheme, masking, local_rows: int,
                         pallas: bool = False) -> int:
    """Conservative per-device HBM bytes one LOCAL dim column costs the
    sharded round stage — the denominator of the watermark tile rule.

    The model counts every live uint32 lane of the per-tile stage body
    (S = participant rows resident on this device, k/t/n/m2/r from the
    scheme; 4 bytes per lane):

    - input block + residue copy, double-buffered against the next
      tile's host->HBM landing: ``3 * S``
    - full-mask draws ``[S, d]``: ``S`` (the Pallas kernel draws
      on-core, but the XLA lane's bound is kept — the rule must hold
      for whichever lane dispatches);
    - share randomness ``[S, t, B]``: ``S * t / k``;
    - matmul operands+result ``[m2, B] + [n, B]``: ``(m2 + n) / k``;
    - accumulators / clerk rows / reconstruct output:
      ``(2n + r) / k + 2``.

    A 25% allocator-slack factor tops it off. The point is not byte
    accuracy — it is that the tile width SCALES from the watermark and
    the scheme instead of being a constant someone measured once.
    """
    k = int(getattr(scheme, "secret_count", 1) or 1)
    t = int(getattr(scheme, "privacy_threshold", 0) or 0)
    n = int(scheme.output_size)
    m2 = 1 + k + t
    r = int(getattr(scheme, "reconstruction_threshold", n) or n)
    S = max(1, int(local_rows))
    from ..protocol import NoMasking

    mask_rows = 0 if isinstance(masking, (NoMasking, type(None))) else 1
    lanes = (
        3 * S                      # block + residues, double-buffered
        + mask_rows * S            # mask draws
        + S * t / k                # share randomness
        + (m2 + n) / k             # matmul operands + result
        + (2 * n + r) / k + 2      # accs + gathered rows + output
    )
    del pallas  # the XLA bound covers the fused kernel too
    return max(16, int(math.ceil(lanes * 4 * 1.25)))


def watermark_dim_tile(
    scheme,
    masking=None,
    *,
    participants_chunk: int,
    p_shards: int,
    d_shards: int,
    pallas: bool = False,
    watermark_bytes: Optional[int] = None,
    dim: Optional[int] = None,
) -> int:
    """The GLOBAL dim-tile width the HBM watermark affords.

    ``watermark // bytes_per_dim_column`` local columns fit one device;
    times ``d_shards`` for the global width, rounded DOWN to the
    mesh/scheme grain (whole packing columns x whole ChaCha blocks x
    d_shards — a tile must be a complete round over its own columns on
    every shard). Clamped to at least one grain and, when ``dim`` is
    given, to the grain-rounded dimension (no tile wider than the
    workload). ``watermark_bytes=None`` reads the live
    :func:`~sda_tpu.obs.devprof.hbm_watermark`.
    """
    from ..protocol import NoMasking

    masking = masking if masking is not None else NoMasking()
    budget = int(watermark_bytes if watermark_bytes is not None
                 else devprof.hbm_watermark())
    # whole packing columns x whole ChaCha blocks, like the scan lane
    grain_loc = math.lcm(_dim_grain(scheme, masking), 8)
    grain = grain_loc * int(d_shards)
    local_rows = -(-int(participants_chunk) // int(p_shards))
    per_col = bytes_per_dim_column(scheme, masking, local_rows, pallas)
    cols_loc = max(grain_loc, budget // per_col)
    tile = max(grain, (cols_loc * int(d_shards)) // grain * grain)
    if dim is not None:
        tile = min(tile, -(-int(dim) // grain) * grain)
    return tile


# ---------------------------------------------------------------------------
# The sharded scan round: one program, tiles streamed inside it


class ModelScaleRound:
    """One jitted shard_map round whose per-device body scans dim tiles.

    The pjit x scan x Pallas composition: the ``[P, dim]`` combine is
    sharded over the ``('p', 'd')`` mesh, each device streams its local
    dim shard through :func:`scan_dim_tiles` at the watermark-derived
    tile width, and the per-tile mask+share+combine runs the fused
    Pallas kernel when active (per-(seed, shard, tile) PRNG keys via
    ``_tile_key`` / the scan's per-tile ``fold_in``). Collectives run
    per tile inside the scan: one ``psum_scatter`` clerk transpose over
    ``'p'``, one ``all_gather``, one mask ``psum``.

    Use this lane when the sharded INPUT fits device memory (the tile
    schedule bounds every intermediate); for inputs larger than memory
    compose :class:`~sda_tpu.mesh.streaming.StreamedPod` with the same
    watermark tile width instead (loadgen/devscale.py drives both).
    """

    def __init__(
        self,
        sharing_scheme,
        masking_scheme=None,
        mesh=None,
        dim_tile: Optional[int] = None,
        use_pallas: Optional[bool] = None,
        pallas_interpret: bool = False,
        pallas_external_bits_fn=None,
        surviving_clerks=None,
        participants_chunk: int = 8,
    ):
        import jax

        from ..protocol import NoMasking

        self.scheme = s = sharing_scheme
        self.modulus = _scheme_modulus(s)
        self.masking = masking_scheme or NoMasking()
        _check_masking_supported(self.masking)
        _check_mask_modulus(self.masking, s)
        if mesh is None:
            p_shards, d_shards = default_mesh_shape(
                len(jax.devices()), s.output_size)
            mesh = make_mesh(p_shards, d_shards)
        self.mesh = mesh
        p_shards, d_shards = mesh.devices.shape
        if s.output_size % p_shards:
            raise ValueError(
                f"committee size {s.output_size} must be divisible by the "
                f"p axis ({p_shards})")
        self.surviving_clerks = _normalize_survivors(s, surviving_clerks)
        self._M_host, self._L_host = _build_matrices(s, self.surviving_clerks)
        self._field = FieldOps.create(self.modulus, cross_terms=p_shards)
        _check_collective_headroom(self._field, p_shards)
        self.pallas_active = _resolve_pallas(
            s, self.masking, self._field, use_pallas, "model-scale")
        self._pallas_interpret = bool(pallas_interpret)
        self._pallas_bits_fn = pallas_external_bits_fn
        # tile grain: whole packing columns AND whole ChaCha blocks (the
        # per-tile d_block0 window arithmetic needs 8-aligned widths),
        # same rule as mesh.single_chip_round's tiled schedule
        self._grain_loc = math.lcm(_dim_grain(s, self.masking), 8)
        self._grain = self._grain_loc * d_shards
        if dim_tile is None:
            dim_tile = watermark_dim_tile(
                s, self.masking, participants_chunk=participants_chunk,
                p_shards=p_shards, d_shards=d_shards,
                pallas=self.pallas_active)
        # the per-DEVICE scan width; the global tile is d_shards of these
        self.dim_tile = max(self._grain,
                            int(dim_tile) // self._grain * self._grain)
        self._tile_loc = self.dim_tile // d_shards
        self._step = None
        self._step_shape = None

    @property
    def _sp(self):
        return self._field.sp

    def _local_round(self, inputs, key):
        """Per-device body: scan the local [P_loc, d_loc] shard in tiles."""
        import jax
        import jax.numpy as jnp

        f, s, masking = self._field, self.scheme, self.masking
        P_loc, d_loc = inputs.shape
        pi = jax.lax.axis_index("p")
        di = jax.lax.axis_index("d")

        def one_tile(blk, round_key, tile_key, i, width):
            # per-(seed, shard, tile) randomness: scan_dim_tiles folded
            # the tile index into tile_key; _tile_key separates shards
            dev_key = _tile_key(tile_key, pi, di)
            # global stream coordinates of this tile (ChaCha windows)
            d_block0 = (di * d_loc + i * width) // 8
            x = f.to_residues(blk)
            if self.pallas_active:
                shares, mask_sum = _pallas_stage(
                    s, f, self._M_host, masking, x, dev_key,
                    round_key=round_key, pid_base=pi * P_loc,
                    d_block0=d_block0,
                    interpret=self._pallas_interpret,
                    external_bits_fn=self._pallas_bits_fn,
                )
            else:
                masked, mask_sum, skey = _mask_stage(
                    masking, f, x, dev_key, round_key,
                    pid_base=pi * P_loc, d_block0=d_block0,
                )
                shares = _share_sum_stage(s, f, self._M_host, masked, skey)
            with jax.named_scope("sda.clerk_combine"):
                rows = jax.lax.psum_scatter(
                    shares, "p", scatter_dimension=0, tiled=True)
                rows = f.canon(rows)
                gathered = jax.lax.all_gather(rows, "p", axis=0, tiled=True)
            if self.surviving_clerks is not None:
                gathered = gathered[jnp.asarray(self.surviving_clerks), :]
            total = _reconstruct_stage(s, f, self._L_host, gathered, width)
            with jax.named_scope("sda.unmask"):
                if mask_sum is None:
                    return f.to_int64(total)
                mask_total = f.canon(jax.lax.psum(mask_sum, "p"))
                return f.to_int64(f.sub(total, mask_total))

        return scan_dim_tiles(one_tile, self._grain_loc, self._tile_loc)(
            inputs, key)

    def _build(self, P_pad: int, d_pad: int):
        import jax
        from jax.sharding import PartitionSpec as P

        fn = _shard_map(
            self._local_round, mesh=self.mesh,
            in_specs=(P("p", "d"), P()), out_specs=P("d"))
        # ONE devprof stage for the whole sharded scan round: repeated
        # same-shape rounds must register a single compiled shape, and a
        # dim change re-tiles via the scan length without touching the
        # per-tile body (tests/test_devprof.py model-scale tripwire)
        return devprof.instrument("devscale.round", jax.jit(fn))

    def padded_shape(self, P_total: int, d_total: int) -> Tuple[int, int]:
        p_shards, _ = self.mesh.devices.shape
        return (
            -(-P_total // p_shards) * p_shards,
            -(-d_total // self._grain) * self._grain,
        )

    def _get_step(self, P_pad: int, d_pad: int):
        shape = (P_pad, d_pad)
        if self._step is None or self._step_shape != shape:
            self._step = self._build(*shape)
            self._step_shape = shape
        return self._step

    def aggregate(self, inputs, key=None):
        """[P, d] participant inputs -> [d] aggregate (one full round)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        inputs = np.asarray(inputs)
        if key is None:
            from ..crypto.core import fresh_prng_key

            key = fresh_prng_key()
        P_total, d_total = inputs.shape
        P_pad, d_pad = self.padded_shape(P_total, d_total)
        if (P_pad, d_pad) != (P_total, d_total):
            # zero rows/columns aggregate as zero; masks on the padding
            # cancel like any other mask; stripped below
            padded = np.zeros((P_pad, d_pad), dtype=inputs.dtype)
            padded[:P_total, :d_total] = inputs
            inputs = padded
        step = self._get_step(P_pad, d_pad)
        sharding = NamedSharding(self.mesh, P("p", "d"))
        with timed_phase("devscale.round"):
            device_inputs = jax.device_put(jnp.asarray(inputs), sharding)
            out = step(device_inputs, key)
            out.block_until_ready()
        return out[:d_total]


# ---------------------------------------------------------------------------
# Host -> device sink: the clerk pipeline lands device-resident tiles


def stream_schedule(participants: int, dimension: int, pc: int, dc: int,
                    grain: int, uniform_tail: bool = True):
    """The (p0, p1, d0, d1, d_size) block sequence the streamed drivers
    request, in drive order (d-tiles outer, participant tiles inner) —
    mirrors ``mesh.streaming._drive_stream`` so a prefetching sink can
    stay one block ahead of the consumer. The sink VERIFIES each request
    against this prediction and falls back to direct decode on any
    mismatch, so a schedule drift degrades to synchronous, never to
    wrong data."""
    uniform_d = uniform_tail and dimension > dc
    out = []
    for d0 in range(0, dimension, dc):
        d1 = min(d0 + dc, dimension)
        d_size = dc if uniform_d else -(-(d1 - d0) // grain) * grain
        for p0 in range(0, participants, pc):
            out.append((p0, min(p0 + pc, participants), d0, d1, d_size))
    return out


class DeviceTileSink:
    """Double-buffered host->HBM landing of decoded share tiles.

    ``decode(p0, p1, d0, d1) -> [rows, cols] host array`` is the clerk
    pipeline's product (a decoded share bundle — in the benched drill, a
    host-side block generator standing in for the decrypt stage). The
    sink runs decode on the bounded crypto pool
    (``crypto.batch.submit``), pads the block to the uniform step shape,
    and lands it on the mesh with ``jax.device_put`` — keeping
    ``prefetch`` future blocks in flight while the consumer combines the
    current one, so host decode/decrypt overlaps the host->HBM transfer
    and the device fold. ``provider()`` adapts the sink to the streamed
    drivers' ``BlockProvider`` seam: the drivers see device-resident
    tiles, never host arrays.
    """

    def __init__(self, decode, participants: int, dimension: int,
                 participants_chunk: int, dim_chunk: int, *,
                 grain: int = 1, uniform_tail: bool = True,
                 sharding=None, dtype=None, prefetch: int = 1):
        from ..crypto import batch as crypto_batch

        self._decode = decode
        self._sharding = sharding
        self._dtype = dtype
        self._batch = crypto_batch
        self._prefetch = max(0, int(prefetch))
        self._schedule = stream_schedule(
            participants, dimension, participants_chunk, dim_chunk,
            grain, uniform_tail)
        self._pc = int(participants_chunk)
        self._next = 0       # next schedule index to launch
        self._queue = []     # [(coords, handle)] in flight, oldest first
        self._fill()

    def _fill(self) -> None:
        while (self._next < len(self._schedule)
               and len(self._queue) < self._prefetch + 1):
            coords = self._schedule[self._next]
            self._queue.append((coords, self._land(coords)))
            self._next += 1

    def _land(self, coords):
        p0, p1, d0, d1, d_size = coords

        def job():
            import jax
            import jax.numpy as jnp

            host = np.asarray(self._decode(p0, p1, d0, d1))
            if self._dtype is not None:
                host = host.astype(self._dtype, copy=False)
            if host.shape != (self._pc, d_size):
                padded = np.zeros((self._pc, d_size), dtype=host.dtype)
                padded[: host.shape[0], : host.shape[1]] = host
                host = padded
            arr = jnp.asarray(host)
            if self._sharding is not None:
                arr = jax.device_put(arr, self._sharding)
            return arr

        return self._batch.submit(job)

    def provider(self):
        """A ``BlockProvider`` serving device-resident tiles in stream
        order (prefetched); out-of-order requests decode synchronously."""

        def get_block(p0, p1, d0, d1):
            if self._queue and self._queue[0][0][:4] == (p0, p1, d0, d1):
                _, handle = self._queue.pop(0)
                self._fill()  # keep the pipeline primed
                metrics.count("devscale.sink.hit")
                return handle.result()
            # drift between consumer and predicted schedule: stay correct
            metrics.count("devscale.sink.miss")
            return np.asarray(self._decode(p0, p1, d0, d1))

        return get_block


class DeviceTileCombiner:
    """Device-resident clerk combine: fold decoded share bundles into a
    tiled device accumulator, bit-exact with
    ``crypto.sharing.mod_combine``.

    The clerk hot path's per-bundle ``[B, dim]`` fold runs as uniform
    ``[B, tile]`` device tiles (width from the HBM watermark unless
    given): each tile is ``device_put`` while the PREVIOUS tile folds,
    so the host->HBM transfer overlaps the device adds, and the decrypt
    pipeline (``prefetch_map``) overlaps both. One compiled fold shape
    per (rows, tile) — repeated bundles never retrace. Enabled on the
    clerk via ``SDA_CLERK_DEVICE_TILES=1``
    (``client.process_clerking_job``).
    """

    def __init__(self, modulus: int, dim_tile: Optional[int] = None):
        self._f = FieldOps.create(int(modulus))
        self._dim_tile = None if dim_tile is None else max(128, int(dim_tile))
        self._tiles = None     # list of per-tile device accumulators
        self._dim = None
        self._folds = 0
        self._step = None

    def _plan(self, rows: int, dim: int):
        import jax.numpy as jnp

        if self._dim_tile is None:
            # watermark rule, combiner flavor: the live set per tile is
            # the [rows, tile] bundle (double-buffered), its residue
            # copy, and the accumulator — ~ (2*rows + 2) uint32/int64
            # lanes per column, 25% slack
            lane = 4 if self._f.sp is not None else 8
            per_col = int((2 * rows + 2) * lane * 1.25)
            self._dim_tile = max(128, devprof.hbm_watermark() // per_col)
        plan = tile_plan(dim, 1, self._dim_tile)
        self._dim = dim
        self._plan_t = plan
        self._tiles = [jnp.zeros((plan.width,), self._f.dtype)
                       for _ in range(plan.n_tiles)]

    def _fold_step(self):
        import jax

        if self._step is None:
            f = self._f

            def step(acc, blk):
                return f.add(acc, f.sum(f.to_residues(blk), axis=0))

            self._step = devprof.instrument(
                "devscale.clerk_combine", jax.jit(step))
        return self._step

    def fold(self, share_rows) -> None:
        """Fold one decoded bundle (``[B, dim]`` array or sequence of
        ``[dim]`` vectors) into the device accumulator."""
        import jax.numpy as jnp

        stacked = np.asarray(share_rows, dtype=np.int64)
        if stacked.ndim == 1:
            stacked = stacked[None, :]
        if self._tiles is None:
            self._plan(stacked.shape[0], stacked.shape[1])
        if stacked.shape[1] != self._dim:
            raise ValueError(
                f"bundle dim {stacked.shape[1]} != combiner dim {self._dim}")
        plan = self._plan_t
        if plan.pad:
            stacked = np.pad(stacked, ((0, 0), (0, plan.pad)))
        step = self._fold_step()
        # land tile j+1 while tile j folds: transfer overlaps compute
        pending = jnp.asarray(stacked[:, : plan.width])
        for j in range(plan.n_tiles):
            current = pending
            if j + 1 < plan.n_tiles:
                lo = (j + 1) * plan.width
                pending = jnp.asarray(stacked[:, lo: lo + plan.width])
            self._tiles[j] = step(self._tiles[j], current)
        self._folds += 1
        metrics.count("devscale.clerk_combine.bundles")

    @property
    def folded(self) -> int:
        return self._folds

    def result(self) -> np.ndarray:
        """The combined [dim] int64 vector (canonical residues)."""
        if self._tiles is None:
            return np.zeros(0, dtype=np.int64)
        f = self._f
        parts = [np.asarray(f.to_int64(t)) for t in self._tiles]
        return np.concatenate(parts)[: self._dim]
