"""Simulated-pod mode: the clerk committee on a TPU device mesh.

The TPU-native execution mode the reference cannot express: instead of
participants HTTP-POSTing encrypted shares to a broker that transposes them
into per-clerk jobs (server/src/snapshot.rs), the whole aggregation round
runs as ONE jitted SPMD program over a `jax.sharding.Mesh`, with XLA
collectives over ICI replacing every server round-trip.

Mesh axes and their protocol meaning (SURVEY.md §2.4 mapping):

- ``p`` — participant shards; the clerk committee also lives along this
  axis (clerk c's combined share lands on device c // (n/p_shards)).
- ``d`` — vector-dimension shards (the reference's analog of sequence/
  tensor parallelism: batching layer chunks, §5.7).

Dataflow per round, per (p, d) device:

1. mask + share the local [P/p, d/d'] participant block (threefry per
   participant, share matmul on the local dim chunk);
2. sum local participants' shares — participant parallelism is a *local*
   reduction;
3. ``psum_scatter`` over ``p`` splits the clerk axis while summing across
   participant shards — this one collective IS the snapshot transpose plus
   every clerk's combine, riding ICI instead of the broker;
4. ``all_gather`` over ``p`` hands the recipient all clerk rows; the
   reconstruct matmul and unmask run dim-sharded.

Trust model: this mode computes the same algebra with the same scheme
parameters but no transport encryption (devices of one pod trust each
other); the scheme enums already model pluggable encryption — the
federated HTTP mode keeps sealed boxes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fields import fastfield, modular, numtheory, sharing
from ..utils import timed_phase


def _to_residues32(inputs, sp: fastfield.SolinasPrime):
    """Any-integer inputs -> canonical uint32 residues mod p.

    uint32/int32 non-negative inputs skip the 64-bit pass entirely.
    """
    if inputs.dtype == jnp.uint32:
        return fastfield.canon32(inputs, sp)
    if inputs.dtype == jnp.int32:
        bits = inputs.astype(jnp.uint32)  # two's complement: negatives ≡ v + 2^32
        r = fastfield.canon32(bits, sp)
        r32 = jnp.uint32((1 << 32) % sp.p)
        return jnp.where(inputs < 0, fastfield.modsub32(r, r32, sp), r)
    return jnp.mod(inputs.astype(jnp.int64), sp.p).astype(jnp.uint32)
from ..protocol import (
    FullMasking,
    LinearMaskingScheme,
    NoMasking,
    PackedShamirSharing,
)


def _check_mask_modulus(masking, scheme) -> None:
    # the mask/unmask algebra only cancels when masking and sharing operate
    # in the same group
    if isinstance(masking, FullMasking) and masking.modulus != scheme.prime_modulus:
        raise ValueError(
            f"masking modulus {masking.modulus} != sharing prime "
            f"{scheme.prime_modulus}: masks would not cancel"
        )


def make_mesh(p_shards: int, d_shards: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = p_shards * d_shards
    if devices.size < n:
        raise ValueError(f"need {n} devices, have {devices.size}")
    return Mesh(devices.reshape(-1)[:n].reshape(p_shards, d_shards), ("p", "d"))


def default_mesh_shape(n_devices: int, share_count: int) -> Tuple[int, int]:
    """Largest p axis that divides both the device count and the committee."""
    p_shards = math.gcd(n_devices, share_count)
    return p_shards, n_devices // p_shards


class SimulatedPod:
    """One secure-aggregation round as a single SPMD program.

    Requires: committee size divisible by the ``p`` axis, participants
    divisible by ``p``, dimension divisible by ``secret_count * d_shards``
    (pad inputs to fit — zero participants/components aggregate as zero).
    """

    def __init__(
        self,
        sharing_scheme: PackedShamirSharing,
        masking_scheme: Optional[LinearMaskingScheme] = None,
        mesh: Optional[Mesh] = None,
    ):
        if not isinstance(sharing_scheme, PackedShamirSharing):
            raise ValueError("SimulatedPod currently runs Packed-Shamir rounds")
        self.scheme = sharing_scheme
        self.masking = masking_scheme or NoMasking()
        if not isinstance(self.masking, (NoMasking, FullMasking)):
            raise ValueError("simulated-pod masking: None or Full (seed PRGs are host-side)")
        _check_mask_modulus(self.masking, sharing_scheme)
        if mesh is None:
            p_shards, d_shards = default_mesh_shape(
                len(jax.devices()), sharing_scheme.share_count
            )
            mesh = make_mesh(p_shards, d_shards)
        self.mesh = mesh
        p_shards = mesh.devices.shape[0]
        if sharing_scheme.share_count % p_shards:
            raise ValueError(
                f"committee size {sharing_scheme.share_count} must be divisible "
                f"by the p axis ({p_shards})"
            )
        s = sharing_scheme
        self._M_host = numtheory.packed_share_matrix(
            s.secret_count, s.share_count, s.privacy_threshold,
            s.prime_modulus, s.omega_secrets, s.omega_shares,
        )
        self._L_host = numtheory.packed_reconstruct_matrix(
            s.secret_count, s.share_count, s.privacy_threshold,
            s.prime_modulus, s.omega_secrets, s.omega_shares,
            tuple(range(s.share_count)),
        )
        self._M = jnp.asarray(self._M_host)
        self._L = jnp.asarray(self._L_host)
        # uint32 fast path: Solinas prime AND cross-shard sums can't wrap u32
        sp = fastfield.SolinasPrime.try_from(s.prime_modulus)
        if sp is not None and p_shards * (s.prime_modulus - 1) >= (1 << 32):
            sp = None
        self._sp = sp
        self._step = None
        self._step_shape = None

    # ------------------------------------------------------------------
    def _local_round_fast(self, inputs, key):
        """uint32 Solinas body under shard_map: inputs [P_loc, d_loc].

        Identical dataflow to ``_local_round`` (same collectives over the
        same axes) with all field math on the fast path; cross-shard sums
        ride the collectives in uint32 (bounded: p_shards * (p-1) < 2^32,
        checked in __init__) and are canonicalized on arrival.
        """
        s = self.scheme
        sp = self._sp
        P_loc, d_loc = inputs.shape
        pi = jax.lax.axis_index("p")
        di = jax.lax.axis_index("d")
        key = jax.random.fold_in(jax.random.fold_in(key, pi), di)

        x = _to_residues32(inputs, sp)
        if isinstance(self.masking, FullMasking):
            mkey, skey = jax.random.split(key)
            masks = fastfield.uniform32(mkey, (P_loc, d_loc), sp)
            masked = fastfield.modadd32(x, masks, sp)
            local_mask_sum = fastfield.modsum32(masks, sp, axis=0)     # [d_loc]
        else:
            skey = key
            masked = x
            local_mask_sum = None

        shares = sharing.packed_share32(
            skey, masked, self._M_host, sp,
            secret_count=s.secret_count, privacy_threshold=s.privacy_threshold,
        )                                                              # [P_loc, n, B_loc]
        local_sum = fastfield.modsum32(shares, sp, axis=0)             # [n, B_loc]

        clerk_rows = jax.lax.psum_scatter(
            local_sum, "p", scatter_dimension=0, tiled=True
        )                                                              # [n/p, B_loc]
        clerk_rows = fastfield.canon32(clerk_rows, sp)

        gathered = jax.lax.all_gather(clerk_rows, "p", axis=0, tiled=True)

        masked_total = sharing.packed_reconstruct32(
            gathered, self._L_host, sp, dimension=d_loc
        )                                                              # [d_loc]

        if local_mask_sum is None:
            return masked_total.astype(jnp.int64)
        mask_total = fastfield.canon32(jax.lax.psum(local_mask_sum, "p"), sp)
        return fastfield.modsub32(masked_total, mask_total, sp).astype(jnp.int64)

    def _local_round(self, inputs, key):
        """Per-device body under shard_map: inputs [P_loc, d_loc]."""
        s = self.scheme
        p = s.prime_modulus
        mod = self.masking.modulus if isinstance(self.masking, FullMasking) else p
        P_loc, d_loc = inputs.shape
        pi = jax.lax.axis_index("p")
        di = jax.lax.axis_index("d")
        # distinct randomness per device block
        key = jax.random.fold_in(jax.random.fold_in(key, pi), di)

        if isinstance(self.masking, FullMasking):
            mkey, skey = jax.random.split(key)
            masks = modular.uniform_mod(mkey, (P_loc, d_loc), mod)
            masked = modular.modadd(inputs, masks, mod)
            local_mask_sum = modular.modsum(masks, mod, axis=0)        # [d_loc]
        else:
            skey = key
            masked = modular.canon(inputs, p)  # kernels need residues in [0, p)
            local_mask_sum = jnp.zeros((d_loc,), jnp.int64)

        # share each local participant's dim chunk: [P_loc, n, B_loc]
        B_loc = d_loc // s.secret_count
        shares = sharing.packed_share(
            skey, masked, self._M,
            prime=p, secret_count=s.secret_count, privacy_threshold=s.privacy_threshold,
        )

        # participant parallelism -> local reduction
        local_sum = modular.modsum(shares, p, axis=0)                  # [n, B_loc]

        # snapshot transpose + clerk combine == one psum_scatter over ICI:
        # clerk axis is split across 'p' while partial sums are combined
        clerk_rows = jax.lax.psum_scatter(
            local_sum, "p", scatter_dimension=0, tiled=True
        )                                                              # [n/p, B_loc]
        clerk_rows = jnp.mod(clerk_rows, p)

        # recipient gathers all clerk rows (clerk -> recipient leg)
        gathered = jax.lax.all_gather(
            clerk_rows, "p", axis=0, tiled=True
        )                                                              # [n, B_loc]

        # reconstruct on the local dim chunk
        masked_total = sharing.packed_reconstruct(
            gathered, self._L, prime=p, dimension=d_loc
        )                                                              # [d_loc]

        # unmask: combine mask across participant shards
        mask_total = jax.lax.psum(local_mask_sum, "p")
        if isinstance(self.masking, FullMasking):
            mask_total = jnp.mod(mask_total, mod)
            out = modular.modsub(masked_total, mask_total, mod)
        else:
            out = masked_total
        return out                                                     # [d_loc]

    def _build(self, P_total: int, d_total: int):
        s = self.scheme
        p_shards, d_shards = self.mesh.devices.shape
        if P_total % p_shards:
            raise ValueError(f"participants {P_total} not divisible by p axis {p_shards}")
        if d_total % (s.secret_count * d_shards):
            raise ValueError(
                f"dimension {d_total} must be divisible by secret_count*d_shards "
                f"= {s.secret_count * d_shards}"
            )
        fn = jax.shard_map(
            self._local_round_fast if self._sp is not None else self._local_round,
            mesh=self.mesh,
            in_specs=(P("p", "d"), P()),
            out_specs=P("d"),
            check_vma=False,
        )
        return jax.jit(fn)

    def aggregate(self, inputs, key=None):
        """[P, d] participant inputs -> [d] aggregate (one full round)."""
        inputs = jnp.asarray(inputs, dtype=jnp.int64)
        if key is None:
            from ..crypto.core import fresh_prng_key

            key = fresh_prng_key()
        shape = tuple(inputs.shape)
        if self._step is None or self._step_shape != shape:
            self._step = self._build(*shape)
            self._step_shape = shape
        sharding = NamedSharding(self.mesh, P("p", "d"))
        # first round per shape includes jit compilation (jax.jit is lazy):
        # it shows in the phase stats as max_s >> min_s
        with timed_phase("mesh.round"):
            inputs = jax.device_put(inputs, sharding)
            out = self._step(inputs, key)
            out.block_until_ready()
        return out

    def aggregate_fn(self, P_total: int, d_total: int):
        """The raw jitted SPMD round for benchmarking/compile checks."""
        return self._build(P_total, d_total)


def single_chip_round(
    sharing_scheme: PackedShamirSharing,
    masking_scheme: Optional[LinearMaskingScheme] = None,
):
    """Collective-free full aggregation round, jittable on one device.

    Same algebra as SimulatedPod (mask -> share -> combine -> reconstruct ->
    unmask) with the committee resident on a single chip — the flagship
    single-chip "forward step" and the unit benchmark kernel. For Solinas
    primes (the generator's preference) the whole round runs on the uint32
    fast path (fields.fastfield); results are bit-identical either way.
    """
    s = sharing_scheme
    masking = masking_scheme or NoMasking()
    if not isinstance(masking, (NoMasking, FullMasking)):
        raise ValueError("single_chip_round masking: None or Full")
    _check_mask_modulus(masking, s)
    p = s.prime_modulus
    M_host = numtheory.packed_share_matrix(
        s.secret_count, s.share_count, s.privacy_threshold,
        p, s.omega_secrets, s.omega_shares,
    )
    L_host = numtheory.packed_reconstruct_matrix(
        s.secret_count, s.share_count, s.privacy_threshold,
        p, s.omega_secrets, s.omega_shares, tuple(range(s.share_count)),
    )

    sp = fastfield.SolinasPrime.try_from(p)
    if sp is not None:

        def round_fn(inputs, key):
            P_total, d = inputs.shape
            x = _to_residues32(inputs, sp)
            if isinstance(masking, FullMasking):
                mkey, skey = jax.random.split(key)
                masks = fastfield.uniform32(mkey, (P_total, d), sp)
                masked = fastfield.modadd32(x, masks, sp)
                mask_total = fastfield.modsum32(masks, sp, axis=0)
            else:
                skey = key
                masked = x
                mask_total = None
            shares = sharing.packed_share32(
                skey, masked, M_host, sp,
                secret_count=s.secret_count, privacy_threshold=s.privacy_threshold,
            )                                                  # [P, n, B]
            combined = fastfield.modsum32(shares, sp, axis=0)  # clerk combine
            masked_total = sharing.packed_reconstruct32(
                combined, L_host, sp, dimension=d
            )
            if mask_total is None:
                return masked_total.astype(jnp.int64)
            return fastfield.modsub32(masked_total, mask_total, sp).astype(jnp.int64)

        return round_fn

    M = jnp.asarray(M_host)
    L = jnp.asarray(L_host)

    def round_fn(inputs, key):
        P_total, d = inputs.shape
        if isinstance(masking, FullMasking):
            mod = masking.modulus
            mkey, skey = jax.random.split(key)
            masks = modular.uniform_mod(mkey, (P_total, d), mod)
            masked = modular.modadd(inputs, masks, mod)
            mask_total = modular.modsum(masks, mod, axis=0)
        else:
            skey = key
            masked = modular.canon(inputs, p)  # kernels need residues in [0, p)
            mask_total = None
        shares = sharing.packed_share(
            skey, masked, M,
            prime=p, secret_count=s.secret_count, privacy_threshold=s.privacy_threshold,
        )                                                   # [P, n, B]
        combined = modular.modsum(shares, p, axis=0)        # [n, B] clerk combine
        masked_total = sharing.packed_reconstruct(combined, L, prime=p, dimension=d)
        if mask_total is None:
            return masked_total
        return modular.modsub(masked_total, mask_total, masking.modulus)

    return round_fn
