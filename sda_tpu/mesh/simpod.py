"""Simulated-pod mode: the clerk committee on a TPU device mesh.

The TPU-native execution mode the reference cannot express: instead of
participants HTTP-POSTing encrypted shares to a broker that transposes them
into per-clerk jobs (server/src/snapshot.rs), the whole aggregation round
runs as ONE jitted SPMD program over a `jax.sharding.Mesh`, with XLA
collectives over ICI replacing every server round-trip.

Mesh axes and their protocol meaning (SURVEY.md §2.4 mapping):

- ``p`` — participant shards; the clerk committee also lives along this
  axis (clerk c's combined share lands on device c // (n/p_shards)).
- ``d`` — vector-dimension shards (the reference's analog of sequence/
  tensor parallelism: batching layer chunks, §5.7).

Dataflow per round, per (p, d) device:

1. mask + share the local [P/p, d/d'] participant block (threefry or
   device-ChaCha per participant, share matmul on the local dim chunk);
2. sum local participants' shares — participant parallelism is a *local*
   reduction;
3. ``psum_scatter`` over ``p`` splits the clerk axis while summing across
   participant shards — this one collective IS the snapshot transpose plus
   every clerk's combine, riding ICI instead of the broker;
4. ``all_gather`` over ``p`` hands the recipient all clerk rows; the
   reconstruct (Lagrange matmul for packed Shamir, share-sum for additive)
   and unmask run dim-sharded.

Scheme coverage matches the reference's full pluggability
(client/src/crypto/masking/mod.rs:33-94, sharing/mod.rs:35-96): sharing is
Packed-Shamir OR additive; masking is None, Full, or ChaCha (seed-
compressed masks expanded on device at each shard's dim offset,
fields/chacha_jax.py). Inputs are auto-padded to the mesh/scheme grain:
zero participants and zero components aggregate as zero and are stripped
from the output.

Trust model: this mode computes the same algebra with the same scheme
parameters but no transport encryption (devices of one pod trust each
other); the scheme enums already model pluggable encryption — the
federated HTTP mode keeps sealed boxes.
"""

from __future__ import annotations

import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fields import chacha_jax, fastfield, numtheory, sharing
from ..fields.ops import FieldOps
from ..obs import devprof
from ..utils import timed_phase
from ..protocol import (
    AdditiveSharing,
    BasicShamirSharing,
    ChaChaMasking,
    FullMasking,
    LinearMaskingScheme,
    LinearSecretSharingScheme,
    NoMasking,
    PackedShamirSharing,
)

#: schemes whose share/reconstruct are host-built matrices applied as
#: device matmuls (numtheory.share_matrix_for / reconstruct_matrix_for)
SHAMIR_SCHEMES = (PackedShamirSharing, BasicShamirSharing)


def _shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` with per-shard checking off, falling back to the
    pre-0.5 ``jax.experimental.shard_map`` spelling (same semantics, the
    check flag was named ``check_rep``) so the mesh modes run on either
    jax generation present across this repo's environments."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


# re-export: lives in fields.fastfield (pure field arithmetic); kept under
# the old name for existing importers
_to_residues32 = fastfield.to_residues32


def _scheme_modulus(scheme: LinearSecretSharingScheme) -> int:
    if isinstance(scheme, SHAMIR_SCHEMES):
        return scheme.prime_modulus
    if isinstance(scheme, AdditiveSharing):
        return scheme.modulus
    raise ValueError(f"unsupported sharing scheme {type(scheme).__name__}")


def _check_mask_modulus(masking, scheme) -> None:
    # the mask/unmask algebra only cancels when masking and sharing operate
    # in the same group
    mask_mod = getattr(masking, "modulus", None)
    if mask_mod is not None and mask_mod != _scheme_modulus(scheme):
        raise ValueError(
            f"masking modulus {mask_mod} != sharing modulus "
            f"{_scheme_modulus(scheme)}: masks would not cancel"
        )


def _check_collective_headroom(field: FieldOps, p_shards: int) -> None:
    """psum/psum_scatter add ``p_shards`` canonical residues before the next
    canonicalize; the int64 path cannot chunk inside a collective, so the
    bound must hold up front (the uint32 path's bound is enforced by
    FieldOps.create falling back to int64)."""
    if field.sp is None and p_shards * (field.m - 1) >= (1 << 63):
        raise ValueError(
            f"modulus {field.m} too large for {p_shards}-way participant "
            f"shards: cross-shard sums would overflow int64 — use fewer "
            f"p shards or a smaller modulus"
        )


def make_mesh(p_shards: int, d_shards: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = p_shards * d_shards
    if devices.size < n:
        raise ValueError(f"need {n} devices, have {devices.size}")
    return Mesh(devices.reshape(-1)[:n].reshape(p_shards, d_shards), ("p", "d"))


def default_mesh_shape(n_devices: int, share_count: int) -> Tuple[int, int]:
    """Largest p axis that divides both the device count and the committee."""
    p_shards = math.gcd(n_devices, share_count)
    return p_shards, n_devices // p_shards


def make_multislice_mesh(
    n_slices: int, p_per_slice: int, d_shards: int, devices=None
) -> Mesh:
    """A ('p', 'd') mesh whose participant axis spans multiple slices.

    Multi-slice layout rule (the DCN story, SURVEY §5.8): the ``d`` axis —
    whose collectives run every round-stage — must stay *inside* a slice on
    ICI, so ``d`` is the minor device axis within each slice's contiguous
    device block; the participant axis is slice-major, so only the
    all-reduce fold over ``p`` crosses the slice boundary, and XLA phases
    that reduction into an intra-slice (ICI) step plus one inter-slice
    (DCN) step of size ``n_slices``. Device order: devices[i] blocks of
    ``p_per_slice * d_shards`` per slice, exactly the contiguous-slice
    ordering ``jax.devices()`` returns on real multislice TPU deployments.
    The returned mesh has plain ('p', 'd') axes, so every pod/streaming
    code path works unchanged on it.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = n_slices * p_per_slice * d_shards
    if devices.size < n:
        raise ValueError(f"need {n} devices, have {devices.size}")
    block = devices.reshape(-1)[:n].reshape(n_slices, p_per_slice, d_shards)
    return Mesh(block.reshape(n_slices * p_per_slice, d_shards), ("p", "d"))


# ---------------------------------------------------------------------------
# Round stages, shared by the SPMD pod body and the single-chip round.
# Every function takes canonical residues in the FieldOps working dtype.

#: fold_in tag separating the ChaCha-seed key stream from share randomness
_SEED_TAG = 0x5EED

#: fold_in tag separating per-device/tile driver keys from the seed stream:
#: without it, a tile index equal to _SEED_TAG would alias the tile's
#: share/mask randomness onto the ChaCha seed-word PRF stream
_TILE_TAG = 0x711E


def _tile_key(round_key, *indices):
    """Per-device/tile randomness key, domain-separated from _SEED_TAG."""
    k = jax.random.fold_in(round_key, _TILE_TAG)
    for ix in indices:
        k = jax.random.fold_in(k, ix)
    return k


def _check_masking_supported(masking) -> None:
    if not isinstance(masking, (NoMasking, FullMasking, ChaChaMasking)):
        raise ValueError(
            f"unsupported masking scheme {type(masking).__name__}"
        )


def _chacha_seed_words(key, global_ids, seed_bitsize: int):
    """[S] global participant ids -> [S, 8] uint32 seed words.

    The seed depends only on (round key, participant id) — every dim shard
    of one participant derives the SAME seed and expands disjoint windows
    of one stream, which is the whole point of seed-compressed masks.
    Words beyond ceil(seed_bitsize/32) are zero, matching the host spec's
    zero-padded ChaCha key (fields/chacha.py).
    """
    seed_key = jax.random.fold_in(key, _SEED_TAG)
    words = (int(seed_bitsize) + 31) // 32
    if words > 8:
        raise ValueError("seed_bitsize > 256 unsupported")

    def one(i):
        w = jax.random.bits(jax.random.fold_in(seed_key, i), (8,), jnp.uint32)
        keep = (jnp.arange(8) < words)
        return jnp.where(keep, w, jnp.uint32(0))

    return jax.vmap(one)(global_ids)


def _mask_stage(masking, f: FieldOps, x, key, round_key, pid_base, d_block0):
    """-> (masked [S, d_loc], local_mask_sum [d_loc] or None, share_key).

    ``pid_base``: global id of the first local participant row (ChaCha
    seeds are a function of (round key, global participant id) only).
    ``d_block0``: ChaCha block counter at this shard's dim offset
    (= global_dim_offset / 8). Both may be traced.
    """
    S, d_loc = x.shape
    # named scope: the mask stage's ops land on a "sda.mask"-prefixed XProf
    # device lane, so merged traces attribute device time to the phase
    with jax.named_scope("sda.mask"):
        if isinstance(masking, FullMasking):
            mkey, skey = jax.random.split(key)
            masks = f.uniform(mkey, (S, d_loc))
        elif isinstance(masking, ChaChaMasking):
            skey = key
            gids = pid_base + jnp.arange(S)
            seeds = _chacha_seed_words(round_key, gids, masking.seed_bitsize)
            draws = chacha_jax.stream_u64_at(seeds, d_block0, dimension=d_loc)
            masks = f.from_u64(draws)
        else:
            return x, None, key
        masked = f.add(x, masks)
        return masked, f.sum(masks, axis=0), skey


def _share_sum_stage(scheme, f: FieldOps, M_host, masked, skey):
    """[S, d_loc] masked residues -> [n, B] participant-SUMMED share rows.

    Share generation is linear in the (secrets, randomness) vector, so the
    clerk-combined output Σ_p M @ v_p equals M @ Σ_p v_p: participants
    fold with cheap modular adds FIRST and the share matmul runs once —
    the [S, n, B] per-participant share tensor is never materialized
    (those rows live on the participants' own devices in the federated
    protocol; a pod computing the aggregate needs only their sum).
    Bit-exact vs summing per-participant shares from
    ``sharing.packed_share32``/``packed_share``/``additive_share`` (the
    federated client path): the same randomness shapes are drawn from the
    same key and mod-m arithmetic is exact, so fold order is free —
    tests/test_mesh.py and test_fast_rounds.py pin this equivalence.
    """
    S, d = masked.shape
    with jax.named_scope("sda.share"):
        if isinstance(scheme, SHAMIR_SCHEMES):
            k, t = scheme.secret_count, scheme.privacy_threshold
            B = -(-d // k)
            rand = f.uniform(skey, (S, t, B))
            rsum = f.sum(rand, axis=0)                             # [t, B]
            sk = sharing.batch_columns(f.sum(masked, axis=0), k)   # [k, B]
            zeros = jnp.zeros((1, B), sk.dtype)
            values = jnp.concatenate([zeros, sk, rsum], axis=0)    # [m2, B]
            if f.sp is not None:
                return fastfield.modmatmul32(M_host, values, f.sp)
            from ..fields import modular

            return modular.modmatmul(jnp.asarray(M_host), values, f.m)
        # additive: Σ_p last_p = Σ_p masked_p - Σ over all draws
        n = scheme.share_count
        draws = f.uniform(skey, (S, n - 1, d))
        dsum = f.sum(draws, axis=0)                                # [n-1, d]
        last = f.sub(f.sum(masked, axis=0), f.sum(dsum, axis=0))   # [d]
        return jnp.concatenate([dsum, last[None, :]], axis=0)


def _pallas_supported(scheme, masking, f: FieldOps) -> bool:
    """The fused kernel serves packed-Shamir over a Solinas prime with any
    masking in the lattice. None/Full draw inside the kernel; ChaCha masks
    are expanded from the CHACHA_PRG_V1 stream in a fused XLA pass FIRST
    and the kernel runs mask-free on the pre-masked input — see
    _pallas_stage. Pod-internal masks are generated AND cancelled inside
    the round (never wire-visible), so this choice is independent of the
    scheme's ``prg`` tag — any prg-tagged ChaChaMasking is accepted and
    the aggregate is exact either way."""
    return (
        isinstance(scheme, SHAMIR_SCHEMES)
        and f.sp is not None
        and isinstance(masking, (NoMasking, FullMasking, ChaChaMasking))
    )


def _pallas_env_default() -> bool:
    return os.environ.get("SDA_PALLAS") == "1"


def _resolve_pallas(scheme, masking, f: FieldOps, use_pallas, what: str) -> bool:
    """Shared constructor gating for the three aggregators: env default
    (SDA_PALLAS=1) falls back to the XLA step silently on unsupported
    configs; an EXPLICIT use_pallas=True raises instead."""
    want = _pallas_env_default() if use_pallas is None else bool(use_pallas)
    active = want and _pallas_supported(scheme, masking, f)
    if use_pallas and not active:
        raise ValueError(
            f"pallas {what} step requires packed-Shamir over a Solinas "
            f"prime (none/full/chacha masking)"
        )
    return active


def _pallas_stage(scheme, f: FieldOps, M_host, masking, x, dev_key, *,
                  round_key=None, pid_base=0, d_block0=0,
                  interpret: bool = False, external_bits_fn=None):
    """[S, d_loc] canonical residues -> (combined shares [n, B0],
    mask sum [d_loc] | None) on the fused Pallas kernel.

    Drop-in replacement for the _mask_stage + _share_sum_stage pair in the
    pod/streamed local steps (fused HBM pass: pallas_round.py). The round
    result is exact for ANY mask/share randomness — masks cancel in the
    final subtract and the random polynomial rows are annihilated by the
    reconstruction matrix — so swapping the XLA threefry draws for the
    kernel's on-core PRNG (or injected external bits) never changes the
    aggregate; tests pin pallas-pod == xla-pod == plain sum.

    ChaCha masking: the mask is the CHACHA_PRG_V1 stream, a function of
    (round key, global participant id, dim offset) — it is applied by the
    existing fused XLA _mask_stage pass first, and the kernel then runs
    mask-free on the pre-masked input; ``round_key``/``pid_base``/
    ``d_block0`` locate this tile in the global stream exactly like the
    XLA path. This is prg-tag-independent by the same cancellation
    argument as above: pod masks never leave the round, so the scheme's
    wire ``prg`` (default rand-0.3) only governs FEDERATED seed uploads,
    which pod mode never produces.

    ``external_bits_fn(key, S, draws, B)`` (tests/util.external_bits
    layout) enables interpret-mode runs on CPU, where the TPU PRNG
    primitive is unavailable.
    """
    from ..fields import pallas_round
    from ..utils.benchtime import pallas_knobs, tile_from_sweep, tree_fold_knob

    chacha_mask_sum = None
    if isinstance(masking, ChaChaMasking):
        x, chacha_mask_sum, _ = _mask_stage(
            masking, f, x, dev_key, round_key,
            pid_base=pid_base, d_block0=d_block0,
        )
        masking = NoMasking()

    S, d_loc = x.shape
    k, t = scheme.secret_count, scheme.privacy_threshold
    masked = isinstance(masking, FullMasking)
    x_cols = sharing.batch_columns(x, k)                    # [S, k, B0]
    B0 = x_cols.shape[-1]
    p_block, tile = pallas_knobs()
    # a SWEEP-sourced tile (tuned at flagship widths) must not inflate
    # SMALL shapes: a 2048 record at B0=8 would pad the kernel's column
    # axis 256x — clamp it to the adaptive per-shape bound. An EXPLICIT
    # user SDA_PALLAS_TILE is honored as-is (padding and all).
    shape_tile = 2048 if B0 >= 2048 else max(128, -(-B0 // 128) * 128)
    if tile is None:
        tile = shape_tile
    elif tile_from_sweep():
        tile = min(tile, shape_tile)
    pad = (-B0) % tile
    if pad:  # padded columns are sliced off below; their shares never land
        x_cols = jnp.pad(x_cols, ((0, 0), (0, 0), (0, pad)))
    seed = jax.random.randint(dev_key, (), 0, np.int32(2**31 - 1),
                              dtype=jnp.int32)
    ext = None
    if external_bits_fn is not None:
        draws = (k + t) if masked else t
        ext = external_bits_fn(dev_key, S, draws, B0 + pad)
    with jax.named_scope("sda.mask_share"):
        shares, mask_tot = pallas_round.fused_mask_share_combine(
            x_cols, seed, f.sp, M_host, t, masked,
            tile=tile, external_bits=ext, interpret=interpret,
            p_block=p_block, tree_fold=tree_fold_knob(),
        )
    shares = shares[:, :B0]
    if not masked:
        return shares, chacha_mask_sum
    return shares, sharing.unbatch_columns(mask_tot[:, :B0], d_loc)


def _scan_combine(f: FieldOps, scheme, masking, M_host, x, key, round_key,
                  pid0, dblk0, chunk: int):
    """[P, d] canonical residues -> (acc_shares [n, B], acc_mask [d]|None).

    Streams participants through ``lax.scan`` in blocks of ``chunk``: the
    live share tensor is [chunk, n, B] instead of [P, n, B], so the XLA
    path stops round-tripping the full share tensor through HBM (the
    round-1 single-chip bottleneck; ~2x even on CPU from cache locality).
    Zero-padded rows aggregate as zero and their masks cancel.
    """
    P, d = x.shape
    chunk = max(1, min(int(chunk), P))
    pad = (-P) % chunk
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)], axis=0)
    nblk = x.shape[0] // chunk
    xb = x.reshape(nblk, chunk, d)
    n = scheme.output_size
    B = d // scheme.input_size
    has_mask = not isinstance(masking, NoMasking)

    def body(carry, blk_i):
        acc_s, acc_m = carry
        blk, i = blk_i
        bkey = jax.random.fold_in(key, i)
        masked, mask_sum, skey = _mask_stage(
            masking, f, blk, bkey, round_key,
            pid_base=pid0 + i * chunk, d_block0=dblk0,
        )
        acc_s = f.add(acc_s, _share_sum_stage(scheme, f, M_host, masked, skey))
        if mask_sum is not None:
            acc_m = f.add(acc_m, mask_sum)
        return (acc_s, acc_m), None

    init = (jnp.zeros((n, B), f.dtype), jnp.zeros((d,), f.dtype))
    (acc_s, acc_m), _ = jax.lax.scan(
        body, init, (xb, jnp.arange(nblk, dtype=jnp.int32))
    )
    return acc_s, (acc_m if has_mask else None)


def _reconstruct_stage(scheme, f: FieldOps, L_host, gathered, d_loc: int):
    """[n, B] clerk rows -> [d_loc] masked totals."""
    with jax.named_scope("sda.reconstruct"):
        if isinstance(scheme, SHAMIR_SCHEMES):
            if f.sp is not None:
                return sharing.packed_reconstruct32(
                    gathered, L_host, f.sp, dimension=d_loc
                )
            return sharing.packed_reconstruct(
                gathered, jnp.asarray(L_host),
                prime=scheme.prime_modulus, dimension=d_loc,
            )
        return f.sum(gathered, axis=0)  # additive: plain share sum


def _dim_grain(scheme, masking) -> int:
    """Smallest dim-chunk size a single device can hold: packing width,
    times the ChaCha block width when masks are stream-expanded."""
    grain = scheme.input_size
    if isinstance(masking, ChaChaMasking):
        grain = math.lcm(grain, 8)
    return grain


def _build_matrices(scheme, survivors: Optional[Tuple[int, ...]] = None):
    if not isinstance(scheme, SHAMIR_SCHEMES):
        return None, None
    M = numtheory.share_matrix_for(scheme)
    L = numtheory.reconstruct_matrix_for(
        scheme,
        tuple(range(scheme.share_count)) if survivors is None else survivors,
    )
    return M, L


def _normalize_survivors(scheme, surviving_clerks) -> Optional[Tuple[int, ...]]:
    """Validate a clerk-dropout quorum for the mesh modes (SURVEY §2.4
    fault-tolerant-quorum row; reference semantics crypto.rs:146-153).

    The pod/streamed finale reconstructs from clerk ROWS; a lost device or
    process loses the clerk rows it hosts, never the mask sums (masks
    travel participant->recipient, not through clerks — receive.rs:102-118),
    so dropping to a quorum of rows recovers the exact aggregate. Truncates
    to exactly reconstruction_threshold rows so the finale has ONE compiled
    shape per survivor count (the fixed-quorum design of
    crypto/sharing.py::PackedShamirReconstructor).
    """
    if surviving_clerks is None:
        return None
    survivors = tuple(int(i) for i in surviving_clerks)
    n = scheme.output_size
    if any(i < 0 or i >= n for i in survivors) or len(set(survivors)) != len(survivors):
        raise ValueError(f"surviving clerks {survivors} must be distinct in [0, {n})")
    if not isinstance(scheme, SHAMIR_SCHEMES):
        if len(survivors) < n:
            raise ValueError(
                "additive sharing needs every clerk row; clerk dropout "
                "requires a Shamir scheme (crypto.rs:146-153)"
            )
        return None  # all rows = the normal finale
    r = scheme.reconstruction_threshold
    if len(survivors) < r:
        raise ValueError(
            f"need at least reconstruction_threshold={r} surviving clerks, "
            f"got {len(survivors)}"
        )
    return survivors[:r]


class SimulatedPod:
    """One secure-aggregation round as a single SPMD program.

    Committee size must be divisible by the ``p`` axis; participant and
    dimension counts are auto-padded to the mesh/scheme grain (zero rows
    and components aggregate as zero; padding is stripped from the output).
    """

    def __init__(
        self,
        sharing_scheme: LinearSecretSharingScheme,
        masking_scheme: Optional[LinearMaskingScheme] = None,
        mesh: Optional[Mesh] = None,
        scan_chunk: int = 8,
        use_pallas: Optional[bool] = None,
        pallas_interpret: bool = False,
        pallas_external_bits_fn=None,
        surviving_clerks=None,
    ):
        self.scan_chunk = int(scan_chunk)
        self.scheme = sharing_scheme
        self.modulus = _scheme_modulus(sharing_scheme)
        self.masking = masking_scheme or NoMasking()
        _check_masking_supported(self.masking)
        _check_mask_modulus(self.masking, sharing_scheme)
        self._pallas_interpret = bool(pallas_interpret)
        self._pallas_bits_fn = pallas_external_bits_fn
        if mesh is None:
            p_shards, d_shards = default_mesh_shape(
                len(jax.devices()), sharing_scheme.output_size
            )
            mesh = make_mesh(p_shards, d_shards)
        self.mesh = mesh
        p_shards = mesh.devices.shape[0]
        if sharing_scheme.output_size % p_shards:
            raise ValueError(
                f"committee size {sharing_scheme.output_size} must be divisible "
                f"by the p axis ({p_shards})"
            )
        self.surviving_clerks = _normalize_survivors(
            sharing_scheme, surviving_clerks
        )
        self._M_host, self._L_host = _build_matrices(
            sharing_scheme, self.surviving_clerks
        )
        # cross-shard share/mask sums ride collectives between canonicalizes
        self._field = FieldOps.create(self.modulus, cross_terms=p_shards)
        _check_collective_headroom(self._field, p_shards)
        self.pallas_active = _resolve_pallas(
            sharing_scheme, self.masking, self._field, use_pallas, "local"
        )
        self._step = None
        self._step_shape = None

    @property
    def _sp(self):
        """Solinas parameters when the uint32 fast path is active, else None."""
        return self._field.sp

    # ------------------------------------------------------------------
    def _local_round(self, inputs, key):
        """Per-device body under shard_map: inputs [P_loc, d_loc]."""
        f = self._field
        P_loc, d_loc = inputs.shape
        pi = jax.lax.axis_index("p")
        di = jax.lax.axis_index("d")
        # distinct randomness per device block, domain-separated from the
        # ChaCha seed stream; seeds fold the raw round key so every dim
        # shard derives the same per-participant seed
        dev_key = _tile_key(key, pi, di)

        x = f.to_residues(inputs)
        if self.pallas_active:
            # fused mask+share+combine in one HBM pass (pallas_round.py)
            local_sum, local_mask_sum = _pallas_stage(
                self.scheme, f, self._M_host, self.masking, x, dev_key,
                round_key=key, pid_base=pi * P_loc,
                d_block0=di * (d_loc // 8),
                interpret=self._pallas_interpret,
                external_bits_fn=self._pallas_bits_fn,
            )                                                      # [n, B_loc]
        else:
            # participant parallelism -> local scan-chunked reduction (share
            # tensor stays [chunk, n, B_loc], never [P_loc, n, B_loc])
            local_sum, local_mask_sum = _scan_combine(
                f, self.scheme, self.masking, self._M_host, x, dev_key, key,
                pid0=pi * P_loc, dblk0=di * (d_loc // 8),
                chunk=self.scan_chunk,
            )                                                      # [n, B_loc]

        # snapshot transpose + clerk combine == one psum_scatter over ICI:
        # clerk axis is split across 'p' while partial sums are combined
        with jax.named_scope("sda.clerk_combine"):
            clerk_rows = jax.lax.psum_scatter(
                local_sum, "p", scatter_dimension=0, tiled=True
            )                                                      # [n/p, B_loc]
            clerk_rows = f.canon(clerk_rows)

            # recipient gathers all clerk rows (clerk -> recipient leg)
            gathered = jax.lax.all_gather(clerk_rows, "p", axis=0, tiled=True)

        if self.surviving_clerks is not None:
            # clerk dropout: reveal from the quorum's rows only — lost
            # rows (dead device/process) never enter the reconstruct
            gathered = gathered[jnp.asarray(self.surviving_clerks), :]
        masked_total = _reconstruct_stage(
            self.scheme, f, self._L_host, gathered, d_loc
        )                                                          # [d_loc]

        with jax.named_scope("sda.unmask"):
            if local_mask_sum is None:
                return f.to_int64(masked_total)
            mask_total = f.canon(jax.lax.psum(local_mask_sum, "p"))
            return f.to_int64(f.sub(masked_total, mask_total))

    def _build(self, P_total: int, d_total: int):
        p_shards, d_shards = self.mesh.devices.shape
        if P_total % p_shards:
            raise ValueError(f"participants {P_total} not divisible by p axis {p_shards}")
        grain = _dim_grain(self.scheme, self.masking) * d_shards
        if d_total % grain:
            raise ValueError(
                f"dimension {d_total} must be divisible by the scheme/mesh "
                f"grain {grain}"
            )
        fn = _shard_map(
            self._local_round,
            mesh=self.mesh,
            in_specs=(P("p", "d"), P()),
            out_specs=P("d"),
        )
        # devprof: compiled-shape registry + retrace span events + (opt-in)
        # cost analysis for the roofline block — one profile entry for the
        # whole SPMD round regardless of how many shapes get built
        return devprof.instrument("mesh.simpod.round", jax.jit(fn))

    def padded_shape(self, P_total: int, d_total: int) -> Tuple[int, int]:
        p_shards, d_shards = self.mesh.devices.shape
        grain = _dim_grain(self.scheme, self.masking) * d_shards
        return (
            -(-P_total // p_shards) * p_shards,
            -(-d_total // grain) * grain,
        )

    def aggregate(self, inputs, key=None):
        """[P, d] participant inputs -> [d] aggregate (one full round)."""
        inputs = np.asarray(inputs)
        if key is None:
            from ..crypto.core import fresh_prng_key

            key = fresh_prng_key()
        P_total, d_total = inputs.shape
        P_pad, d_pad = self.padded_shape(P_total, d_total)
        if (P_pad, d_pad) != (P_total, d_total):
            # zero participants/components aggregate as zero (masks on the
            # padding cancel like any other mask); strip below
            padded = np.zeros((P_pad, d_pad), dtype=inputs.dtype)
            padded[:P_total, :d_total] = inputs
            inputs = padded
        step = self._get_step(P_pad, d_pad)
        sharding = NamedSharding(self.mesh, P("p", "d"))
        # first round per shape includes jit compilation (jax.jit is lazy):
        # it shows in the phase stats as max_s >> min_s
        with timed_phase("mesh.round"):
            device_inputs = jax.device_put(jnp.asarray(inputs), sharding)
            out = step(device_inputs, key)
            out.block_until_ready()
        return out[:d_total]

    def _get_step(self, P_pad: int, d_pad: int):
        """The jitted SPMD round for an already-padded shape (one-shape
        cache, shared by aggregate() and multihost.aggregate_process_local)."""
        shape = (P_pad, d_pad)
        if self._step is None or self._step_shape != shape:
            self._step = self._build(*shape)
            self._step_shape = shape
        return self._step

    def aggregate_fn(self, P_total: int, d_total: int):
        """The raw jitted SPMD round for benchmarking/compile checks
        (shapes must already satisfy the mesh/scheme grain)."""
        return self._build(P_total, d_total)


def single_chip_round(
    sharing_scheme: LinearSecretSharingScheme,
    masking_scheme: Optional[LinearMaskingScheme] = None,
    dim_tile: Optional[int] = None,
):
    """Collective-free full aggregation round, jittable on one device.

    Same algebra as SimulatedPod (mask -> share -> combine -> reconstruct ->
    unmask) with the committee resident on a single chip — the flagship
    single-chip "forward step" and the unit benchmark kernel. For Solinas
    moduli the whole round runs on the uint32 fast path (fields.fastfield);
    results are bit-identical either way. ChaCha masking requires the
    dimension to be a multiple of 8 (one ChaCha block).

    ``dim_tile``: process the dimension in fixed-width tiles via
    ``lax.scan`` instead of one full-width program. The round-3 hardware
    window measured the full-width XLA program SUPERLINEAR in d (marginal
    25.8ms at d~1M vs 7.7ms at d/2 — ratio 3.4, i.e. per-element cost
    1.7x worse at full width; HW_WATCH.jsonl timing_check), so tiling the
    dim axis keeps every tile on the fast side of that cliff and makes
    round cost linear in d by construction. Exact for any tile width:
    each tile is a complete mask->share->combine->reconstruct->unmask
    round over its own columns (masks cancel per tile; ChaCha tiles read
    their window of the global stream via d_block0).
    """
    scheme = sharing_scheme
    masking = masking_scheme or NoMasking()
    if not isinstance(masking, (NoMasking, FullMasking, ChaChaMasking)):
        raise ValueError(
            f"unsupported masking scheme {type(masking).__name__}"
        )
    _check_mask_modulus(masking, scheme)
    M_host, L_host = _build_matrices(scheme)
    f = FieldOps.create(_scheme_modulus(scheme))
    # tile grain: whole packing columns (input_size) and whole ChaCha
    # blocks (8 u64 draws) — same grain as the streaming driver
    grain = scheme.input_size * 8 // math.gcd(scheme.input_size, 8)

    def one_tile(x, bkey, round_key, d_block0, d_loc):
        masked, mask_total, skey = _mask_stage(
            masking, f, x, bkey, round_key, pid_base=0, d_block0=d_block0
        )
        # share + clerk combine fused via linearity (see _share_sum_stage)
        combined = _share_sum_stage(scheme, f, M_host, masked, skey)  # [n, B]
        masked_total = _reconstruct_stage(scheme, f, L_host, combined, d_loc)
        with jax.named_scope("sda.unmask"):
            if mask_total is None:
                return f.to_int64(masked_total)
            return f.to_int64(f.sub(masked_total, mask_total))

    if dim_tile is None:
        def round_fn(inputs, key):
            P_total, d = inputs.shape
            return one_tile(f.to_residues(inputs), key, key, 0, d)

        return round_fn

    from ..fields.dimtile import scan_dim_tiles

    def tile_body(blk, round_key, tile_key, i, width):
        # per-tile residue conversion fuses into the tile program; the
        # ChaCha block counter locates this tile in the global stream
        return one_tile(f.to_residues(blk), tile_key, round_key,
                        i * (width // 8), width)

    return scan_dim_tiles(tile_body, grain, dim_tile)
