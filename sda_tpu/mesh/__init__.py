"""TPU-native simulated-pod execution over a device mesh."""

from . import multihost
from .devscale import (
    DeviceTileCombiner,
    DeviceTileSink,
    ModelScaleRound,
    watermark_dim_tile,
)
from .simpod import (
    SimulatedPod,
    default_mesh_shape,
    make_mesh,
    make_multislice_mesh,
    single_chip_round,
)
from .streaming import (
    StreamedPod,
    StreamingAggregator,
    array_block_provider,
    synthetic_block_provider,
    synthetic_block_provider32,
    synthetic_device_block_provider32,
)
