"""Multi-host execution: the DCN-scale story made runnable.

The reference scales across machines with a REST broker; the pod modes
replace that with XLA collectives over ICI (SURVEY §5.8). This module
closes the remaining gap — *multi-controller* runs where each host owns a
process-local slice of the participants and the collectives ride ICI
within a host/slice and DCN across them:

- ``initialize()`` wraps ``jax.distributed.initialize`` (call before any
  jax backend touch; on TPU pods the arguments are auto-detected).
- ``aggregate_process_local(pod, local_inputs)`` runs one full secure-
  aggregation round where every process contributes its own participant
  rows: inputs are assembled into a global array with
  ``jax.make_array_from_process_local_data`` (no host ever materializes
  the global input), the pod's SPMD round runs once, and every process
  receives the full [d] aggregate.

Pair the mesh with ``make_multislice_mesh(n_slices=process_count, ...)``
so each process's devices form one contiguous slice block of the ``p``
axis — then participant data never crosses hosts; only the clerk-combine
reduction does (one DCN step, SURVEY §2.4's committee parallelism).

Tested for real with two OS processes over gRPC on CPU meshes
(tests/test_multihost.py) — the same code path multi-host TPU uses.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` with explicit args (CPU/GPU fleets)
    or auto-detection (TPU pods). Must run before any jax backend init.
    On CPU fleets set the per-process device count via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def aggregate_process_local(pod, local_inputs, key=None):
    """One secure-aggregation round over process-local participant rows.

    Every process passes a ``[P_local, d]`` block of the SAME shape (ragged
    counts must be zero-padded by the caller first — zero rows aggregate as
    zero with their masks cancelling). Returns the full [d] aggregate as
    host numpy, identical on every process.
    """
    import math

    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..crypto.core import fresh_prng_key
    from ..utils import timed_phase

    inputs = np.asarray(local_inputs)
    if inputs.ndim != 2:
        raise ValueError("local_inputs must be [P_local, d]")
    nproc = jax.process_count()
    P_local, d_total = inputs.shape

    # all processes must agree on the global shape; cheapest agreement is
    # requiring a common local row count (ragged blocks would silently
    # misalign the participant axis)
    shapes = multihost_utils.process_allgather(
        jnp.asarray([P_local, d_total], dtype=jnp.int32)
    ).reshape(nproc, 2)
    if not (shapes == shapes[0]).all():
        raise ValueError(
            f"process-local input shapes disagree: {shapes.tolist()}"
        )

    P_global = P_local * nproc
    # each process's devices must tile whole, contiguous p-rows of the mesh
    # (jax.make_array_from_process_local_data maps local blocks onto the
    # process-addressed extent) — make_multislice_mesh(n_slices=nproc, ...)
    # produces exactly this layout
    p_shards, d_shards = pod.mesh.devices.shape
    n_local = len(jax.local_devices())
    if p_shards % nproc or (p_shards // nproc) * d_shards != n_local:
        raise ValueError(
            f"mesh ({p_shards}, {d_shards}) does not split its p axis "
            f"evenly over {nproc} processes x {n_local} local devices; "
            f"build it with make_multislice_mesh(n_slices={nproc}, "
            f"p_per_slice={n_local}//d_shards, d_shards)"
        )
    # the participant axis must honor BOTH grains: the mesh p axis (via
    # pod.padded_shape) and an integer per-process row count
    p_grain = math.lcm(p_shards, nproc)
    P_lift = -(-P_global // p_grain) * p_grain
    P_pad, d_pad = pod.padded_shape(P_lift, d_total)
    assert P_pad == P_lift and P_pad % nproc == 0
    P_pad_local = P_pad // nproc
    padded = np.zeros((P_pad_local, d_pad), dtype=inputs.dtype)
    padded[:P_local, :d_total] = inputs

    if key is None:
        key = fresh_prng_key()
    # one round key for the whole pod: process 0's key wins
    key = multihost_utils.broadcast_one_to_all(key)

    step = pod._get_step(P_pad, d_pad)

    sharding = NamedSharding(pod.mesh, P("p", "d"))
    with timed_phase("mesh.multihost_round"):
        global_inputs = jax.make_array_from_process_local_data(
            sharding, padded, (P_pad, d_pad)
        )
        out = step(global_inputs, key)
        # out is dim-sharded across the global mesh; allgather to every host
        result = multihost_utils.process_allgather(out, tiled=True)
    return np.asarray(result)[:d_total]
