"""Multi-host execution: the DCN-scale story made runnable.

The reference scales across machines with a REST broker; the pod modes
replace that with XLA collectives over ICI (SURVEY §5.8). This module
closes the remaining gap — *multi-controller* runs where each host owns a
process-local slice of the participants and the collectives ride ICI
within a host/slice and DCN across them:

- ``initialize()`` wraps ``jax.distributed.initialize`` (call before any
  jax backend touch; on TPU pods the arguments are auto-detected).
- ``aggregate_process_local(pod, local_inputs)`` runs one full secure-
  aggregation round where every process contributes its own participant
  rows: inputs are assembled into a global array with
  ``jax.make_array_from_process_local_data`` (no host ever materializes
  the global input), the pod's SPMD round runs once, and every process
  receives the full [d] aggregate.

Pair the mesh with ``make_multislice_mesh(n_slices=process_count, ...)``
so each process's devices form one contiguous slice block of the ``p``
axis — then participant data never crosses hosts; only the clerk-combine
reduction does (one DCN step, SURVEY §2.4's committee parallelism).

Tested for real with two OS processes over gRPC on CPU meshes
(tests/test_multihost.py) — the same code path multi-host TPU uses.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` with explicit args (CPU/GPU fleets)
    or auto-detection (TPU pods). Must run before any jax backend init.
    On CPU fleets set the per-process device count via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def aggregate_process_local(pod, local_inputs, key=None):
    """One secure-aggregation round over process-local participant rows.

    Every process passes its own ``[P_local, d]`` block (same ``d``
    everywhere; ragged ``P_local`` is fine — blocks are zero-padded to the
    max, and zero rows aggregate as zero with their masks cancelling).
    Returns the full [d] aggregate as host numpy, identical on every
    process.
    """
    import math

    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..crypto.core import fresh_prng_key
    from ..utils import timed_phase

    inputs = np.asarray(local_inputs)
    if inputs.ndim != 2:
        raise ValueError("local_inputs must be [P_local, d]")
    nproc = jax.process_count()
    P_local, d_total = inputs.shape

    # processes must agree on the dimension; ragged participant counts are
    # fine — every process sizes its block to the max, and zero rows
    # aggregate as zero with their masks cancelling
    shapes = multihost_utils.process_allgather(
        jnp.asarray([P_local, d_total], dtype=jnp.int32)
    ).reshape(nproc, 2)
    if not (shapes[:, 1] == d_total).all():
        raise ValueError(
            f"process-local dimensions disagree: {shapes[:, 1].tolist()}"
        )
    P_local = int(shapes[:, 0].max())  # sizing only; `padded` zero-fills

    P_global = P_local * nproc
    # each process's devices must tile whole, contiguous p-rows of the mesh
    # (jax.make_array_from_process_local_data maps local blocks onto the
    # process-addressed extent) — make_multislice_mesh(n_slices=nproc, ...)
    # produces exactly this layout
    _check_mesh_process_split(pod.mesh, nproc)
    p_shards = pod.mesh.devices.shape[0]
    # the participant axis must honor BOTH grains: the mesh p axis (via
    # pod.padded_shape) and an integer per-process row count
    p_grain = math.lcm(p_shards, nproc)
    P_lift = -(-P_global // p_grain) * p_grain
    P_pad, d_pad = pod.padded_shape(P_lift, d_total)
    assert P_pad == P_lift and P_pad % nproc == 0
    P_pad_local = P_pad // nproc
    padded = np.zeros((P_pad_local, d_pad), dtype=inputs.dtype)
    padded[: inputs.shape[0], :d_total] = inputs

    if key is None:
        key = fresh_prng_key()
    # one round key for the whole pod: process 0's key wins
    key = multihost_utils.broadcast_one_to_all(key)

    step = pod._get_step(P_pad, d_pad)

    sharding = NamedSharding(pod.mesh, P("p", "d"))
    with timed_phase("mesh.multihost_round"):
        global_inputs = jax.make_array_from_process_local_data(
            sharding, padded, (P_pad, d_pad)
        )
        out = step(global_inputs, key)
        # out is dim-sharded across the global mesh; allgather to every host
        result = multihost_utils.process_allgather(out, tiled=True)
    return np.asarray(result)[:d_total]


def _check_mesh_process_split(mesh, nproc: int) -> None:
    import jax

    p_shards, d_shards = mesh.devices.shape
    n_local = len(jax.local_devices())
    if p_shards % nproc or (p_shards // nproc) * d_shards != n_local:
        raise ValueError(
            f"mesh ({p_shards}, {d_shards}) does not split its p axis "
            f"evenly over {nproc} processes x {n_local} local devices; "
            f"build it with make_multislice_mesh(n_slices={nproc}, "
            f"p_per_slice={n_local}//d_shards, d_shards)"
        )


class _MultihostCheckpointer:
    """Coordinated per-process snapshots for multihost streamed rounds.

    Every process snapshots its OWN addressable shards of the global
    accumulators (plus the — identical-everywhere — completed output
    prefix and tile cursor) to ``path.r{rank}of{n}`` at the same
    deterministic loop boundaries, rotating TWO slots. A crash can leave
    ranks one boundary apart (saves are lockstep but not atomic across
    processes), so resume picks the newest cursor EVERY rank still holds:
    each rank allgathers its available cursors and the same minimum is
    chosen everywhere; if the spread exceeds the two-slot history the
    round restarts from scratch rather than resuming inconsistently.
    Accumulator shards are re-placed by global index, so resume is
    bit-identical to an uninterrupted run (same tile/key derivation).
    """

    SLOTS = 2

    def __init__(self, path, spod, fingerprint):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.nproc = jax.process_count()
        self.rank = jax.process_index()
        self.fingerprint = f"{fingerprint}|nproc={self.nproc}|rank={self.rank}"
        base = f"{path}.r{self.rank}of{self.nproc}"
        self.paths = [f"{base}.{s}" for s in ("a", "b")]
        self.sharding = NamedSharding(spod.mesh, P("p", "d"))
        self._slot = 0

    # -- save --------------------------------------------------------------

    def _acc_payload(self, name, acc):
        payload = {}
        if isinstance(acc, np.ndarray):  # d-tile boundary: empty acc
            payload[f"{name}_host"] = acc
            return payload
        payload[f"{name}_shape"] = np.asarray(acc.shape, dtype=np.int64)
        for j, shard in enumerate(acc.addressable_shards):
            starts = [
                (s.start if s.start is not None else 0)
                for s in shard.index
            ]
            payload[f"{name}_{j}_start"] = np.asarray(starts, dtype=np.int64)
            payload[f"{name}_{j}_data"] = np.asarray(shard.data)
        return payload

    def save(self, out, done_dims, di, pi, acc_shares, acc_mask):
        from .streaming import _atomic_npz, _snapshot_header

        payload = _snapshot_header(self.fingerprint, out, done_dims, di, pi)
        payload.update(self._acc_payload("accS", acc_shares))
        payload.update(self._acc_payload("accM", acc_mask))
        _atomic_npz(self.paths[self._slot], **payload)
        self._slot ^= 1

    # -- load / coordinate -------------------------------------------------

    def _local_candidates(self):
        """cursor -> path, probing ONLY the cursor header (no accumulator
        payloads are materialized until the fleet has picked a target)."""
        from .streaming import _read_snapshot

        cands = {}
        for path in self.paths:
            header = _read_snapshot(path, self.fingerprint,
                                    keys=("done_dims", "di", "pi"))
            if header is not None:
                cursor = (int(header["di"]), int(header["pi"]),
                          int(header["done_dims"]))
                cands[cursor] = path
        return cands

    def load(self):
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        from .streaming import _read_snapshot

        cands = self._local_candidates()
        # encode this rank's available cursors as a fixed [SLOTS, 3] block
        # (-1 rows = no snapshot) and allgather — every rank computes the
        # SAME resume decision from the identical gathered table
        enc = np.full((self.SLOTS, 3), -1, dtype=np.int64)
        for j, cursor in enumerate(sorted(cands)[: self.SLOTS]):
            enc[j] = cursor
        table = np.asarray(multihost_utils.process_allgather(
            jnp.asarray(enc))).reshape(self.nproc, self.SLOTS, 3)
        per_rank = []
        for r in range(self.nproc):
            have = {tuple(int(v) for v in row)
                    for row in table[r] if row[0] >= 0}
            if not have:
                return None  # a rank with no snapshot: fresh start
            per_rank.append(have)
        target = min(max(have) for have in per_rank)
        if any(target not in have for have in per_rank):
            return None  # spread beyond history: restart, never mix
        payload = _read_snapshot(cands[target], self.fingerprint)
        # the full-read outcome must stay a FLEET decision: a snapshot
        # lost between probe and read on one rank must send every rank
        # down the fresh-start path together, not split them
        ok = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([1 if payload is not None else 0])))
        if int(ok.sum()) != self.nproc:
            return None
        return {
            "out": payload["out"],
            "done_dims": payload["done_dims"],
            "di": payload["di"],
            "pi": payload["pi"],
            "_payload": payload,
        }

    def restore(self, resume):
        import jax

        payload = resume["_payload"]

        def rebuild(name):
            shape = tuple(int(v) for v in payload[f"{name}_shape"])
            blocks = {}
            j = 0
            while f"{name}_{j}_data" in payload:
                starts = tuple(int(v) for v in payload[f"{name}_{j}_start"])
                blocks[starts] = payload[f"{name}_{j}_data"]
                j += 1

            def cb(index):
                starts = tuple(
                    (s.start if s.start is not None else 0) for s in index
                )
                return blocks[starts]

            return jax.make_array_from_callback(shape, self.sharding, cb)

        return rebuild("accS"), rebuild("accM")

    def finish(self):
        import os

        for path in self.paths:
            try:
                os.unlink(path)
            except OSError:
                pass


def streamed_aggregate_process_local(
    spod, get_local_block, local_participants: int, dimension: int, key=None,
    *, checkpoint_path=None, checkpoint_every_chunks: int = 16,
):
    """Flagship-scale multihost rounds: every process STREAMS its own
    participant rows through the StreamedPod tile loop.

    ``get_local_block(lp0, lp1, d0, d1)`` returns this process's local rows
    ``[lp0:lp1]`` for dim window ``[d0:d1)`` (short or empty edge blocks
    are zero-padded here, so ragged per-process ``local_participants`` is
    fine). All processes iterate in lockstep to the max local count — each
    global tile is assembled from per-process local blocks with
    ``make_array_from_process_local_data``, so no host ever materializes a
    global tile, let alone the global matrix. Aggregation is a sum, so the
    (process-major) global participant ordering is irrelevant to the
    result. Returns the [dimension] aggregate on every process.

    ``checkpoint_path``: coordinated multi-process resume — every process
    snapshots its own accumulator shards at the same loop boundaries
    (two-slot history; see _MultihostCheckpointer) and a relaunched fleet
    resumes bit-identically from the newest cursor all ranks still hold.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..crypto.core import fresh_prng_key
    from ..utils import timed_phase

    nproc = jax.process_count()
    _check_mesh_process_split(spod.mesh, nproc)
    shapes = multihost_utils.process_allgather(
        jnp.asarray([local_participants, dimension], dtype=jnp.int32)
    ).reshape(nproc, 2)
    if not (shapes[:, 1] == dimension).all():
        raise ValueError(
            f"process-local stream dimensions disagree: {shapes[:, 1].tolist()}"
        )
    # ragged local counts: iterate to the max, but never ask the caller's
    # provider for rows beyond what IT declared — short/empty blocks are
    # zero-padded below and zeros aggregate as zero
    my_count = local_participants
    local_participants = int(shapes[:, 0].max())

    if key is None:
        key = fresh_prng_key()
    key = multihost_utils.broadcast_one_to_all(key)

    pc = spod.participants_chunk
    # StreamedPod rounds pc up to a multiple of p_shards, and the mesh check
    # guarantees nproc divides p_shards — so whole local rows per tile
    assert pc % nproc == 0, (pc, nproc)
    pc_local = pc // nproc
    sharding = NamedSharding(spod.mesh, P("p", "d"))
    dt = spod._field.dtype

    def zeros_global(shape):
        def cb(index):
            sizes = tuple(
                (s.stop if s.stop is not None else dim)
                - (s.start if s.start is not None else 0)
                for s, dim in zip(index, shape)
            )
            return np.zeros(sizes, dt)

        return jax.make_array_from_callback(shape, sharding, cb)

    def make_accs(d_size):
        sS, sM = spod._acc_shapes(d_size)
        return zeros_global(sS), zeros_global(sM)

    def make_block(p0, p1, d0, d1, d_size):
        # global tile rows [p0:p1) map process-major onto local rows
        lp0 = min(p0 // nproc, my_count)
        lp1 = min(p1 // nproc, my_count)
        host = np.asarray(get_local_block(lp0, max(lp0, lp1), d0, d1))
        if host.shape != (pc_local, d_size):
            padded = np.zeros((pc_local, d_size), dtype=host.dtype)
            padded[: host.shape[0], : host.shape[1]] = host
            host = padded
        return jax.make_array_from_process_local_data(
            sharding, host, (pc, d_size)
        )

    def fetch(arr):
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    checkpointer = None
    if checkpoint_path is not None:
        checkpointer = _MultihostCheckpointer(
            checkpoint_path, spod,
            spod._checkpoint_fingerprint(
                local_participants * nproc, dimension, key),
        )

    with timed_phase("mesh.multihost_streamed_round"):
        # drive over the GLOBAL participant count so every process iterates
        # the identical tile sequence in lockstep
        return spod.drive_tiles(
            local_participants * nproc, dimension, key,
            make_block=make_block, make_accs=make_accs, fetch=fetch,
            checkpointer=checkpointer,
            checkpoint_every_chunks=checkpoint_every_chunks,
        )
