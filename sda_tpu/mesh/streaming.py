"""Streamed secure-aggregation rounds for workloads larger than HBM.

SURVEY.md §7 hard part (f): the flagship configs (10k participants x
10M-dim vectors) cannot materialize [P, d] on one chip, let alone the
[P, n, B] share tensor. But the whole pipeline is a sum over participants
of per-participant shares, so it streams: tile the participant axis and
the dimension axis, push each [P_chunk, d_chunk] block through
mask -> share -> local combine on device, and fold it into running
[n, B_chunk] share and [d_chunk] mask accumulators. Peak memory is one
block plus accumulators, independent of P. Per dim-tile, reconstruction
and unmasking run once at the end.

The reference reaches the same scale by chunking vectors into
secret_count-sized batches and streaming participations through the server
one HTTP upload at a time (client/src/crypto/sharing/batched.rs:18-53,
server/src/snapshot.rs); here the chunk loop is a host-side driver around
jitted device steps (at most two compiled shapes per axis: full chunk and
remainder), with the uint32 Solinas fast path when the prime qualifies.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import fastfield, modular, numtheory, sharing
from ..protocol import (
    FullMasking,
    LinearMaskingScheme,
    NoMasking,
    PackedShamirSharing,
)
from .simpod import _check_mask_modulus, _to_residues32

#: get_block(p0, p1, d0, d1) -> integer array [p1-p0, d1-d0]
BlockProvider = Callable[[int, int, int, int], np.ndarray]


def array_block_provider(inputs) -> BlockProvider:
    """Adapt an in-memory (or np.memmap) [P, d] array to a BlockProvider."""

    def get_block(p0, p1, d0, d1):
        return inputs[p0:p1, d0:d1]

    return get_block


def synthetic_block_provider(
    modulus: int, seed: int = 0, max_value: Optional[int] = None
) -> BlockProvider:
    """Deterministic pseudo-random blocks without materializing [P, d] —
    benchmark-scale inputs. Each element is a splitmix64-style hash of its
    absolute (participant, component) coordinates, so every tiling reads
    the same virtual matrix."""
    bound = np.uint64(max_value if max_value is not None else modulus)
    s = np.uint64(seed * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF)

    def _mix(z):
        z = (z + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))

    def get_block(p0, p1, d0, d1):
        with np.errstate(over="ignore"):
            rows = _mix(np.arange(p0, p1, dtype=np.uint64)[:, None] + s)
            cols = _mix(np.arange(d0, d1, dtype=np.uint64)[None, :] ^ s)
            vals = _mix(rows ^ cols)
        return (vals % bound).astype(np.int64)

    return get_block


class StreamingAggregator:
    """Chunked single-chip rounds: fixed device memory for any P and d."""

    def __init__(
        self,
        sharing_scheme: PackedShamirSharing,
        masking_scheme: Optional[LinearMaskingScheme] = None,
        participants_chunk: int = 64,
        dim_chunk: int = 3 * (1 << 20),
    ):
        if not isinstance(sharing_scheme, PackedShamirSharing):
            raise ValueError("StreamingAggregator runs Packed-Shamir rounds")
        self.scheme = s = sharing_scheme
        self.masking = masking_scheme or NoMasking()
        if not isinstance(self.masking, (NoMasking, FullMasking)):
            raise ValueError("streaming masking: None or Full (seed PRGs are host-side)")
        _check_mask_modulus(self.masking, s)
        if dim_chunk % s.secret_count:
            raise ValueError(
                f"dim_chunk {dim_chunk} must be divisible by secret_count "
                f"{s.secret_count}"
            )
        self.participants_chunk = int(participants_chunk)
        self.dim_chunk = int(dim_chunk)
        self._M_host = numtheory.packed_share_matrix(
            s.secret_count, s.share_count, s.privacy_threshold,
            s.prime_modulus, s.omega_secrets, s.omega_shares,
        )
        self._L_host = numtheory.packed_reconstruct_matrix(
            s.secret_count, s.share_count, s.privacy_threshold,
            s.prime_modulus, s.omega_secrets, s.omega_shares,
            tuple(range(s.share_count)),
        )
        self._sp = fastfield.SolinasPrime.try_from(s.prime_modulus)
        self._steps = {}      # block shape -> jitted accumulate step
        self._finals = {}     # dim size -> jitted reconstruct+unmask

    # -- jitted pieces ---------------------------------------------------
    def _step_fn(self, block_shape):
        s, sp, mask = self.scheme, self._sp, isinstance(self.masking, FullMasking)
        p = s.prime_modulus
        M_host = self._M_host

        if sp is not None:

            def step(block, key, acc_shares, acc_mask):
                x = _to_residues32(block, sp)
                if mask:
                    mkey, skey = jax.random.split(key)
                    masks = fastfield.uniform32(mkey, block.shape, sp)
                    masked = fastfield.modadd32(x, masks, sp)
                    acc_mask = fastfield.modadd32(
                        acc_mask, fastfield.modsum32(masks, sp, axis=0), sp
                    )
                else:
                    skey = key
                    masked = x
                shares = sharing.packed_share32(
                    skey, masked, M_host, sp,
                    secret_count=s.secret_count,
                    privacy_threshold=s.privacy_threshold,
                )
                acc_shares = fastfield.modadd32(
                    acc_shares, fastfield.modsum32(shares, sp, axis=0), sp
                )
                return acc_shares, acc_mask

        else:
            M = jnp.asarray(M_host)

            def step(block, key, acc_shares, acc_mask):
                x = modular.canon(block.astype(jnp.int64), p)
                if mask:
                    mkey, skey = jax.random.split(key)
                    masks = modular.uniform_mod(mkey, block.shape, p)
                    masked = modular.modadd(x, masks, p)
                    acc_mask = modular.modadd(
                        acc_mask, modular.modsum(masks, p, axis=0), p
                    )
                else:
                    skey = key
                    masked = x
                shares = sharing.packed_share(
                    skey, masked, M,
                    prime=p, secret_count=s.secret_count,
                    privacy_threshold=s.privacy_threshold,
                )
                acc_shares = modular.modadd(
                    acc_shares, modular.modsum(shares, p, axis=0), p
                )
                return acc_shares, acc_mask

        return jax.jit(step, donate_argnums=(2, 3))

    def _final_fn(self, d_size):
        s, sp = self.scheme, self._sp
        p = s.prime_modulus
        mask = isinstance(self.masking, FullMasking)
        L_host = self._L_host

        if sp is not None:

            def final(acc_shares, acc_mask):
                total = sharing.packed_reconstruct32(
                    acc_shares, L_host, sp, dimension=d_size
                )
                if mask:
                    total = fastfield.modsub32(total, acc_mask, sp)
                return total.astype(jnp.int64)

        else:
            L = jnp.asarray(L_host)

            def final(acc_shares, acc_mask):
                total = sharing.packed_reconstruct(
                    acc_shares, L, prime=p, dimension=d_size
                )
                if mask:
                    total = modular.modsub(total, acc_mask, p)
                return total

        return jax.jit(final, donate_argnums=(0, 1))

    # -- driver ----------------------------------------------------------
    def aggregate_blocks(
        self, get_block: BlockProvider, participants: int, dimension: int, key=None
    ) -> np.ndarray:
        """Stream all blocks; returns the [dimension] aggregate (host array)."""
        s = self.scheme
        p = s.prime_modulus
        if key is None:
            from ..crypto.core import fresh_prng_key

            key = fresh_prng_key()
        acc_dtype = jnp.uint32 if self._sp is not None else jnp.int64
        out = np.empty(dimension, dtype=np.int64)
        for di, d0 in enumerate(range(0, dimension, self.dim_chunk)):
            d1 = min(d0 + self.dim_chunk, dimension)
            d_size = d1 - d0
            B = -(-d_size // s.secret_count)
            acc_shares = jnp.zeros((s.share_count, B), acc_dtype)
            acc_mask = jnp.zeros((d_size,), acc_dtype)
            for pi, p0 in enumerate(range(0, participants, self.participants_chunk)):
                p1 = min(p0 + self.participants_chunk, participants)
                block = jnp.asarray(np.asarray(get_block(p0, p1, d0, d1)))
                bkey = jax.random.fold_in(jax.random.fold_in(key, pi), di)
                step = self._steps.get(block.shape)
                if step is None:
                    step = self._steps[block.shape] = self._step_fn(block.shape)
                acc_shares, acc_mask = step(block, bkey, acc_shares, acc_mask)
            final = self._finals.get(d_size)
            if final is None:
                final = self._finals[d_size] = self._final_fn(d_size)
            out[d0:d1] = np.asarray(final(acc_shares, acc_mask))
        return out

    def aggregate(self, inputs, key=None) -> np.ndarray:
        inputs = np.asarray(inputs)
        return self.aggregate_blocks(
            array_block_provider(inputs), inputs.shape[0], inputs.shape[1], key
        )
