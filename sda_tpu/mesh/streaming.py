"""Streamed secure-aggregation rounds for workloads larger than HBM.

SURVEY.md §7 hard part (f): the flagship configs (10k participants x
10M-dim vectors) cannot materialize [P, d] on one chip, let alone the
[P, n, B] share tensor. But the whole pipeline is a sum over participants
of per-participant shares, so it streams: tile the participant axis and
the dimension axis, push each [P_chunk, d_chunk] block through
mask -> share -> local combine on device, and fold it into running
[n, B_chunk] share and [d_chunk] mask accumulators. Peak memory is one
block plus accumulators, independent of P. Per dim-tile, reconstruction
and unmasking run once at the end.

Two drivers share that structure:

- ``StreamingAggregator`` — single chip.
- ``StreamedPod`` — the streamed x multi-chip composition (round-1 verdict:
  neither mode alone reached the 10k x 10M flagship). Blocks are sharded
  over the SimulatedPod ('p', 'd') mesh and every tile step is
  COLLECTIVE-FREE: each device folds its local share/mask sums into
  device-local accumulators, and the psum_scatter clerk transpose +
  all_gather + reconstruct run ONCE per dim tile at the end — ICI traffic
  is independent of the participant count.

The reference reaches the same scale by chunking vectors into
secret_count-sized batches and streaming participations through the server
one HTTP upload at a time (client/src/crypto/sharing/batched.rs:18-53,
server/src/snapshot.rs); here the chunk loop is a host-side driver around
jitted device steps (at most two compiled shapes per axis: full chunk and
remainder), with the uint32 Solinas fast path when the prime qualifies.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fields.ops import FieldOps
from ..obs import devprof
from ..protocol import (
    ChaChaMasking,
    FullMasking,
    LinearMaskingScheme,
    NoMasking,
)
from ..utils import timed_phase
from .simpod import (
    _check_collective_headroom,
    _check_mask_modulus,
    _check_masking_supported,
    _dim_grain,
    _build_matrices,
    _mask_stage,
    _normalize_survivors,
    _pallas_stage,
    _reconstruct_stage,
    _resolve_pallas,
    _scheme_modulus,
    _shard_map,
    _share_sum_stage,
    _tile_key,
)

#: get_block(p0, p1, d0, d1) -> integer array [p1-p0, d1-d0]
BlockProvider = Callable[[int, int, int, int], np.ndarray]


def array_block_provider(inputs) -> BlockProvider:
    """Adapt an in-memory (or np.memmap) [P, d] array to a BlockProvider."""

    def get_block(p0, p1, d0, d1):
        return inputs[p0:p1, d0:d1]

    return get_block


def _hash32(rows, cols, seed, xp):
    """Deterministic uint32 hash of absolute (participant, component)
    coordinates — one formula, two backends (numpy and jnp), bit-identical.
    Pure 32-bit ops only so the device path never needs emulated 64-bit
    multiplies on TPU."""
    u = (lambda v: xp.uint32(v))
    x = rows * u(0x9E3779B1) ^ cols * u(0x85EBCA77) ^ u(seed)
    x = x ^ (x >> u(16))
    x = x * u(0x7FEB352D)
    x = x ^ (x >> u(15))
    x = x * u(0x846CA68B)
    x = x ^ (x >> u(16))
    return x


def synthetic_block_provider32(
    modulus: int, seed: int = 0, max_value: Optional[int] = None
) -> BlockProvider:
    """Host (numpy) uint32 coordinate-hash blocks: ~10x faster than the
    splitmix64 provider, and bit-identical to the device generator below —
    the e2e streamed benches verify sampled device results against host
    column sums of the same virtual matrix."""
    bound_i = int(max_value if max_value is not None else modulus)
    if not 0 < bound_i <= 0xFFFFFFFF:
        raise ValueError("synthetic32 values must fit uint32")
    bound = np.uint32(bound_i)
    sd = np.uint32((seed ^ 0x5851F42D) & 0xFFFFFFFF)

    def get_block(p0, p1, d0, d1):
        with np.errstate(over="ignore"):
            rows = np.arange(p0, p1, dtype=np.uint32)[:, None]
            cols = np.arange(d0, d1, dtype=np.uint32)[None, :]
            return _hash32(rows, cols, sd, np) % bound

    return get_block


def synthetic_device_block_provider32(
    modulus: int, seed: int = 0, max_value: Optional[int] = None
) -> BlockProvider:
    """Device (jnp) twin of :func:`synthetic_block_provider32`: generates
    each block on the accelerator from its absolute coordinates, so
    flagship-scale end-to-end runs are not bottlenecked by host hashing or
    dev-tunnel H2D bandwidth. Same virtual matrix, bit-identical values —
    exactness checks compare device aggregates against host-generated
    column sums. Benchmarks that use it label the record
    ``device_generated_inputs: true``; the host-fed path is measured
    separately."""
    bound = int(max_value if max_value is not None else modulus)
    if not 0 < bound <= 0xFFFFFFFF:
        raise ValueError("synthetic32 values must fit uint32")
    sd = (seed ^ 0x5851F42D) & 0xFFFFFFFF

    import functools

    # only the SHAPE is static: tile offsets are traced operands, so the
    # generator compiles once per block shape (2-3 shapes per run), not
    # once per tile — a flagship run has hundreds of distinct offsets and
    # per-tile retraces would feed serial compile time into the timed span
    @functools.partial(jax.jit, static_argnames=("rows", "cols"))
    def gen(p0, d0, *, rows, cols):
        r = p0 + jnp.arange(rows, dtype=jnp.uint32)[:, None]
        c = d0 + jnp.arange(cols, dtype=jnp.uint32)[None, :]
        return _hash32(r, c, jnp.uint32(sd), jnp) % jnp.uint32(bound)

    def get_block(p0, p1, d0, d1):
        return gen(jnp.uint32(p0), jnp.uint32(d0),
                   rows=int(p1 - p0), cols=int(d1 - d0))

    return get_block


def synthetic_block_provider(
    modulus: int, seed: int = 0, max_value: Optional[int] = None
) -> BlockProvider:
    """Deterministic pseudo-random blocks without materializing [P, d] —
    benchmark-scale inputs. Each element is a splitmix64-style hash of its
    absolute (participant, component) coordinates, so every tiling reads
    the same virtual matrix."""
    bound = np.uint64(max_value if max_value is not None else modulus)
    s = np.uint64(seed * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF)

    def _mix(z):
        z = (z + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))

    # uint32 blocks when values fit: half the host->device bytes, and the
    # device residue pass skips emulated 64-bit ops (_to_residues32)
    out_dtype = np.uint32 if int(bound) <= (1 << 32) else np.int64

    def get_block(p0, p1, d0, d1):
        with np.errstate(over="ignore"):
            rows = _mix(np.arange(p0, p1, dtype=np.uint64)[:, None] + s)
            cols = _mix(np.arange(d0, d1, dtype=np.uint64)[None, :] ^ s)
            vals = _mix(rows ^ cols)
        return (vals % bound).astype(out_dtype)

    return get_block


def _atomic_npz(path, **arrays):
    """Atomic, crash-durable npz write: temp file, fsync, rename, dir
    fsync — the durability primitive under every streamed snapshot."""
    import os
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            # data must reach stable storage BEFORE the rename lands, or a
            # power loss leaves a truncated snapshot at the destination
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # ... and the rename itself must reach the journal: fsync the
        # containing directory, else a crash can roll back to the prior
        # snapshot (harmless to correctness, but the durability claim
        # would be false)
        try:
            dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform without directory fsync
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _snapshot_header(fingerprint, out, done_dims, di, pi):
    """The cursor/prefix fields every streamed snapshot carries — ONE
    definition shared by the single-process and multihost checkpointers
    so the formats cannot drift."""
    return {
        "fingerprint": np.frombuffer(fingerprint.encode(), dtype=np.uint8),
        "out": out[:done_dims],
        "done_dims": np.int64(done_dims),
        "di": np.int64(di),
        "pi": np.int64(pi),
    }


def _read_snapshot(path, fingerprint, keys=None):
    """Fingerprint-guarded snapshot read; ``keys=None`` loads every entry,
    a key list loads only those (npz members load lazily, so a cursor-only
    probe does not materialize accumulator payloads). Returns None for a
    missing/foreign/corrupt snapshot — never trusts one."""
    import os
    import zipfile

    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            if bytes(z["fingerprint"]).decode() != fingerprint:
                return None  # different round/config: start fresh
            return {k: z[k] for k in (keys if keys is not None else z.files)}
    except (OSError, ValueError, KeyError, EOFError, zipfile.BadZipFile):
        return None  # unreadable/truncated snapshot: start fresh


def _checkpoint_load(path, fingerprint):
    return _read_snapshot(path, fingerprint,
                          keys=("out", "done_dims", "di", "pi",
                                "acc_shares", "acc_mask"))


class _FileCheckpointer:
    """Single-process snapshot/resume (the original streamed contract):
    one atomic npz at ``path``, fingerprint-guarded, removed on
    completion. ``restore_accs`` re-places loaded host accumulators
    (identity/`jnp.asarray` single-chip; mesh re-placement for pods)."""

    def __init__(self, path, fingerprint, restore_accs=None):
        self.path = path
        self.fingerprint = fingerprint
        self.restore_accs = restore_accs or (
            lambda aS, aM: (jnp.asarray(aS), jnp.asarray(aM)))

    def load(self):
        return _checkpoint_load(self.path, self.fingerprint)

    def restore(self, resume):
        return self.restore_accs(resume["acc_shares"], resume["acc_mask"])

    def save(self, out, done_dims, di, pi, acc_shares, acc_mask):
        _atomic_npz(
            self.path,
            **_snapshot_header(self.fingerprint, out, done_dims, di, pi),
            acc_shares=np.asarray(acc_shares),
            acc_mask=np.asarray(acc_mask),
        )

    def finish(self):
        import os

        try:
            os.unlink(self.path)
        except OSError:
            pass


def _drive_stream(owner, participants, dimension, key, *, make_block,
                  make_accs, fetch, checkpoint_path=None,
                  checkpoint_every_chunks=16, restore_accs=None,
                  checkpointer=None):
    """THE streamed tile loop — one definition of the tile/key derivation
    and of the checkpoint/resume state machine, shared by
    StreamingAggregator, StreamedPod, and (via StreamedPod.drive_tiles)
    the multihost driver. d-tiles outer, participant tiles inner, one
    accumulate step per tile, one finale per d-tile; snapshots every
    ``checkpoint_every_chunks`` chunks (0 = boundaries only) and at every
    d-tile boundary, removed on completion. Mask windows and share
    randomness depend on the tile indexing here — any change breaks
    resume bit-identity.
    """
    if key is None:
        from ..crypto.core import fresh_prng_key

        key = fresh_prng_key()
    pc, dc = owner.participants_chunk, owner.dim_chunk
    out = np.empty(dimension, dtype=np.int64)
    resume = None
    if checkpoint_path is not None and checkpointer is None:
        if jax.process_count() > 1:
            raise ValueError(
                "checkpoint_path is the single-process snapshot; for "
                "multihost rounds pass checkpoint_path to "
                "multihost.streamed_aggregate_process_local, which builds "
                "the per-process coordinated checkpointer"
            )
        checkpointer = _FileCheckpointer(
            checkpoint_path,
            owner._checkpoint_fingerprint(participants, dimension, key),
            restore_accs,
        )
    if checkpointer is not None:
        resume = checkpointer.load()
        if resume is not None:
            out[: int(resume["done_dims"])] = resume["out"]
    # ground truth for callers recording resumed runs (e.g. benches)
    owner.last_resumed = resume is not None
    resume_di = int(resume["di"]) if resume is not None else -1
    resume_pi = int(resume["pi"]) if resume is not None else 0
    empty = np.zeros((0,), owner._field.dtype)
    # uniform_tail: one step/finale shape for every tile — tails on BOTH
    # axes pad to the full chunk (dc is already grain-rounded); otherwise
    # the dim tail pads only to the grain and the participant tail keeps
    # its ragged (separately compiled) shape. Single-tile axes stay at
    # their natural size — there is no second shape to avoid
    uniform = bool(getattr(owner, "uniform_tail", False))
    uniform_d = uniform and dimension > dc
    uniform_p = uniform and participants > pc
    for di, d0 in enumerate(range(0, dimension, dc)):
        d1 = min(d0 + dc, dimension)
        d_size = dc if uniform_d else (
            -(-(d1 - d0) // owner._grain) * owner._grain)  # pad to grain
        if resume is not None and di < resume_di:
            continue  # completed tile: out prefix already restored
        if resume is not None and di == resume_di and resume_pi > 0:
            acc_shares, acc_mask = checkpointer.restore(resume)
            start_pi = resume_pi
        else:
            acc_shares, acc_mask = make_accs(d_size)
            start_pi = 0
        for pi, p0 in enumerate(range(0, participants, pc)):
            if pi < start_pi:
                continue  # chunk already folded into the snapshot accs
            p1 = min(p0 + pc, participants)
            with timed_phase("stream.feed"):
                block = make_block(p0, p1, d0, d1, d_size)
                if uniform_p and block.shape[0] < pc:
                    # ragged participant tail: zero rows aggregate as
                    # zero and their masks cancel within the tile, same
                    # argument as the zero columns
                    block = jnp.pad(
                        jnp.asarray(block),
                        ((0, pc - block.shape[0]), (0, 0)))
            step = owner._steps.get(block.shape)
            if step is None:
                step = owner._steps[block.shape] = owner._step_fn(block.shape)
            with timed_phase("stream.dispatch"):
                acc_shares, acc_mask = step(
                    block, _tile_key(key, pi, di), key,
                    jnp.int32(p0), jnp.int32(d0 // 8),
                    acc_shares, acc_mask,
                )
            if (checkpointer is not None
                    and checkpoint_every_chunks > 0
                    and (pi + 1) % checkpoint_every_chunks == 0):
                with timed_phase("stream.checkpoint"):
                    checkpointer.save(out, d0, di, pi + 1,
                                      acc_shares, acc_mask)
        # sync before the finale so stream.finale times the reconstruct
        # (for pods: psum_scatter + all_gather + reconstruct) alone, not
        # the queued accumulate backlog
        with timed_phase("stream.steps_sync"):
            jax.block_until_ready(acc_shares)
        final = owner._finals.get(d_size)
        if final is None:
            final = owner._finals[d_size] = owner._final_fn(d_size)
        with timed_phase("stream.finale"):
            out[d0:d1] = fetch(final(acc_shares, acc_mask))[: d1 - d0]
        if checkpointer is not None:
            with timed_phase("stream.checkpoint"):
                checkpointer.save(out, d1, di + 1, 0, empty, empty)
    if checkpointer is not None:
        checkpointer.finish()  # round complete
    return out


def _round_fingerprint(scheme, masking, participants, dimension, pc, dc,
                       pallas, survivors, key, extra=None):
    """sha256 over everything that determines a streamed round's bytes."""
    import hashlib

    from ..protocol.helpers import canonical_json

    payload = {
        "scheme": scheme.to_obj(),
        "masking": masking.to_obj(),
        "participants": int(participants),
        "dimension": int(dimension),
        "participants_chunk": int(pc),
        "dim_chunk": int(dc),
        "pallas": bool(pallas),
        "survivors": survivors,
        "key": np.asarray(
            jax.random.key_data(key) if jnp.issubdtype(
                getattr(key, "dtype", None), jax.dtypes.prng_key)
            else key).tolist(),
        **(extra or {}),
    }
    return hashlib.sha256(canonical_json(payload)).hexdigest()


class StreamingAggregator:
    """Chunked single-chip rounds: fixed device memory for any P and d.

    Full scheme-lattice coverage like the pod modes: Packed-Shamir OR
    additive sharing x none/full/chacha masking — ChaCha seed masks are
    expanded on device per tile at the tile's (participant, dim) offset,
    so every tiling of the same round key sees the same masks.
    """

    def __init__(
        self,
        sharing_scheme,
        masking_scheme: Optional[LinearMaskingScheme] = None,
        participants_chunk: int = 64,
        dim_chunk: int = 3 * (1 << 20),
        use_pallas: Optional[bool] = None,
        pallas_interpret: bool = False,
        pallas_external_bits_fn=None,
        surviving_clerks=None,
        uniform_tail: bool = False,
    ):
        self.scheme = s = sharing_scheme
        self.modulus = _scheme_modulus(s)  # also validates the scheme type
        self.masking = masking_scheme or NoMasking()
        _check_masking_supported(self.masking)
        _check_mask_modulus(self.masking, s)
        # ChaCha seed masks expand a window of one per-participant stream at
        # each tile's dim offset, so tiles align to the 8-word block grain
        self._grain = _dim_grain(s, self.masking)
        self.participants_chunk = int(participants_chunk)
        self.dim_chunk = -(-int(dim_chunk) // self._grain) * self._grain
        # uniform_tail pads the LAST dim tile to the full dim_chunk width
        # (zero columns aggregate as zero; per-tile masks cancel), so every
        # tile shares ONE compiled step/finale shape — in scarce tunnel
        # windows the tail shapes' extra compiles cost more than the
        # padded columns' compute when dim_chunk ~ dim/ntiles. Exactness
        # pinned in tests/test_streaming.py (uniform-tail block).
        self.uniform_tail = bool(uniform_tail)
        self.surviving_clerks = _normalize_survivors(s, surviving_clerks)
        self._M_host, self._L_host = _build_matrices(
            s, self.surviving_clerks
        )  # None for additive
        self._field = FieldOps.create(self.modulus)
        self._sp = self._field.sp
        self.pallas_active = _resolve_pallas(
            s, self.masking, self._field, use_pallas, "streamed"
        )
        self._pallas_interpret = bool(pallas_interpret)
        self._pallas_bits_fn = pallas_external_bits_fn
        self._steps = {}      # block shape -> jitted accumulate step
        self._finals = {}     # dim size -> jitted reconstruct+unmask

    # -- jitted pieces ---------------------------------------------------
    def _step_fn(self, block_shape):
        s, f = self.scheme, self._field
        M_host = self._M_host

        def step(block, key, round_key, pid0, dblk0, acc_shares, acc_mask):
            x = f.to_residues(block)
            if self.pallas_active:
                # fused mask+share+combine in one HBM pass (pallas_round.py)
                shares, mask_sum = _pallas_stage(
                    s, f, M_host, self.masking, x, key,
                    round_key=round_key, pid_base=pid0, d_block0=dblk0,
                    interpret=self._pallas_interpret,
                    external_bits_fn=self._pallas_bits_fn,
                )
            else:
                # pid0/dblk0 (traced) locate this tile in the global stream
                # so ChaCha seed masks expand the right window of each
                # participant's stream regardless of tiling
                masked, mask_sum, skey = _mask_stage(
                    self.masking, f, x, key, round_key,
                    pid_base=pid0, d_block0=dblk0,
                )
                # share + participant-combine fused via linearity
                # (simpod._share_sum_stage): no [S, n, B] tensor in HBM
                shares = _share_sum_stage(s, f, M_host, masked, skey)
            acc_shares = f.add(acc_shares, shares)
            if mask_sum is not None:
                acc_mask = f.add(acc_mask, mask_sum)
            return acc_shares, acc_mask

        # one "stream.step" profile for every block shape: the compiled-
        # shape registry is how the "at most 2-3 shapes per axis" claim
        # stays a tested property instead of a docstring
        return devprof.instrument("stream.step",
                                  jax.jit(step, donate_argnums=(5, 6)))

    def _final_fn(self, d_size):
        s, f = self.scheme, self._field
        mask = not isinstance(self.masking, NoMasking)

        def final(acc_shares, acc_mask):
            if self.surviving_clerks is not None:
                # clerk dropout: reveal from the quorum's rows only
                acc_shares = acc_shares[jnp.asarray(self.surviving_clerks), :]
            total = _reconstruct_stage(s, f, self._L_host, acc_shares, d_size)
            if mask:
                total = f.sub(total, acc_mask)
            return f.to_int64(total)

        return devprof.instrument("stream.finale",
                                  jax.jit(final, donate_argnums=(0, 1)))

    # -- checkpoint/resume -----------------------------------------------
    # The reference is durable-by-construction (every protocol object is a
    # store row the moment it exists, SURVEY §5.4); a flagship streamed
    # round is minutes of accumulate steps, so the TPU-native mode gets
    # the same property: the driver can persist (completed output prefix,
    # in-flight accumulators, tile cursor) and resume mid-round. Tile keys
    # are a pure function of (round key, tile indices), so a resumed run
    # draws identical masks/shares and the result is bit-identical to an
    # uninterrupted one.

    def _checkpoint_fingerprint(self, participants, dimension, key):
        return _round_fingerprint(
            self.scheme, self.masking, participants, dimension,
            self.participants_chunk, self.dim_chunk, self.pallas_active,
            self.surviving_clerks, key,
            # tail padding changes accumulator shapes mid-round, so a
            # snapshot must never cross the setting (included only when
            # set: existing False-mode snapshots keep their fingerprint)
            extra={"uniform_tail": True} if self.uniform_tail else None,
        )

    # back-compat alias for the module-level snapshot loader
    _checkpoint_load = staticmethod(_checkpoint_load)

    # -- driver ----------------------------------------------------------
    def aggregate_blocks(
        self, get_block: BlockProvider, participants: int, dimension: int,
        key=None, *, checkpoint_path: Optional[str] = None,
        checkpoint_every_chunks: int = 16,
    ) -> np.ndarray:
        """Stream all blocks; returns the [dimension] aggregate (host array).

        ``checkpoint_path``: persist an atomic, fsync'd resume snapshot
        there every ``checkpoint_every_chunks`` participant chunks (0 =
        only at dim-tile boundaries) and at every dim-tile boundary; an
        existing snapshot for the identical round (scheme, shape,
        chunking, key — sha256 fingerprint) resumes where it left off,
        bit-identically. A snapshot from a different round, or a damaged
        one, is ignored, never trusted.
        """
        s = self.scheme
        acc_dtype = self._field.dtype

        def make_block(p0, p1, d0, d1, d_size):
            raw = get_block(p0, p1, d0, d1)
            real = d1 - d0
            if isinstance(raw, jax.Array):
                # device-generated block: pad on device, no host hop
                return (raw if d_size == real else
                        jnp.pad(raw, ((0, 0), (0, d_size - real))))
            host = np.asarray(raw)
            if d_size != real:  # zero columns sum to zero
                padded = np.zeros((host.shape[0], d_size), dtype=host.dtype)
                padded[:, :real] = host
                host = padded
            return jnp.asarray(host)

        def make_accs(d_size):
            B = d_size // s.input_size
            return (jnp.zeros((s.output_size, B), acc_dtype),
                    jnp.zeros((d_size,), acc_dtype))

        return _drive_stream(
            self, participants, dimension, key,
            make_block=make_block, make_accs=make_accs, fetch=np.asarray,
            checkpoint_path=checkpoint_path,
            checkpoint_every_chunks=checkpoint_every_chunks,
        )

    def aggregate(self, inputs, key=None) -> np.ndarray:
        inputs = np.asarray(inputs)
        return self.aggregate_blocks(
            array_block_provider(inputs), inputs.shape[0], inputs.shape[1], key
        )


class StreamedPod:
    """Streamed rounds over a SimulatedPod mesh — the flagship-scale mode.

    Host loop tiles (participants x dim); each tile step is a collective-
    free SPMD program folding device-local [n, B_loc] share and [d_loc]
    mask accumulators; one psum_scatter + all_gather + reconstruct runs per
    dim tile at the end. Covers the full scheme lattice (additive/packed x
    none/full/chacha) via the simpod stage helpers. Peak device memory is
    one block shard plus accumulators — independent of total participants.
    """

    def __init__(
        self,
        sharing_scheme,
        masking_scheme: Optional[LinearMaskingScheme] = None,
        mesh: Optional[Mesh] = None,
        participants_chunk: int = 64,
        dim_chunk: int = 3 * (1 << 20),
        use_pallas: Optional[bool] = None,
        pallas_interpret: bool = False,
        pallas_external_bits_fn=None,
        surviving_clerks=None,
        uniform_tail: bool = False,
    ):
        from .simpod import SimulatedPod, default_mesh_shape, make_mesh

        self.scheme = s = sharing_scheme
        self.modulus = _scheme_modulus(s)
        self.masking = masking_scheme or NoMasking()
        _check_masking_supported(self.masking)
        _check_mask_modulus(self.masking, s)
        if mesh is None:
            p_shards, d_shards = default_mesh_shape(
                len(jax.devices()), s.output_size
            )
            mesh = make_mesh(p_shards, d_shards)
        self.mesh = mesh
        p_shards, d_shards = mesh.devices.shape
        if s.output_size % p_shards:
            raise ValueError(
                f"committee size {s.output_size} must be divisible by the "
                f"p axis ({p_shards})"
            )
        grain = _dim_grain(s, self.masking) * d_shards
        self._grain = grain
        # round the tile sizes up to the mesh grain
        self.participants_chunk = -(-int(participants_chunk) // p_shards) * p_shards
        self.dim_chunk = -(-int(dim_chunk) // grain) * grain
        # uniform_tail pads the LAST dim tile to the full dim_chunk width
        # (zero columns aggregate as zero; per-tile masks cancel), so every
        # tile shares ONE compiled step/finale shape — and a DIFFERENT tile
        # count (a different model dim at the same tile width) reuses the
        # exact same compiled per-tile program. The model-scale driver
        # (mesh/devscale.py) runs with this on; exactness pinned in
        # tests/test_devscale.py. The participant axis is always uniform
        # here (make_block pads every block to participants_chunk rows).
        self.uniform_tail = bool(uniform_tail)
        self.surviving_clerks = _normalize_survivors(s, surviving_clerks)
        self._M_host, self._L_host = _build_matrices(s, self.surviving_clerks)
        self._field = FieldOps.create(self.modulus, cross_terms=p_shards)
        _check_collective_headroom(self._field, p_shards)
        self.pallas_active = _resolve_pallas(
            s, self.masking, self._field, use_pallas, "streamed"
        )
        self._pallas_interpret = bool(pallas_interpret)
        self._pallas_bits_fn = pallas_external_bits_fn
        self._steps = {}      # local block shape -> jitted accumulate step
        self._finals = {}     # dim-tile size -> jitted collective finale

    # -- jitted pieces ---------------------------------------------------
    def _acc_shapes(self, d_size: int):
        p_shards, _ = self.mesh.devices.shape
        n = self.scheme.output_size
        B = d_size // self.scheme.input_size
        return (p_shards * n, B), (p_shards, d_size)

    def _new_accs(self, d_size: int):
        sharding = NamedSharding(self.mesh, P("p", "d"))
        (sS, sM) = self._acc_shapes(d_size)
        dt = self._field.dtype
        return (
            jax.device_put(jnp.zeros(sS, dt), sharding),
            jax.device_put(jnp.zeros(sM, dt), sharding),
        )

    def _step_fn(self, block_shape):
        f, s, masking = self._field, self.scheme, self.masking

        def local_step(block, tile_key, round_key, tile_base, d_block_base,
                       acc_shares, acc_mask):
            # block [Pc_loc, d_loc]; acc_shares [n, B_loc]; acc_mask [1, d_loc]
            pi = jax.lax.axis_index("p")
            di = jax.lax.axis_index("d")
            Pc_loc, d_loc = block.shape
            dev_key = jax.random.fold_in(jax.random.fold_in(tile_key, pi), di)
            x = f.to_residues(block)
            if self.pallas_active:
                # fused mask+share+combine in one HBM pass (pallas_round.py)
                shares, local_mask_sum = _pallas_stage(
                    s, f, self._M_host, masking, x, dev_key,
                    round_key=round_key,
                    pid_base=tile_base + pi * Pc_loc,
                    d_block0=d_block_base + di * (d_loc // 8),
                    interpret=self._pallas_interpret,
                    external_bits_fn=self._pallas_bits_fn,
                )
            else:
                masked, local_mask_sum, skey = _mask_stage(
                    masking, f, x, dev_key, round_key,
                    pid_base=tile_base + pi * Pc_loc,
                    d_block0=d_block_base + di * (d_loc // 8),
                )
                shares = _share_sum_stage(s, f, self._M_host, masked, skey)
            acc_shares = f.add(acc_shares, shares)
            if local_mask_sum is not None:
                acc_mask = f.add(acc_mask, local_mask_sum[None, :])
            return acc_shares, acc_mask

        fn = _shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(P("p", "d"), P(), P(), P(), P(), P("p", "d"), P("p", "d")),
            out_specs=(P("p", "d"), P("p", "d")),
        )
        return devprof.instrument("stream.pod.step",
                                  jax.jit(fn, donate_argnums=(5, 6)))

    def _final_fn(self, d_size: int):
        f, s = self._field, self.scheme
        masked = not isinstance(self.masking, NoMasking)

        def local_final(acc_shares, acc_mask):
            d_loc = acc_mask.shape[-1]
            with jax.named_scope("sda.clerk_combine"):
                clerk_rows = jax.lax.psum_scatter(
                    acc_shares, "p", scatter_dimension=0, tiled=True
                )
                clerk_rows = f.canon(clerk_rows)
                gathered = jax.lax.all_gather(
                    clerk_rows, "p", axis=0, tiled=True)
            if self.surviving_clerks is not None:
                # clerk dropout: rows hosted on a lost device/process never
                # enter the reconstruct — the quorum reveals exactly
                gathered = gathered[jnp.asarray(self.surviving_clerks), :]
            masked_total = _reconstruct_stage(
                s, f, self._L_host, gathered, d_loc
            )
            if not masked:
                return f.to_int64(masked_total)
            mask_total = f.canon(jax.lax.psum(acc_mask[0], "p"))
            return f.to_int64(f.sub(masked_total, mask_total))

        fn = _shard_map(
            local_final,
            mesh=self.mesh,
            in_specs=(P("p", "d"), P("p", "d")),
            out_specs=P("d"),
        )
        return devprof.instrument("stream.pod.finale",
                                  jax.jit(fn, donate_argnums=(0, 1)))

    # -- driver ----------------------------------------------------------
    def aggregate_blocks(
        self, get_block: BlockProvider, participants: int, dimension: int,
        key=None, *, checkpoint_path: Optional[str] = None,
        checkpoint_every_chunks: int = 16,
    ) -> np.ndarray:
        """Stream all blocks; returns the [dimension] aggregate (host array).

        ``checkpoint_path``: same atomic snapshot / bit-identical resume
        contract as StreamingAggregator (single-process; the fingerprint
        additionally pins the mesh shape). Loaded accumulators are
        re-placed onto the mesh with the pod's ('p', 'd') sharding.
        """
        sharding = NamedSharding(self.mesh, P("p", "d"))

        def make_block(p0, p1, d0, d1, d_size):
            pc = self.participants_chunk
            raw = get_block(p0, p1, d0, d1)
            if isinstance(raw, jax.Array):
                # device-generated block: pad on device, reshard, no host hop
                if raw.shape != (pc, d_size):
                    raw = jnp.pad(raw, ((0, pc - raw.shape[0]),
                                        (0, d_size - raw.shape[1])))
                return jax.device_put(raw, sharding)
            host = np.asarray(raw)
            if host.shape != (pc, d_size):  # zero-pad the edge tiles
                padded = np.zeros((pc, d_size), dtype=host.dtype)
                padded[: host.shape[0], : host.shape[1]] = host
                host = padded
            return jax.device_put(jnp.asarray(host), sharding)

        def restore_accs(acc_shares_np, acc_mask_np):
            return (
                jax.device_put(jnp.asarray(acc_shares_np), sharding),
                jax.device_put(jnp.asarray(acc_mask_np), sharding),
            )

        return self.drive_tiles(
            participants, dimension, key,
            make_block=make_block, make_accs=self._new_accs,
            fetch=np.asarray,
            checkpoint_path=checkpoint_path,
            checkpoint_every_chunks=checkpoint_every_chunks,
            restore_accs=restore_accs,
        )

    def _checkpoint_fingerprint(self, participants, dimension, key):
        # tail padding changes accumulator shapes mid-round, so a snapshot
        # must never cross the uniform_tail setting (included only when
        # set: existing False-mode snapshots keep their fingerprint)
        extra = {"mesh": list(self.mesh.devices.shape)}
        if self.uniform_tail:
            extra["uniform_tail"] = True
        return _round_fingerprint(
            self.scheme, self.masking, participants, dimension,
            self.participants_chunk, self.dim_chunk, self.pallas_active,
            self.surviving_clerks, key,
            extra=extra,
        )

    def drive_tiles(
        self, participants: int, dimension: int, key,
        *, make_block, make_accs, fetch,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_chunks: int = 16, restore_accs=None,
        checkpointer=None,
    ) -> np.ndarray:
        """The tile loop shared by single-host streaming and the multihost
        driver (mesh/multihost.py): d-tiles outer, participant tiles inner,
        one accumulate step per tile, one collective finale per d-tile.

        ``make_block(p0, p1, d0, d1, d_size)`` supplies each global
        [participants_chunk, d_size] device block; ``make_accs(d_size)``
        the zeroed (shares, mask) accumulators; ``fetch(arr)`` brings a
        d-sharded finale result to host numpy. The tile/key derivation here
        is THE definition — mask windows and share randomness depend on it.

        ``checkpoint_path`` (single-process only): same atomic snapshot /
        bit-identical resume contract as StreamingAggregator;
        ``restore_accs(acc_shares_np, acc_mask_np)`` re-places loaded host
        accumulators onto the mesh (defaults to plain ``jnp.asarray``).
        """
        return _drive_stream(
            self, participants, dimension, key,
            make_block=make_block, make_accs=make_accs, fetch=fetch,
            checkpoint_path=checkpoint_path,
            checkpoint_every_chunks=checkpoint_every_chunks,
            restore_accs=restore_accs, checkpointer=checkpointer,
        )

    def aggregate(self, inputs, key=None) -> np.ndarray:
        inputs = np.asarray(inputs)
        return self.aggregate_blocks(
            array_block_provider(inputs), inputs.shape[0], inputs.shape[1], key
        )
