"""sda-tpu: a TPU-native secure-aggregation framework.

A from-scratch re-design of the capabilities of Snips SDA (the reference
multi-party-computation system for privately summing vectors from many
participants; see `/root/reference`, surveyed in SURVEY.md): masking,
additive / packed-Shamir secret sharing, an untrusted broker/scheduler
server, and client roles (participant / clerk / recipient) — with all field
arithmetic expressed as JAX/XLA kernels (modular matmuls on the MXU, threefry
PRNG, vmap'd participant batching) and a simulated-pod mode that maps the
clerk committee onto a `jax.sharding.Mesh` with ICI collectives in place of
HTTP round-trips.

Layout (mirrors SURVEY.md §7's build plan):

- ``sda_tpu.protocol`` — resources, scheme parameters, service seam (L0)
- ``sda_tpu.fields``   — Z_p/Z_m math core: modular kernels, NTT/Lagrange (L1a)
- ``sda_tpu.crypto``   — sharing/masking/encryption/signing modules (L1b)
- ``sda_tpu.client``   — participant/clerk/recipient workflows (L2)
- ``sda_tpu.server``   — server core, ACL, snapshot scheduler, stores (L3/L4)
- ``sda_tpu.http``     — REST transport, both directions (L5)
- ``sda_tpu.store``    — client-side key/identity storage (L6)
- ``sda_tpu.cli``      — `sda` and `sdad` command-line tools (L7)
- ``sda_tpu.mesh``     — simulated-pod device-mesh execution (TPU-native)
- ``sda_tpu.native``   — C++ host-side kernels (CPU oracle, ChaCha20)

Protocol values are i64 (reference: client/src/crypto/mod.rs:33-36), so the
framework enables JAX x64 mode at import. Hot TPU kernels internally use
int32/limb paths where profitable; the public dtype is int64.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
