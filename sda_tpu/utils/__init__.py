"""Cross-cutting utilities: phase timing, counters, profiler hooks, logging."""

from .timing import (
    PhaseStat,
    phase_report,
    profile_trace,
    reset_phase_report,
    timed_phase,
)
from .metrics import count, counter_report, reset_counters
from .logsetup import configure_logging

__all__ = [
    "PhaseStat",
    "configure_logging",
    "count",
    "counter_report",
    "phase_report",
    "profile_trace",
    "reset_counters",
    "reset_phase_report",
    "timed_phase",
]
