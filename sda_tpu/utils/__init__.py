"""Cross-cutting utilities: phase timing, profiler hooks, logging setup."""

from .timing import (
    PhaseStat,
    phase_report,
    profile_trace,
    reset_phase_report,
    timed_phase,
)
from .logsetup import configure_logging

__all__ = [
    "PhaseStat",
    "configure_logging",
    "phase_report",
    "profile_trace",
    "reset_phase_report",
    "timed_phase",
]
