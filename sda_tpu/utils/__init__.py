"""Cross-cutting utilities: phase timing, counters, profiler hooks, logging."""

from .timing import (
    PhaseStat,
    phase_report,
    profile_trace,
    reset_phase_report,
    timed_phase,
)
from .metrics import (
    count,
    counter_report,
    gauge_max,
    gauge_report,
    gauge_set,
    histogram_report,
    observe,
    prometheus_text,
    reset_counters,
    reset_gauges,
    reset_histograms,
)
from .logsetup import configure_logging
from .env import env_float

__all__ = [
    "PhaseStat",
    "configure_logging",
    "count",
    "counter_report",
    "env_float",
    "gauge_max",
    "gauge_report",
    "gauge_set",
    "histogram_report",
    "observe",
    "phase_report",
    "profile_trace",
    "prometheus_text",
    "reset_counters",
    "reset_gauges",
    "reset_histograms",
    "reset_phase_report",
    "timed_phase",
]
