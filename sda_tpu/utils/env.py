"""Environment-variable knob parsing — the one parser for every
``SDA_*`` tunable (HTTP client retry knobs, long-poll bounds, ...), so
the knobs can't drift in how they treat blanks or garbage."""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

__all__ = ["env_float"]


def env_float(name: str, default: float) -> float:
    """Float env knob with a default; blank or unparseable values fall
    back (with a warning) instead of crashing the process at import."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("ignoring unparseable %s=%r", name, raw)
        return default
