"""Structured logging setup shared by the CLIs and daemons.

Reference: slog 1.x with -v verbosity flags (cli/src/main.rs:83-88,
server-cli/src/lib.rs:29-36); here stdlib logging with one canonical
format: timestamp, level, logger, message.
"""

from __future__ import annotations

import logging

_LEVELS = [logging.WARNING, logging.INFO, logging.DEBUG]
FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"


def configure_logging(verbosity: int = 0) -> None:
    """verbosity 0 -> WARNING, 1 -> INFO, >=2 -> DEBUG (the -v/-vv flags)."""
    logging.basicConfig(
        level=_LEVELS[min(int(verbosity), len(_LEVELS) - 1)], format=FORMAT
    )
