"""Structured logging setup shared by the CLIs and daemons.

Reference: slog 1.x with -v verbosity flags (cli/src/main.rs:83-88,
server-cli/src/lib.rs:29-36); here stdlib logging with one canonical
format: timestamp, level, logger, message.

``SDA_LOG_FORMAT=json`` switches to one JSON object per record, stamped
with the active ``trace_id``/``span_id`` from the tracing layer
(``sda_tpu.obs``) so logs and traces join on one key.
"""

from __future__ import annotations

import json
import logging
import os

_LEVELS = [logging.WARNING, logging.INFO, logging.DEBUG]
FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, message, plus
    trace_id/span_id when a span is active on the logging thread."""

    def format(self, record: logging.LogRecord) -> str:
        from .. import obs

        obj = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        ctx = obs.current_context()
        if ctx is not None:
            obj["trace_id"] = ctx.trace_id
            obj["span_id"] = ctx.span_id
        if record.exc_info:
            obj["exc_info"] = self.formatException(record.exc_info)
        return json.dumps(obj)


def log_format() -> str:
    """``"json"`` when SDA_LOG_FORMAT=json, else ``"text"``."""
    raw = os.environ.get("SDA_LOG_FORMAT", "").strip().lower()
    return "json" if raw == "json" else "text"


def configure_logging(verbosity: int = 0) -> None:
    """verbosity 0 -> WARNING, 1 -> INFO, >=2 -> DEBUG (the -v/-vv flags).
    Honors ``SDA_LOG_FORMAT=json`` (trace-correlated structured logs)."""
    level = _LEVELS[min(int(verbosity), len(_LEVELS) - 1)]
    if log_format() == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonFormatter())
        logging.basicConfig(level=level, handlers=[handler])
    else:
        logging.basicConfig(level=level, format=FORMAT)
