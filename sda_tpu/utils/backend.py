"""Programmatic JAX backend selection for benchmarks and drivers.

This image's sitecustomize registers the axon TPU PJRT plugin in every
interpreter and sets ``jax_platforms`` itself, so ``JAX_PLATFORMS`` env-var
selection is ignored; worse, the axon backend can hang indefinitely at
init when the chip tunnel is down (round-1 postmortem: both driver
artifacts died this way). Rules that keep harnesses alive:

- never initialize the TPU backend in-process without first probing it in
  a KILLABLE subprocess with a bounded timeout;
- select the backend with ``jax.config.update("jax_platforms", ...)``
  BEFORE any jax operation, not with env vars;
- to change platform after a backend initialized, clear backends first.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_PROBE_CODE = """
import jax
jax.config.update("jax_platforms", "axon")
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
(x @ x).block_until_ready()
print("PROBE_OK", ds[0].platform, getattr(ds[0], "device_kind", "?"), flush=True)
"""


def log(msg: str) -> None:
    print(f"[backend] {msg}", file=sys.stderr, flush=True)


def probe_tpu(timeout_s: float, attempts: int = 2) -> bool:
    """Bounded-time TPU liveness check in a subprocess (init can hang)."""
    for attempt in range(1, attempts + 1):
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            log(f"TPU probe attempt {attempt}: timed out after {timeout_s:.0f}s")
            continue
        dt = time.perf_counter() - t0
        if r.returncode == 0 and "PROBE_OK" in r.stdout:
            log(f"TPU probe attempt {attempt}: OK in {dt:.1f}s "
                f"({r.stdout.strip()})")
            return True
        tail = (r.stderr or r.stdout).strip().splitlines()[-3:]
        log(f"TPU probe attempt {attempt}: rc={r.returncode} in {dt:.1f}s; "
            + " | ".join(tail))
    return False


def select_platform(env_var: str = "SDA_BENCH_PLATFORM") -> str:
    """'axon' if the TPU answers a probe (or is forced), else 'cpu'."""
    want = os.environ.get(env_var, "auto")
    if want in ("tpu", "axon"):
        return "axon"
    if want == "cpu":
        return "cpu"
    timeout_s = float(os.environ.get("SDA_BENCH_TPU_PROBE_TIMEOUT", 300))
    return "axon" if probe_tpu(timeout_s) else "cpu"


def use_platform(platform: str) -> None:
    """Point jax at ``platform``, clearing stale backends if needed."""
    import jax
    from jax._src import xla_bridge as xb

    if xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
    jax.config.update("jax_platforms", platform)


def compile_cache_dir() -> str:
    """The one place the persistent-cache location is derived (repo-root
    /.jax_compile_cache); enable_compile_cache, the watch heartbeats and
    hw_check's cache-stats observable must all agree on it."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        ".jax_compile_cache")


def enable_compile_cache(platform: str = "axon",
                         path: str | None = None) -> str | None:
    """Persistent XLA compilation cache for the bench entry points.

    Hardware windows through the axon tunnel can be minutes long (the
    2026-07-31 03:45Z window died ~4 min in, with most of it spent
    compiling the flagship step); a persistent cache lets the NEXT window
    skip straight to the timed sections. Opt-in from bench/hw_check/suite
    /probe entry points only — library/test runs must not grow an
    on-disk cache dependency. Returns the cache dir, or None when caching
    is skipped/unsupported. Call BEFORE the first jit.

    CPU runs are excluded: XLA:CPU's AOT loader warns about machine-
    feature mismatches with a SIGILL caveat when reloading cached
    executables (observed in this image), and CPU compiles are seconds,
    not scarce-window minutes — not worth any crash risk in a fallback
    rung.
    """
    import jax

    if platform == "cpu":
        return None
    if path is None:
        path = compile_cache_dir()
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache every entry: through the tunnel even "fast" compiles cost
        # a scarce-window round-trip
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except (AttributeError, ValueError, OSError) as e:
        log(f"compile cache unavailable: {type(e).__name__}: {e}")
        return None
    try:  # newer knob; cache autotuning etc. too when present
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
    except (AttributeError, ValueError):
        pass
    # devprof taps jax.monitoring for the cache's hit/miss events
    # (xla.compile.cache.*) plus per-compile seconds — armed together with
    # the cache so every bench entry point reports whether a window
    # actually skipped its compiles
    try:
        from ..obs import devprof

        devprof.install_monitoring()
    except Exception as e:
        log(f"devprof monitoring unavailable: {type(e).__name__}: {e}")
    return path


def force_cpu(n_devices: int = 1) -> None:
    """CPU backend with >= n_devices virtual devices, for mesh tests."""
    import re

    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    use_platform("cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except (AttributeError, RuntimeError):
        pass
    got = jax.local_device_count()
    if got < n_devices:
        raise RuntimeError(
            f"CPU backend came up with {got} devices, need {n_devices} "
            f"(XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})"
        )
