"""Honest device timing through high-latency dispatch paths.

On this image the TPU is reached through the axon tunnel: dispatches
pipeline asynchronously, ``block_until_ready`` returns before the device
has actually finished, and any ``device_get`` pays a fixed ~70ms
round-trip regardless of payload. Naive ``start; fn(); block; stop``
timing therefore reports near-zero (round 2 postmortem: bench.py printed
3.8e12 el/s, 200x above the hardware roofline).

The honest measurement is the MARGINAL cost of one repetition: dispatch a
chain of r reps whose outputs the next rep does not need (the device
serializes them anyway), force completion with one tiny ``device_get``,
and difference two chain lengths so the fixed round-trip and dispatch
overheads cancel:

    per_rep = (T(r2) - T(r1)) / (r2 - r1)

``chain_seconds``/``marginal_seconds`` implement exactly that; they are
correct on plain local backends too (just slightly more work than a
block_until_ready loop).
"""

from __future__ import annotations

import time
from typing import Callable, Tuple


def chain_seconds(dispatch: Callable[[int], object], reps: int) -> float:
    """Wall time to dispatch ``reps`` calls and drain the device queue.

    ``dispatch(i)`` must issue rep ``i`` and return a jax array (any
    shape); completion is forced with a single elementwise D2H get.
    """
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    out = None
    for i in range(reps):
        out = dispatch(i)
    jax.device_get(jnp.ravel(out)[0])
    return time.perf_counter() - t0


def marginal_seconds(
    dispatch: Callable[[int], object],
    target_seconds: float = 10.0,
    max_reps: int = 64,
) -> Tuple[float, dict]:
    """Marginal per-rep seconds of ``dispatch``, with diagnostics.

    Probes one rep to size the chains, then returns
    ``(T(r2) - T(r1)) / (r2 - r1)`` with r2 ~ target_seconds of work.
    The dict records the raw chain timings for the bench JSON.
    """
    probe = chain_seconds(dispatch, 1)  # includes fixed RTT: overestimates
    r2 = int(min(max_reps, max(10, round(target_seconds / max(probe, 1e-4)))))
    r1 = max(1, r2 // 5)
    t1 = chain_seconds(dispatch, r1)
    t2 = chain_seconds(dispatch, r2)
    if t2 > t1 and r2 > r1:
        per = (t2 - t1) / (r2 - r1)
    else:  # noise swamped the difference; fall back to the long chain
        per = t2 / r2
    info = {
        "timing": "chained-dispatch marginal (cancels fixed RTT)",
        "probe_s": round(probe, 4),
        "chain": {"r1": r1, "t1_s": round(t1, 4), "r2": r2, "t2_s": round(t2, 4)},
        "fixed_overhead_s": round(max(t1 - r1 * per, 0.0), 4),
    }
    return per, info


def _knobs_record() -> dict:
    """The committed hardware-sweep record benchmarks/PALLAS_KNOBS.json
    (written by hw_check's on-chip sweep), or {} when absent/unreadable.
    Resolved relative to this package's repo checkout."""
    import json
    import os

    try:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "benchmarks", "PALLAS_KNOBS.json")
        with open(path) as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else {}
    except (OSError, ValueError):
        return {}


def pallas_knobs():
    """(p_block, tile) kernel-tuning knobs: SDA_PALLAS_PBLOCK /
    SDA_PALLAS_TILE env vars, else (16, None=auto).

    Env-only by design: library runtime behavior must not depend on the
    mutable committed sweep artifact (benchmarks/PALLAS_KNOBS.json). The
    bench entry points (bench.py rung children, benchmarks/suite.py) opt
    in to the file record via ``export_knobs_to_env`` before running;
    hw_check derives knobs from its own on-chip sweep and exports them
    to the same env vars.
    """
    import os

    pb_env = os.environ.get("SDA_PALLAS_PBLOCK")
    tile_env = os.environ.get("SDA_PALLAS_TILE")
    return (int(pb_env) if pb_env else 16,
            int(tile_env) if tile_env else None)


def tile_from_sweep() -> bool:
    """True when SDA_PALLAS_TILE came from a hardware-sweep record (set by
    export_knobs_to_env / the hw_check sweep) rather than an explicit user
    override. Sweep-sourced tiles were tuned at flagship widths, so small
    shapes may clamp them; explicit overrides are honored as-is."""
    import os

    return os.environ.get("SDA_PALLAS_TILE_SOURCE") == "sweep"


def export_knobs_to_env() -> dict:
    """Opt in to the committed hardware-sweep record: copy its knobs into
    the SDA_* env vars (where not already set by the user) so everything
    downstream — including library code that reads env-only pallas_knobs()
    — inherits the tuned values. Called by the bench entry points ONLY;
    plain library/test runs never see the file. Returns the record."""
    import os

    rec = _knobs_record()
    if isinstance(rec.get("p_block"), int):
        os.environ.setdefault("SDA_PALLAS_PBLOCK", str(rec["p_block"]))
    if isinstance(rec.get("tile"), int):
        if "SDA_PALLAS_TILE" not in os.environ:
            os.environ["SDA_PALLAS_TILE"] = str(rec["tile"])
            os.environ["SDA_PALLAS_TILE_SOURCE"] = "sweep"
    if isinstance(rec.get("stream_pc"), int):
        os.environ.setdefault("SDA_BENCH_STREAM_PC", str(rec["stream_pc"]))
    if isinstance(rec.get("dim_tile"), int):
        if "SDA_PALLAS_DIMTILE" not in os.environ:
            os.environ["SDA_PALLAS_DIMTILE"] = str(rec["dim_tile"])
            # marked so a record verdict (measured on the pallas A/B only)
            # can be told apart from an explicit user disable
            os.environ["SDA_PALLAS_DIMTILE_SOURCE"] = "sweep"
    if rec.get("tree_fold") is True:
        os.environ.setdefault("SDA_PALLAS_TREEFOLD", "1")
    return rec


def tree_fold_knob() -> bool:
    """Dense-sublane tree fold inside the fused kernel:
    SDA_PALLAS_TREEFOLD env ("1" enables), default off. Env-only in
    library code like the other kernel knobs; the hardware A/B record's
    tree_fold verdict arrives via export_knobs_to_env at bench entry
    points. No-op (slice fold) when the effective p_block is not a power
    of two — results are bit-identical either way."""
    import os

    return os.environ.get("SDA_PALLAS_TREEFOLD") == "1"


#: default monolithic dim-tile width: 24-grain aligned, 3 tiles at the
#: flagship d=999999 with 9 padded columns (the round-3 window measured
#: the full-width program superlinear in d; tiles stay on the fast side)
DEFAULT_DIM_TILE = 333336


def dim_tile_knob(default: int = DEFAULT_DIM_TILE):
    """Monolithic dim-tile width: SDA_PALLAS_DIMTILE env (0 disables
    tiling -> None), else ``default``. The hardware A/B record's dim_tile
    arrives via export_knobs_to_env at bench entry points."""
    import os

    env = os.environ.get("SDA_PALLAS_DIMTILE")
    val = int(env) if env else default
    return val if val > 0 else None


def stream_pc_knob(default: int = 64) -> int:
    """Streamed participant-chunk size: SDA_BENCH_STREAM_PC env (the
    hardware A/B record's stream_pc arrives via export_knobs_to_env at
    bench entry points), else ``default``."""
    import os

    env = os.environ.get("SDA_BENCH_STREAM_PC")
    return int(env) if env else default
