"""Parse jax.profiler Chrome traces for device-side kernel durations.

The marginal-timing methodology (utils/benchtime.py) is the single source
of every committed TPU number; this parser provides the independent
cross-check the round-2 verdict asked for (weak #4): capture a
``jax.profiler.trace`` around a few round dispatches, read the device
lane's per-module execution events, and compare the median on-device
duration against the marginal number. XProf device lanes appear as trace
processes named like ``/device:TPU:0`` with one complete ("X") event per
executed XLA module (name = the ``jit_...`` module name, ``dur`` in
microseconds). XLA:CPU has no such lane — callers treat an empty result
as "no device lane", not an error.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, Optional


def load_latest_trace(logdir: str) -> Optional[dict]:
    """The most recent ``*.trace.json.gz`` under a profiler logdir."""
    files = glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not files:
        return None
    with gzip.open(max(files, key=os.path.getmtime), "rt") as f:
        return json.load(f)


def device_lane_pids(trace: dict) -> Dict[int, str]:
    """pids of trace processes that are accelerator device lanes."""
    out = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = (e.get("args") or {}).get("name", "")
            if "/device:" in name and "CPU" not in name.upper():
                out[e["pid"]] = name
    return out


def device_module_stats(trace: dict, name_hint: str = "jit") -> Dict[str, dict]:
    """{module_name: {count, total_us, median_us}} for complete events on
    device lanes whose name contains ``name_hint``."""
    lanes = device_lane_pids(trace)
    if not lanes:
        return {}
    durs: Dict[str, list] = {}
    for e in trace.get("traceEvents", []):
        if (e.get("ph") == "X" and e.get("pid") in lanes
                and name_hint in e.get("name", "") and "dur" in e):
            durs.setdefault(e["name"], []).append(float(e["dur"]))
    out = {}
    for name, ds in durs.items():
        ds.sort()
        n = len(ds)
        median = ds[n // 2] if n % 2 else (ds[n // 2 - 1] + ds[n // 2]) / 2
        out[name] = {
            "count": n,
            "total_us": round(sum(ds), 1),
            "median_us": round(median, 1),
        }
    return out


def dominant_module(stats: Dict[str, dict]) -> Optional[str]:
    """The module name carrying the most total device time."""
    if not stats:
        return None
    return max(stats, key=lambda n: stats[n]["total_us"])
