"""Process-global event counters — the metrics floor the reference lacks.

The reference has structured logging but zero metrics counters anywhere
(SURVEY.md §5.5: "No metrics counters"). This registry closes that gap the
same way ``timing.py`` does for spans: named monotonic counters with a
process-global, thread-safe store, incremented at the protocol choke points
(server ops, HTTP requests) and read back by benchmarks, the sim CLI, and
tests. Cost per hit is one lock + dict update — noise next to any I/O.

Naming convention: dotted paths, ``server.participation.created``,
``http.request``, ``http.status.200``.
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_counts: Dict[str, int] = {}


def count(name: str, n: int = 1) -> None:
    """Add ``n`` to the named counter (creating it at zero)."""
    with _lock:
        _counts[name] = _counts.get(name, 0) + n


def counter_report(prefix: str = "") -> Dict[str, int]:
    """Snapshot of all counters (optionally filtered by name prefix)."""
    with _lock:
        return {k: v for k, v in sorted(_counts.items()) if k.startswith(prefix)}


def reset_counters() -> None:
    with _lock:
        _counts.clear()
